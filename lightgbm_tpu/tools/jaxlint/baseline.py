"""Baseline: accepted pre-existing findings that don't block CI.

The committed ``jaxlint_baseline.json`` records each accepted finding as
``(file, rule, normalized source line)`` with a count — line numbers are
deliberately NOT part of the key, so unrelated edits that shift lines
don't invalidate the baseline, while any *new* occurrence of a flagged
pattern (even in a baselined file) is reported.  Regenerate with
``python -m lightgbm_tpu.tools.jaxlint <paths> --write-baseline``; the
goal over time is to shrink it to empty (see docs/StaticAnalysis.md).
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

from .context import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = "jaxlint_baseline.json"

Key = Tuple[str, str, str]   # (file, rule, snippet)


def finding_key(f: Finding) -> Key:
    return (f.path, f.rule, f.snippet)


def load(path: str) -> Dict[Key, int]:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {doc.get('version')!r} in "
            f"{path} (expected {BASELINE_VERSION})")
    out: Dict[Key, int] = {}
    for e in doc.get("entries", []):
        key = (e["file"], e["rule"], e["snippet"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def dump(findings: Sequence[Finding],
         extra: Optional[Dict[Key, int]] = None) -> Dict:
    counts = Counter(finding_key(f) for f in findings)
    for k, n in (extra or {}).items():
        counts[k] += n
    entries = [{"file": k[0], "rule": k[1], "snippet": k[2], "count": n}
               for k, n in sorted(counts.items())]
    return {"version": BASELINE_VERSION, "tool": "jaxlint",
            "entries": entries}


def write(path: str, findings: Sequence[Finding],
          extra: Optional[Dict[Key, int]] = None) -> None:
    """Write ``findings`` (plus ``extra`` pre-counted entries — used by
    ``--select --write-baseline`` to preserve unselected rules) as the
    baseline."""
    with open(path, "w") as fh:
        json.dump(dump(findings, extra), fh, indent=1, sort_keys=False)
        fh.write("\n")


def apply(findings: Sequence[Finding], baseline: Dict[Key, int]) \
        -> Tuple[List[Finding], List[Tuple[Key, int]]]:
    """Split ``findings`` against the baseline.

    Returns ``(new_findings, stale_entries)``: per key the first
    ``baseline[key]`` occurrences (in line order) are accepted, the rest
    are new; stale entries are baseline keys whose budget exceeds what
    the tree still contains (candidates for regeneration)."""
    remaining = dict(baseline)
    new: List[Finding] = []
    for f in sorted(findings, key=Finding.sort_key):
        k = finding_key(f)
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    stale = [(k, n) for k, n in sorted(remaining.items()) if n > 0]
    return new, stale
