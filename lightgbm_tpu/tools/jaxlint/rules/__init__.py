"""Rule registry: JLxxx code -> rule module.

Each rule module exposes ``CODE``, ``SHORT`` and either ``check(ctx)``
(per-file, JL0xx) or ``check_project(project)`` with ``PROJECT_RULE =
True`` (cross-module dataflow, JL1xx).  Registration is explicit (no
import-time magic) so the set of shipped rules is grep-able here.
"""

from __future__ import annotations

from . import (abi_parity, concurrency, determinism, dtype_drift,
               dtype_flow, fault_coverage, global_state, host_sync,
               jit_registry, lock_order, recompile, set_order, trace_key)

_MODULES = (host_sync, recompile, jit_registry, dtype_drift, set_order,
            global_state, trace_key, dtype_flow, lock_order, determinism,
            concurrency, abi_parity, fault_coverage)

#: code -> rule module, in code order
RULES = {m.CODE: m for m in _MODULES}

#: code -> per-file rule module (checked one file at a time)
FILE_RULES = {c: m for c, m in RULES.items()
              if not getattr(m, "PROJECT_RULE", False)}

#: code -> project rule module (needs the whole-repo symbol table)
PROJECT_RULES = {c: m for c, m in RULES.items()
                 if getattr(m, "PROJECT_RULE", False)}

#: code -> one-line description (CLI --list-rules, docs)
RULE_DOCS = {m.CODE: m.SHORT for m in _MODULES}

#: code -> full rule documentation (CLI --explain)
RULE_EXPLAIN = {m.CODE: (m.__doc__ or m.SHORT).strip() for m in _MODULES}
