"""Rule registry: JLxxx code -> (checker, one-line description).

Each rule module exposes ``CODE``, ``SHORT`` and ``check(ctx)`` yielding
:class:`~..context.Finding` objects.  Registration is explicit (no
import-time magic) so the set of shipped rules is grep-able here.
"""

from __future__ import annotations

from . import (dtype_drift, global_state, host_sync, jit_registry,
               recompile, set_order)

_MODULES = (host_sync, recompile, jit_registry, dtype_drift, set_order,
            global_state)

#: code -> rule module, in code order
RULES = {m.CODE: m for m in _MODULES}

#: code -> one-line description (CLI --list-rules, docs)
RULE_DOCS = {m.CODE: m.SHORT for m in _MODULES}
