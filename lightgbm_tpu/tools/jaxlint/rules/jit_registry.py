"""JL003 — ``jax.jit`` callables invisible to the recompile tracker.

PR 1's ``obs/jit_track.py`` attributes every XLA compile to a named
shape signature; a jitted callable that never passes through
``obs.track_jit`` compiles silently, and the per-window recompile
telemetry (the whole point of the tracker in the retrain-every-window
harness) under-counts.  This rule finds jit bindings in a module and
checks each is registered:

- ``name = obs.track_jit("name", jax.jit(f))`` — tracked at creation.
- ``@jax.jit``-decorated ``f`` later rebound via
  ``f = obs.track_jit("f", f)`` — tracked by rebind.
- anything else — finding.

Suppress for callables that compile exactly once by construction (cold
helpers, test fixtures) with ``# jaxlint: disable=JL003``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..context import FileContext, dotted_name

CODE = "JL003"
SHORT = ("jax.jit callable not registered with obs.track_jit "
         "(compiles invisible to the recompile telemetry)")


def _is_track_jit_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted_name(node.func)
    return d is not None and d.split(".")[-1] == "track_jit"


def check(ctx: FileContext):
    # names (or dotted attribute targets) that flow through track_jit
    tracked: set = set()
    for node in ast.walk(ctx.tree):
        if _is_track_jit_call(node):
            for a in node.args[1:]:
                d = dotted_name(a)
                if d is not None:
                    tracked.add(d)

    # jit bindings: (reported name, node to attach the finding to)
    bindings: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if ctx.jit_decorator_statics(dec) is not None:
                    bindings.append((node.name, dec))
                    break
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and ctx.is_jit_call(node.value):
            target = dotted_name(node.targets[0])
            if target is not None:
                bindings.append((target, node.value))
        elif ctx.is_jit_call(node):
            # a bare jax.jit(...) expression: tracked when nested inside
            # a track_jit(...) call; assigned/decorator cases are handled
            # above; an immediately-invoked jit is JL002's finding
            parent = ctx.parent(node)
            if isinstance(parent, ast.Assign) or _in_track_jit(ctx, node) \
                    or (isinstance(parent, ast.Call)
                        and parent.func is node):
                continue
            bindings.append((dotted_name(node.func) or "jax.jit",
                             node))

    seen: Dict[int, bool] = {}
    for name, node in bindings:
        if id(node) in seen:
            continue
        seen[id(node)] = True
        if name in tracked or _in_track_jit(ctx, node):
            continue
        yield ctx.make_finding(
            CODE, node,
            f"jitted callable `{name}` is not wrapped with obs.track_jit; "
            "its recompiles are invisible to the shape-signature tracker "
            "(obs/jit_track.py)")


def _in_track_jit(ctx: FileContext, node: ast.AST) -> bool:
    return any(_is_track_jit_call(a) for a in ctx.ancestors(node))
