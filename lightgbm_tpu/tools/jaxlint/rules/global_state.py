"""JL006 — unguarded mutation of module-level state.

The boosting driver, sklearn wrapper, C-API embed path and the user's
own threads can all reach module-level registries concurrently —
``obs/registry.py`` had to grow a lock for exactly this reason.  This
rule finds module-level mutable containers (dict/list/set literals or
``dict()``/``defaultdict()``/… constructors) and ``global``-rebound
names, then flags any mutation from inside a function that is not
under a ``with <...lock...>:`` block:

- ``NAME.append/add/update/pop/…(…)``
- ``NAME[...] = …`` / ``NAME[...] += …``
- ``global NAME`` followed by an assignment to ``NAME``

The lock heuristic is textual: any ``with`` context expression whose
dotted name contains "lock" (``_LOCK``, ``self._lock``,
``registry.lock()``) guards its body.  Single-threaded-by-construction
mutations can carry ``# jaxlint: disable=JL006`` with a comment saying
why.
"""

from __future__ import annotations

import ast
from typing import Dict

from ..context import FileContext, dotted_name

CODE = "JL006"
SHORT = ("module-level mutable state mutated outside a lock "
         "(thread-unsafe under the multi-threaded C-API/callback paths)")

_MUTABLE_CONSTRUCTORS = ("dict", "list", "set", "bytearray", "deque",
                         "defaultdict", "OrderedDict", "Counter")
_MUTATORS = ("append", "add", "update", "pop", "popitem", "setdefault",
             "clear", "extend", "insert", "remove", "discard",
             "appendleft", "popleft", "sort")


def _module_mutables(ctx: FileContext) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for stmt in ctx.tree.body:
        targets = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if isinstance(value, (ast.Dict, ast.List, ast.Set,
                                  ast.DictComp, ast.ListComp,
                                  ast.SetComp)):
                out[t.id] = stmt.lineno
            elif isinstance(value, ast.Call):
                d = dotted_name(value.func)
                if d is not None \
                        and d.split(".")[-1] in _MUTABLE_CONSTRUCTORS:
                    out[t.id] = stmt.lineno
    return out


def _under_lock(ctx: FileContext, node: ast.AST) -> bool:
    for a in ctx.ancestors(node):
        if isinstance(a, ast.With):
            for item in a.items:
                expr = item.context_expr
                d = dotted_name(expr.func if isinstance(expr, ast.Call)
                                else expr)
                if d is not None and "lock" in d.lower():
                    return True
    return False


def check(ctx: FileContext):
    mutables = _module_mutables(ctx)

    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        global_names = set()
        for stmt in fn.body:
            if isinstance(stmt, ast.Global):
                global_names.update(stmt.names)

        for node in ast.walk(fn):
            # NAME.append(...) etc. on a module-level container
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in mutables \
                    and node.func.attr in _MUTATORS \
                    and not _under_lock(ctx, node):
                yield ctx.make_finding(
                    CODE, node,
                    f"mutation of module-level `{node.func.value.id}` "
                    f"(.{node.func.attr}) outside a lock; guard with a "
                    "module lock or move the state into an instance "
                    "(obs/registry.py pattern)")
            # NAME[...] = ... / NAME[...] += ...
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in mutables \
                            and not _under_lock(ctx, node):
                        yield ctx.make_finding(
                            CODE, node,
                            f"item assignment on module-level "
                            f"`{t.value.id}` outside a lock; guard with "
                            "a module lock or move the state into an "
                            "instance")
                    elif isinstance(t, ast.Name) and t.id in global_names \
                            and not _under_lock(ctx, node):
                        yield ctx.make_finding(
                            CODE, node,
                            f"`global {t.id}` rebound outside a lock is "
                            "a read-modify-write race under the "
                            "multi-threaded C-API path; guard it or use "
                            "a thread-safe holder")
