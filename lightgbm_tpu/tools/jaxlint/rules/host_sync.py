"""JL001 — host-device synchronization inside hot-path loops.

The retrain-every-window harness (PAPER.md) multiplies every
per-iteration host transfer by thousands of windows: a stray
``float(device_scalar)`` or ``np.asarray(device_array)`` inside the
boosting loop serializes the async dispatch pipeline once per tree.
This rule fires only in hot-path modules (``context.HOT_PATH_SUFFIXES``
or a ``# jaxlint: hot-path`` marker) and only inside loops — module-level
or once-per-call transfers are fine.

Detected shapes, in a loop body:

- ``x.item()`` — the canonical single-value sync.
- ``float(e)`` / ``int(e)`` / ``bool(e)`` where ``e`` contains a
  ``jnp.``/``jax.``-rooted expression, a name locally assigned from one,
  or an ``np.asarray(...)`` transfer.
- ``float(x[i])``-style scalar reads (subscript argument): per-iteration
  scalar extraction; hoist or batch the read.
- ``np.asarray(x)`` / ``jax.device_get(x)`` of a (probable) device value.

Fix patterns: batch handles with one ``jax.device_get(list)`` outside
the loop (gbdt.py's nl-queue stall check), hoist the scalar read, or
keep the value on device.
"""

from __future__ import annotations

import ast

from ..context import FileContext, chain_root, dotted_name

CODE = "JL001"
SHORT = ("host-device sync inside a hot-path loop "
         "(.item()/float()/np.asarray of device values)")

_CASTS = ("float", "int", "bool")


def _contains_transfer_source(ctx: FileContext, node: ast.AST,
                              device_names) -> bool:
    """Does ``node``'s subtree reference something device-resident: a
    jnp/jax-rooted expression, a locally device-assigned name, or an
    np.asarray transfer?"""
    roots = ctx.jnp_aliases | ctx.jax_aliases
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            if sub.id in roots or sub.id in device_names:
                # metadata reads (x.shape, x.ndim, x.dtype) are host-side
                # statics — no transfer happens
                parent = ctx.parent(sub)
                if isinstance(parent, ast.Attribute) and parent.attr in (
                        "shape", "ndim", "dtype", "size"):
                    continue
                return True
        elif isinstance(sub, ast.Call):
            d = dotted_name(sub.func)
            if d and any(d == f"{np}.asarray" for np in ctx.numpy_aliases):
                return True
    return False


def _classify(ctx: FileContext, call: ast.Call, device_names):
    func = call.func
    # x.item()
    if isinstance(func, ast.Attribute) and func.attr == "item" \
            and not call.args and not call.keywords:
        return (".item() forces a host-device sync every loop iteration; "
                "batch the values and fetch once outside the loop "
                "(jax.device_get on the whole list)")
    # float()/int()/bool() of a device-ish expression or a subscript read
    if isinstance(func, ast.Name) and func.id in _CASTS and len(call.args) == 1:
        arg = call.args[0]
        if _contains_transfer_source(ctx, arg, device_names):
            return (f"{func.id}() of a device value inside a loop blocks "
                    "on the transfer each iteration; hoist or batch the "
                    "host read")
        if isinstance(arg, ast.Subscript):
            # x.shape[0] / x.strides[1] are host-side metadata, not reads
            if isinstance(arg.value, ast.Attribute) and arg.value.attr in (
                    "shape", "strides", "ndim"):
                return None
            return (f"per-iteration scalar read {func.id}(...[...]) in a "
                    "hot loop; hoist the conversion out of the loop or "
                    "read the whole array once")
        return None
    # np.asarray(x) / jax.device_get(x) of a device value
    d = dotted_name(func)
    if d is None:
        return None
    is_asarray = any(d == f"{np}.asarray" for np in ctx.numpy_aliases)
    is_devget = any(d == f"{j}.device_get" for j in ctx.jax_aliases)
    if (is_asarray or is_devget) and call.args:
        arg = call.args[0]
        roots = ctx.jnp_aliases | ctx.jax_aliases
        argroot = chain_root(arg)
        if (argroot in device_names or argroot in roots
                or _contains_transfer_source(ctx, arg, device_names)):
            return (f"{d}() of a device array inside a loop is one "
                    "blocking transfer per iteration; start the copies "
                    "async and fetch them batched after the loop")
    return None


def check(ctx: FileContext):
    if not ctx.is_hot:
        return
    reported = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if ctx.loop_depth(node) < 1:
            continue
        # a call nested inside an already-reported call (e.g. the
        # np.asarray inside int(np.asarray(v))) is the same sync
        if any(ctx.is_ancestor(r, node) for r in reported):
            continue
        device_names = ctx.device_names(node)
        msg = _classify(ctx, node, device_names)
        if msg is not None:
            reported.append(node)
            yield ctx.make_finding(CODE, node, msg)
