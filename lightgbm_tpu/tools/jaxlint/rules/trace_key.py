"""JL101 — trace-key completeness around ``programs_signature``.

The grower program cache (and the persisted stage-plan/compile caches
keyed from it) is only correct when its key covers EVERYTHING that
shapes a trace and NOTHING that is merely traced:

* ``INT32_SCAN_ROWS`` was initially missing from ``programs_signature``
  — a test that monkeypatched the bound could be handed a cached
  program built under the other scan; and
* ``learning_rate`` was originally hashed INTO the key although it is a
  traced argument — lr-decay callbacks forced a spurious cache miss
  (full retrace) every window.

Three checks, driven by the project symbol table (a "signature module"
is any module defining ``programs_signature`` or ``shape_signature``):

1. **Missing trace-shaping constant**: a module-level ``UPPER_CASE``
   constant compared (or ``min``/``max``-ed) against shape-carrying
   values (``num_data``, ``n_pad``, ``rows``, ``bucket``, ...) selects
   program structure, so it must appear inside the signature function.
   Field-index constants (``F_GAIN`` as a subscript) and host-side
   bookkeeping bounds (``len(cache) > MAX``) are exempt because they
   never meet a shape in a comparison.
2. **Excluded param shapes a trace**: a config attribute listed in the
   digest's exclusion container (``_NON_TRACE_PARAMS``) must never be
   read inside a traced region anywhere in the project — that would
   bake an un-keyed value into compiled programs.
3. **Traced-only param in the key**: a config attribute that flows into
   a jitted program as a runtime argument (``self.lr = float(
   config.learning_rate)`` → ``programs._grow(..., lr, ...)``) must be
   in the exclusion container, or changing it forces a pointless
   recompile-key miss.

Over-keying a genuinely static constant is always safe (it only costs
cache hits), so the correct fix for check 1 is to add the constant to
the signature; the fix for check 3 is to extend the exclusion list.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..context import FileContext, dotted_name
from ..project import ProjectContext

CODE = "JL101"
SHORT = ("trace-key completeness: trace-shaping constants missing from "
         "programs_signature, or traced-only values hashed into it")

PROJECT_RULE = True

_SIGNATURE_FN_NAMES = ("programs_signature", "shape_signature")
_CONST_RE = re.compile(r"^_?[A-Z][A-Z0-9_]*$")
_SHAPE_HINT_RE = re.compile(
    r"num_data|n_pad|num_valid|rows|bucket|num_features|num_groups|"
    r"frontier|\bshape\b|num_leaves|length|\bnb\b")


def _expr_text(ctx: FileContext, node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _signature_functions(project: ProjectContext):
    for fi in project.functions.values():
        if fi.name in _SIGNATURE_FN_NAMES:
            yield fi


def _key_names(project: ProjectContext, fi) -> Set[str]:
    """Names that flow into the signature: everything mentioned in the
    signature function's body, plus the bodies of same-module helper
    functions it calls (e.g. ``_config_digest``)."""
    out = _names_in(fi.node)
    for callee in project.calls.get(fi.key, ()):
        if callee[0] == fi.module:
            out |= _names_in(project.functions[callee].node)
    return out


def _shape_compared_constants(ctx: FileContext, mod_consts: Set[str],
                              skip_nodes: List[ast.AST]) \
        -> Dict[str, List[ast.AST]]:
    """Constants used as a direct comparand/min/max operand against a
    shape-carrying expression; every usage node per constant."""
    out: Dict[str, List[ast.AST]] = {}

    def direct_operand_names(node: ast.AST) -> Set[str]:
        # names reachable through arithmetic only (no subscripts/calls)
        if isinstance(node, ast.Name):
            return {node.id}
        if isinstance(node, ast.BinOp):
            return direct_operand_names(node.left) \
                | direct_operand_names(node.right)
        if isinstance(node, ast.UnaryOp):
            return direct_operand_names(node.operand)
        return set()

    def consider(const_sides: List[ast.AST], other_sides: List[ast.AST],
                 site: ast.AST):
        other_text = " ".join(_expr_text(ctx, o) for o in other_sides)
        if not _SHAPE_HINT_RE.search(other_text):
            return
        for side in const_sides:
            for name in direct_operand_names(side):
                if name in mod_consts:
                    out.setdefault(name, []).append(site)

    for node in ast.walk(ctx.tree):
        if any(ctx.is_ancestor(s, node) or s is node for s in skip_nodes):
            continue
        if isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            for i, side in enumerate(sides):
                others = sides[:i] + sides[i + 1:]
                consider([side], others, node)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id in ("min", "max") and len(node.args) >= 2:
            for i, a in enumerate(node.args):
                others = node.args[:i] + node.args[i + 1:]
                consider([a], list(others), node)
    return out


def _exclusion_container(mod) -> Optional[Tuple[str, List[str]]]:
    """(name, members) of a module-level tuple/list/set of string
    literals used as a ``(not) in`` filter — the ``_NON_TRACE_PARAMS``
    idiom."""
    for name, value in mod.assigns.items():
        if not isinstance(value, (ast.Tuple, ast.List, ast.Set)):
            continue
        members = [e.value for e in value.elts
                   if isinstance(e, ast.Constant)
                   and isinstance(e.value, str)]
        if not members or len(members) != len(value.elts):
            continue
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.Compare) \
                    and any(isinstance(op, (ast.In, ast.NotIn))
                            for op in node.ops) \
                    and any(isinstance(c, ast.Name) and c.id == name
                            for c in node.comparators):
                return name, members
    return None


def _config_attr_reads(ctx: FileContext,
                       tree: ast.AST) -> List[ast.Attribute]:
    """``config.X`` / ``cfg.X`` / ``self.config.X`` attribute reads
    (method calls like ``config.clone()`` are not reads)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute):
            continue
        parent = ctx.parent(node)
        if isinstance(parent, ast.Call) and parent.func is node:
            continue
        base = dotted_name(node.value)
        if base is not None and base.split(".")[-1] in ("config", "cfg"):
            out.append(node)
    return out


def _is_float_conversion(ctx: FileContext, value: ast.AST) -> bool:
    """``float(config.X)`` / ``jnp.float32(config.X)`` /
    ``jnp.asarray(config.X, <float>)`` — the idiom for a numeric
    hyperparameter consumed at RUN time.  ``int(config.X)`` conversions
    are structural (shapes, counts) and genuinely belong in the key,
    so they are not runtime-traced origins."""
    if not isinstance(value, ast.Call):
        return False
    d = dotted_name(value.func)
    if d is None:
        return False
    tail = d.split(".")[-1]
    if tail in ("float", "float32", "bfloat16", "float16"):
        return True
    if tail == "asarray" and len(value.args) >= 2:
        d2 = dotted_name(value.args[1])
        return d2 is not None and "float" in d2.split(".")[-1]
    return False


def _runtime_traced_params(project: ProjectContext, mod) \
        -> Dict[str, ast.AST]:
    """Config attrs that flow (through a local / self-attr assignment)
    into an argument of a call to a jit-bound callable — i.e. values the
    program receives traced, at call time.  Returns attr -> read site."""
    jit_names = project.jit_bound_names.get(mod.name, set())
    if not jit_names:
        return {}
    out: Dict[str, ast.AST] = {}
    # origin maps: plain/self-attr name -> (config attr, read node);
    # two passes so `self.lr = float(config.learning_rate)` then
    # `lr = self.lr` both resolve regardless of walk order
    origins: Dict[str, Tuple[str, ast.AST]] = {}
    for _ in range(2):
        for node in ast.walk(mod.ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1):
                continue
            t = node.targets[0]
            tname = None
            if isinstance(t, ast.Name):
                tname = t.id
            elif isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                tname = t.attr
            if tname is None or tname in origins:
                continue
            if _is_float_conversion(mod.ctx, node.value):
                reads = _config_attr_reads(mod.ctx, node.value)
                if len(reads) == 1:
                    origins[tname] = (reads[0].attr, reads[0])
                    continue
            for leaf in ast.walk(node.value):
                name = None
                if isinstance(leaf, ast.Name):
                    name = leaf.id
                elif isinstance(leaf, ast.Attribute) \
                        and isinstance(leaf.value, ast.Name) \
                        and leaf.value.id == "self":
                    name = leaf.attr
                if name is not None and name in origins \
                        and name != tname:
                    origins[tname] = origins[name]
                    break
    if not origins:
        return {}
    for node in ast.walk(mod.ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func)
        if d is None or d.split(".")[-1] not in jit_names:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for leaf in ast.walk(arg):
                name = None
                if isinstance(leaf, ast.Name):
                    name = leaf.id
                elif isinstance(leaf, ast.Attribute) \
                        and isinstance(leaf.value, ast.Name) \
                        and leaf.value.id == "self":
                    name = leaf.attr
                if name in origins:
                    attr, site = origins[name]
                    out.setdefault(attr, site)
    return out


def check_project(project: ProjectContext):
    for fi in _signature_functions(project):
        mod = project.modules[fi.module]
        ctx = mod.ctx
        key_names = _key_names(project, fi)

        # (1) shape-compared constants must be in the key
        mod_consts = {n for n in mod.assigns if _CONST_RE.match(n)
                      and not isinstance(mod.assigns[n],
                                         (ast.Tuple, ast.List, ast.Set,
                                          ast.Dict))}
        skip = [f.node for f in _signature_functions(project)
                if f.module == fi.module]
        skip += [project.functions[c].node
                 for c in project.calls.get(fi.key, ())
                 if c[0] == fi.module]
        for name, sites in sorted(
                _shape_compared_constants(ctx, mod_consts, skip).items()):
            if name in key_names:
                continue
            for site in sorted(sites, key=lambda s: (s.lineno,
                                                     s.col_offset)):
                yield ctx.make_finding(
                    CODE, site,
                    f"trace-shaping constant `{name}` is compared "
                    f"against a shape here but never flows into "
                    f"`{fi.name}`; add it to the signature (over-keying "
                    "is always safe) or a cached program built under a "
                    "different value will be reused")

        # (2)/(3) need the digest's exclusion container
        excl = _exclusion_container(mod)
        if excl is None:
            continue
        excl_name, excl_members = excl

        # (2) excluded params must not shape traces anywhere
        for mname2, mod2 in project.modules.items():
            for read in _config_attr_reads(mod2.ctx, mod2.ctx.tree):
                if read.attr in excl_members \
                        and project.is_traced_node(mname2, read):
                    yield mod2.ctx.make_finding(
                        CODE, read,
                        f"config attribute `{read.attr}` is excluded "
                        f"from the program-cache key ({excl_name} in "
                        f"{fi.module}) but read inside a traced region: "
                        "the compiled program bakes in a value the key "
                        "does not cover — key it or pass it as a traced "
                        "argument")

        # (3) runtime-traced params must be excluded from the key
        for attr, site in sorted(
                _runtime_traced_params(project, mod).items()):
            if attr in excl_members:
                continue
            yield ctx.make_finding(
                CODE, site,
                f"config attribute `{attr}` flows into a jitted program "
                "as a runtime (traced) argument but still hashes into "
                f"the program-cache key; add it to {excl_name} or every "
                "change forces a spurious retrace/cache miss")
