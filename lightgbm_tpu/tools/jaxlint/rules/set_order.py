"""JL005 — iteration order of a ``set`` leaking into output.

Python sets iterate in hash order: stable within one process, but
different across runs (PYTHONHASHSEED for strings) and across
insertion histories.  Where the iteration order affects output —
callback execution order, serialized lists, score accumulation order —
the result is nondeterministic: exactly the callback-dedupe bug PR 1
fixed by hand in ``engine.py`` (a ``set()`` of callbacks ran in hash
order).  Flagged order-sensitive consumers:

- ``for x in <set>:`` and comprehension iteration over a set
- ``list(<set>)``, ``tuple(<set>)``, ``enumerate(<set>)``,
  ``iter(<set>)``, ``reversed(<set>)``, ``", ".join(<set>)``

Membership tests, ``len``/``sum``/``min``/``max``/``any``/``all`` and
``sorted(<set>)`` are order-insensitive and exempt.  A "set" is a set
literal/comprehension, a ``set(...)``/``frozenset(...)`` call, or a name
locally assigned from one.
"""

from __future__ import annotations

import ast

from ..context import FileContext

CODE = "JL005"
SHORT = ("iteration over a set where order affects output "
         "(nondeterministic across runs); sort or use an ordered "
         "container")

_ORDER_SENSITIVE_FUNCS = ("list", "tuple", "enumerate", "iter", "reversed")


def _is_set_expr(ctx: FileContext, node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("set", "frozenset"):
        return True
    if isinstance(node, ast.Name):
        return node.id in ctx.set_names(node)
    return False


def _finding(ctx: FileContext, node: ast.AST, how: str):
    return ctx.make_finding(
        CODE, node,
        f"{how} iterates a set in hash order — nondeterministic across "
        "runs when the order reaches the output; use sorted(...), a "
        "list-based dedupe, or an insertion-ordered dict")


def check(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.For):
            if _is_set_expr(ctx, node.iter):
                yield _finding(ctx, node.iter, "`for` loop")
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                if _is_set_expr(ctx, gen.iter):
                    yield _finding(ctx, gen.iter, "comprehension")
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) \
                    and node.func.id in _ORDER_SENSITIVE_FUNCS \
                    and node.args and _is_set_expr(ctx, node.args[0]):
                yield _finding(ctx, node.args[0],
                               f"{node.func.id}(...)")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" and node.args \
                    and _is_set_expr(ctx, node.args[0]):
                yield _finding(ctx, node.args[0], "str.join(...)")
