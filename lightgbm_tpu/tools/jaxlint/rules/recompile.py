"""JL002 — XLA recompile hazards around ``jax.jit``.

Every distinct jit signature is a full trace + XLA compile; in the
windowed harness a signature that churns per window turns "training"
into "compiling" (the PR-1 telemetry counts exactly this).  Three
statically visible hazard shapes:

1. **Weak-type churn at call sites**: a Python scalar or dict literal
   passed positionally/by keyword to a same-module jitted function at a
   position not declared in ``static_argnums``/``static_argnames``.
   Python scalars trace as weak-typed 0-d arrays whose signature differs
   from the arrays the same slot sees elsewhere, and dicts hash into the
   static side only when declared static.
2. **Python branches on traced values**: an ``if``/``while`` inside a
   jitted function whose test reads a non-static parameter's *value*
   (`x is None` checks and ``x.shape``/``x.ndim``/``x.dtype``/``len(x)``
   reads are static and exempt) — these raise TracerBoolConversionError
   at best, silently specialize at worst.
3. **Immediately-invoked jit**: ``jax.jit(fn)(args)`` builds a fresh jit
   object — and a fresh empty compile cache — per call, recompiling
   every time.  Hoist the jitted callable to module/instance scope.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..context import FileContext

CODE = "JL002"
SHORT = ("recompile hazard: non-static Python scalar/dict jit args, "
         "Python branch on a traced value, or jax.jit(f)(x) per call")


class _JitFn:
    __slots__ = ("name", "node", "static_pos", "static_names", "params")

    def __init__(self, name: str, node: Optional[ast.FunctionDef],
                 static_pos: Set[int], static_names: Set[str]):
        self.name = name
        self.node = node
        self.static_pos = static_pos
        self.static_names = static_names
        self.params: List[str] = []
        if node is not None:
            self.params = [a.arg for a in node.args.args]

    def is_static(self, pos: Optional[int], name: Optional[str]) -> bool:
        if pos is not None and pos in self.static_pos:
            return True
        if name is not None and name in self.static_names:
            return True
        if pos is not None and pos < len(self.params) \
                and self.params[pos] in self.static_names:
            return True
        if name is not None and name in self.params \
                and self.params.index(name) in self.static_pos:
            return True
        return False


def _collect_jitted(ctx: FileContext) -> Dict[str, _JitFn]:
    out: Dict[str, _JitFn] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                statics = ctx.jit_decorator_statics(dec)
                if statics is not None:
                    out[node.name] = _JitFn(node.name, node, *statics)
                    break
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and ctx.is_jit_call(node.value):
            nums, names = ctx._parse_statics(node.value.keywords)
            out[node.targets[0].id] = _JitFn(node.targets[0].id, None,
                                             nums, names)
    return out


def _is_hazard_literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) \
            and isinstance(node.value, (bool, int, float)):
        return "Python scalar"
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub) \
            and isinstance(node.operand, ast.Constant) \
            and isinstance(node.operand.value, (int, float)):
        return "Python scalar"
    if isinstance(node, ast.Dict):
        return "dict"
    return None


def _static_value_read(ctx: FileContext, name_node: ast.Name) -> bool:
    """x.shape / x.ndim / x.dtype / len(x) / `x is None` are trace-time
    statics, not value reads."""
    parent = ctx.parent(name_node)
    if isinstance(parent, ast.Attribute) and parent.attr in (
            "shape", "ndim", "dtype", "size", "weak_type"):
        return True
    if isinstance(parent, ast.Call) and isinstance(parent.func, ast.Name) \
            and parent.func.id in ("len", "isinstance", "type") \
            and name_node in parent.args:
        return True
    if isinstance(parent, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops):
        return True
    return False


def check(ctx: FileContext):
    jitted = _collect_jitted(ctx)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        # (3) immediately-invoked jit: jax.jit(fn)(...)
        if ctx.is_jit_call(node.func):
            yield ctx.make_finding(
                CODE, node,
                "jax.jit(...) invoked immediately builds a fresh compile "
                "cache per call (recompiles every time); bind the jitted "
                "callable once at module/instance scope")
            continue
        # (1) literal scalar/dict at a non-static slot of a known jit fn
        if not isinstance(node.func, ast.Name):
            continue
        fn = jitted.get(node.func.id)
        if fn is None:
            continue
        for i, arg in enumerate(node.args):
            kind = _is_hazard_literal(arg)
            if kind and not fn.is_static(i, None):
                yield ctx.make_finding(
                    CODE, arg,
                    f"{kind} passed as traced argument {i} of jitted "
                    f"`{fn.name}`; declare it in static_argnums/"
                    "static_argnames or pass a device array")
        for kw in node.keywords:
            kind = _is_hazard_literal(kw.value)
            if kind and kw.arg is not None \
                    and not fn.is_static(None, kw.arg):
                yield ctx.make_finding(
                    CODE, kw.value,
                    f"{kind} passed as traced kwarg `{kw.arg}` of jitted "
                    f"`{fn.name}`; declare it static or pass a device "
                    "array")

    # (2) Python branches on traced parameter values inside jitted bodies
    for fn in jitted.values():
        if fn.node is None:
            continue
        traced = set(fn.params)
        for i, p in enumerate(fn.params):
            if fn.is_static(i, p):
                traced.discard(p)
        for sub in ast.walk(fn.node):
            if not isinstance(sub, (ast.If, ast.While)):
                continue
            hit = None
            for nm in ast.walk(sub.test):
                if isinstance(nm, ast.Name) and nm.id in traced \
                        and not _static_value_read(ctx, nm):
                    hit = nm
                    break
            if hit is not None:
                yield ctx.make_finding(
                    CODE, sub,
                    f"Python `{'if' if isinstance(sub, ast.If) else 'while'}`"
                    f" on traced value `{hit.id}` inside jitted "
                    f"`{fn.name}`: shape-specializes or fails at trace "
                    "time; use jnp.where/lax.cond or mark the arg static")
