"""JL141 — thread/queue concurrency-graph hazards.

The threaded subsystems (``pipeline/core.py``, ``serve/engine.py``,
``serve/fleet.py``, ``obs/export.py``, ``data/stream_loader.py``) talk
through queues and hand trace context across thread boundaries by
convention.  This rule builds a project-wide thread/queue graph — every
``threading.Thread(target=...)`` spawn resolved to its entry function,
every ``queue.Queue(...)`` bound to the local / ``self.<attr>`` name it
is assigned to — and flags three hazards no per-file rule can see:

1. **Span without a SpanContext handoff** (the PR-16 invariant): a
   spawned thread whose transitive closure opens ``obs.span(...)`` but
   never activates a captured context — no ``tracing.set_current(...)``
   call, no ``span``/``span_event`` with an explicit ``trace_id=`` /
   ``parent_id=``, and no context-like entry parameter.  Such spans
   start fresh traces, severing the causal chain the exporters stitch.
2. **Unbounded blocking in a dispatch scope**: ``Queue.get`` with no
   ``timeout``/``block=False`` (hangs forever when the producer dies),
   ``Queue.put`` on a *bounded* queue with no timeout (deadlocks when
   the consumer dies; puts on unbounded queues never block and are
   exempt), and bare ``lock.acquire()`` calls outside a ``with`` and
   without a timeout — all checked in functions reachable from a
   thread entry point or a thread-spawning dispatch function.
3. **Join under a lock the target needs**: ``t.join()`` executed while
   holding a lock that the joined thread's transitive closure also
   acquires — the join can never return (composes with JL121's lock
   graph).

Sanctioned escapes: hand the context explicitly
(``tracing.set_current(captured)`` or ``trace_id=`` kwargs), give every
blocking call a timeout and handle the Empty/Full, and join threads
only after releasing their locks — or write a justified
``# jaxlint: disable=JL141``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..context import dotted_name
from ..project import FuncInfo, FuncKey, ProjectContext
from .lock_order import _direct_locks, _locks_reachable

CODE = "JL141"
SHORT = ("spawned thread opens spans without a SpanContext handoff, "
         "blocks without a timeout in a dispatch scope, or joins a "
         "thread while holding a lock its target acquires")

PROJECT_RULE = True

_SPAN_OWNERS = {"obs", "tracing"}
_EVIDENCE_KWARGS = {"trace_id", "parent_id"}
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue"}


def _spawn_sites(project: ProjectContext) \
        -> List[Tuple[FuncKey, str, ast.Call]]:
    """(entry key, spawning module, spawn call node) per resolved
    ``threading.Thread(target=...)``."""
    out: List[Tuple[FuncKey, str, ast.Call]] = []
    for mname in sorted(project.modules):
        ctx = project.modules[mname].ctx
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is None or d.split(".")[-1] != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                r = _target_key(project, mname, ctx, node, kw.value)
                if r is not None:
                    out.append((r, mname, node))
    return out


def _target_key(project: ProjectContext, mname: str, ctx, spawn: ast.Call,
                value: ast.AST) -> Optional[FuncKey]:
    r = project._callable_ref(mname, ctx, value)
    if r is not None:
        return r
    if isinstance(value, ast.Name):
        # a nested `def worker()` in the function doing the spawning
        fi = project.enclosing_function(mname, spawn)
        if fi is not None:
            k = (mname, f"{fi.qualname}.<locals>.{value.id}")
            if k in project.functions:
                return k
    return None


# -- (1) span-without-handoff -----------------------------------------

def _trace_facts(project: ProjectContext, fi: FuncInfo) \
        -> Tuple[bool, bool]:
    """(opens spans, shows handoff evidence) for one function body."""
    spans = evidence = False
    for node in project.own_nodes(fi):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func)
        if d is None:
            continue
        parts = d.split(".")
        last = parts[-1]
        if last == "set_current":
            evidence = True
        elif last in ("span", "span_event") and len(parts) >= 2 \
                and parts[-2] in _SPAN_OWNERS:
            if last == "span":
                spans = True
            if any(kw.arg in _EVIDENCE_KWARGS for kw in node.keywords):
                evidence = True
    return spans, evidence


def _has_ctx_param(fi: FuncInfo) -> bool:
    a = fi.node.args
    names = [p.arg for p in list(a.posonlyargs) + list(a.args)
             + list(a.kwonlyargs)]
    return any(n != "self" and (n == "context" or n.endswith("ctx"))
               for n in names)


# -- (2) queue / lock bookkeeping -------------------------------------

def _queue_bounded(call: ast.Call) -> bool:
    """True when the queue is definitely or possibly bounded (a
    ``put`` can block); ``Queue()`` / ``Queue(0)`` never block."""
    val: Optional[ast.AST] = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "maxsize":
            val = kw.value
    if val is None:
        return False
    if isinstance(val, ast.Constant) and isinstance(val.value, int):
        return val.value > 0
    return True


def _known_queues(project: ProjectContext):
    """Queues by assignment: per-function locals and per-class
    ``self.<attr>``s, each mapped to a bounded? flag."""
    locs: Dict[FuncKey, Dict[str, bool]] = {}
    attrs: Dict[Tuple[str, str], Dict[str, bool]] = {}
    for key in sorted(project.functions):
        fi = project.functions[key]
        for node in project.own_nodes(fi):
            tgt, val = _assign_parts(node)
            if not isinstance(val, ast.Call):
                continue
            d = dotted_name(val.func)
            if d is None or d.split(".")[-1] not in _QUEUE_CTORS:
                continue
            bounded = _queue_bounded(val)
            if isinstance(tgt, ast.Name):
                locs.setdefault(key, {})[tgt.id] = bounded
            elif isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self" and fi.class_name:
                attrs.setdefault((fi.module, fi.class_name),
                                 {})[tgt.attr] = bounded
    return locs, attrs


def _assign_parts(node: ast.AST):
    if isinstance(node, ast.Assign) and len(node.targets) == 1:
        return node.targets[0], node.value
    if isinstance(node, ast.AnnAssign) and node.value is not None:
        return node.target, node.value
    return None, None


def _lookup_scoped(project: ProjectContext, fi: FuncInfo, name: str,
                   locs: Dict[FuncKey, Dict[str, object]]):
    """Resolve ``name`` through the lexical chain of enclosing
    functions (a nested ``drain()`` reads its parent's queue)."""
    cur: Optional[FuncInfo] = fi
    while cur is not None:
        got = locs.get(cur.key, {}).get(name)
        if got is not None:
            return got
        up = cur.qualname.rsplit(".<locals>.", 1)
        cur = project.functions.get((cur.module, up[0])) \
            if len(up) == 2 else None
    return None


def _receiver_queue(project: ProjectContext, fi: FuncInfo,
                    expr: ast.AST, locs, attrs) -> Optional[bool]:
    if isinstance(expr, ast.Name):
        return _lookup_scoped(project, fi, expr.id, locs)
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and fi.class_name:
        return attrs.get((fi.module, fi.class_name), {}).get(expr.attr)
    return None


def _blocking_forever(call: ast.Call, n_leading: int) -> bool:
    """True when a get/put/acquire call has neither a timeout nor a
    non-blocking flag.  ``n_leading`` = payload args before the
    block/timeout pair (1 for ``put(item, ...)``, 0 otherwise)."""
    args = call.args
    if len(args) > n_leading + 1:
        return False                      # positional timeout
    if len(args) == n_leading + 1:
        blk = args[n_leading]
        if isinstance(blk, ast.Constant) and blk.value is False:
            return False                  # positional block=False
    for kw in call.keywords:
        if kw.arg == "timeout":
            return False
        if kw.arg in ("block", "blocking") \
                and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return False
    return True


# -- (3) thread variables ---------------------------------------------

def _known_threads(project: ProjectContext):
    """Thread objects by assignment, mapped to their entry FuncKey."""
    locs: Dict[FuncKey, Dict[str, FuncKey]] = {}
    attrs: Dict[Tuple[str, str], Dict[str, FuncKey]] = {}
    for key in sorted(project.functions):
        fi = project.functions[key]
        ctx = project.ctx_for[fi.module]
        for node in project.own_nodes(fi):
            tgt, val = _assign_parts(node)
            if not isinstance(val, ast.Call):
                continue
            d = dotted_name(val.func)
            if d is None or d.split(".")[-1] != "Thread":
                continue
            entry = None
            for kw in val.keywords:
                if kw.arg == "target":
                    entry = _target_key(project, fi.module, ctx, val,
                                        kw.value)
            if entry is None:
                continue
            if isinstance(tgt, ast.Name):
                locs.setdefault(key, {})[tgt.id] = entry
            elif isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self" and fi.class_name:
                attrs.setdefault((fi.module, fi.class_name),
                                 {})[tgt.attr] = entry
    return locs, attrs


def check_project(project: ProjectContext):
    spawns = _spawn_sites(project)
    if not spawns:
        return
    entries = sorted({e for e, _, _ in spawns})

    # (1) spans opened on a spawned thread with no context handoff
    facts: Dict[FuncKey, Tuple[bool, bool]] = {}
    for entry, mname, node in spawns:
        closure = sorted(project.reachable_from([entry]))
        spans = evidence = False
        for k in closure:
            if k not in facts:
                facts[k] = _trace_facts(project, project.functions[k])
            s, ev = facts[k]
            spans = spans or s
            evidence = evidence or ev
        if spans and not evidence \
                and not _has_ctx_param(project.functions[entry]):
            ctx = project.ctx_for[mname]
            yield ctx.make_finding(
                CODE, node,
                f"thread entry `{entry[1]}` opens obs.span(...) but "
                "never receives the spawner's SpanContext — its spans "
                "start a fresh trace, severing the causal chain: "
                "capture the context before spawning and activate it "
                "with tracing.set_current(...) on the thread (or pass "
                "trace_id=/parent_id= explicitly)")

    # (2) blocking-forever calls in dispatch scopes
    spawners = sorted({project.enclosing_function(m, n).key
                       for _, m, n in spawns
                       if project.enclosing_function(m, n) is not None})
    qlocs, qattrs = _known_queues(project)
    scope = sorted(project.reachable_from(entries + spawners))
    for k in scope:
        fi = project.functions[k]
        ctx = project.ctx_for[fi.module]
        for node in project.own_nodes(fi):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            attr = node.func.attr
            if attr in ("put", "get"):
                bounded = _receiver_queue(project, fi, node.func.value,
                                          qlocs, qattrs)
                if bounded is None:
                    continue
                if attr == "put" and not bounded:
                    continue      # puts on unbounded queues never block
                if _blocking_forever(node, 1 if attr == "put" else 0):
                    yield ctx.make_finding(
                        CODE, node,
                        f"`{attr}` on a queue with no timeout in a "
                        "thread dispatch scope: if the peer thread "
                        "dies this blocks forever — use "
                        f"`{attr}(..., timeout=...)`, handle "
                        "queue.Empty/Full, and check the peer is "
                        "still alive")
            elif attr == "acquire":
                d = dotted_name(node.func.value)
                if d is None or "lock" not in d.lower():
                    continue
                if _blocking_forever(node, 0):
                    yield ctx.make_finding(
                        CODE, node,
                        "bare `.acquire()` with no timeout in a "
                        "thread dispatch scope: use `with lock:` or "
                        "`acquire(timeout=...)` so a wedged peer "
                        "cannot hang the dispatcher forever")

    # (3) join while holding a lock the target's closure acquires
    tlocs, tattrs = _known_threads(project)
    direct = _direct_locks(project)
    lock_reach = _locks_reachable(project, direct)
    for k in sorted(project.functions):
        fi = project.functions[k]
        ctx = project.ctx_for[fi.module]
        for lid, with_node in direct.get(k, ()):
            for node in ast.walk(with_node):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute) \
                        or node.func.attr != "join":
                    continue
                if project.enclosing_function(fi.module, node) is not fi:
                    continue
                entry = _receiver_queue(project, fi, node.func.value,
                                        tlocs, tattrs)
                if entry is None:
                    continue
                if lid in lock_reach.get(entry, set()):
                    yield ctx.make_finding(
                        CODE, node,
                        f"`join()` on the `{entry[1]}` thread while "
                        f"holding `{lid}`, a lock that thread also "
                        "acquires: the join can never return — "
                        "release the lock before joining")
