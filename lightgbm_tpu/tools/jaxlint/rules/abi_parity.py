"""JL151 — cross-language C-ABI parity.

The C ABI exists in four places that only convention keeps in sync:
the declarations in ``include/lightgbm_tpu/c_api.h``, the embedded-
interpreter glue in ``src/capi/lgbm_capi.cpp``, the Python
compatibility layer ``lightgbm_tpu/c_api.py`` and the adapter table in
``lightgbm_tpu/capi_embed.py``.  A drifted arity or a swapped
parameter corrupts buffers at the language boundary, where no test
stack trace points at the cause.

A Python module opts in with directives whose paths are relative to
the directive-carrying file::

    # jaxlint: abi-header=../include/lightgbm_tpu/c_api.h
    # jaxlint: abi-impl=../src/capi/lgbm_capi.cpp

A tolerant C declaration scanner (comment-stripping + paren/template
balancing, no compiler needed) extracts every ``LGBM_*`` declaration
from the header and every definition plus
``Py_BuildValue``/``call_adapter`` pair from the ``.cpp``.  Checks:

* **header <-> Python bindings** (a module with ``abi-header`` that
  defines ``LGBM_*`` functions): every header declaration must have a
  Python ``def`` of the same name and arity (extra Python-only compat
  entry points are allowed).
* **header <-> cpp** (a module carrying both directives): every header
  declaration must be defined in the ``.cpp`` and vice versa.
* **cpp <-> adapter table**: every ``call_adapter("name", ...)`` in
  the ``.cpp`` must resolve to a module-level function of that name,
  and the paired ``Py_BuildValue`` format must carry exactly as many
  values as the adapter has parameters.
* **adapter <-> header**: every forwarded ``_call(C.LGBM_X, ...)``
  must pass the header's arity for ``LGBM_X``, and the adapter
  parameters must be forwarded in header order (a swap reads the
  wrong buffer as the wrong scalar).

Directives whose target file is missing are inert (the tree hash still
records the absence, so creating the file invalidates the cache); a
single-source run (no project root) never reports.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from ..cache import resolve_extra_path
from ..context import FileContext, dotted_name
from ..project import ProjectContext

CODE = "JL151"
SHORT = ("C-ABI surfaces out of sync: header/cpp/bindings/adapter "
         "entry-point, arity, or parameter-order divergence")

PROJECT_RULE = True

_DIRECTIVE_RE = re.compile(
    r"#\s*jaxlint:\s*abi-(header|impl)\s*=\s*(\S+)")
_BUILDVALUE_RE = re.compile(r'Py_BuildValue\s*\(\s*"([^"]*)"')
_ADAPTER_RE = re.compile(r'call_adapter\s*\(\s*"(\w+)"')
_NAME_RE = re.compile(r"\bLGBM_(\w+)\s*\(")


def _strip_c_comments(text: str) -> str:
    """Blank out // and /* */ comments, preserving newlines and string
    literals (the adapter names live in strings)."""
    out: List[str] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == '"':
            j = i + 1
            while j < n and text[j] != '"':
                j += 2 if text[j] == "\\" else 1
            out.append(text[i:min(j + 1, n)])
            i = j + 1
        elif text.startswith("//", i):
            j = text.find("\n", i)
            j = n if j < 0 else j
            out.append(" " * (j - i))
            i = j
        elif text.startswith("/*", i):
            j = text.find("*/", i)
            j = n - 2 if j < 0 else j
            seg = text[i:j + 2]
            out.append("".join(ch if ch == "\n" else " " for ch in seg))
            i = j + 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _split_params(params: str) -> int:
    """Top-level comma count -> C parameter arity; handles template
    commas (``unordered_map<string, string>``) and ``(void)``."""
    depth = 0
    parts, cur = [], []
    for ch in params:
        if ch in "(<[":
            depth += 1
        elif ch in ")>]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    parts = [p.strip() for p in parts]
    parts = [p for p in parts if p and p != "void"]
    return len(parts)


def _scan_c(text: str, want_defs: bool) -> Dict[str, int]:
    """``LGBM_*`` name -> arity.  ``want_defs`` keeps only entries
    followed by ``{`` (function definitions); otherwise only ``;``
    -terminated declarations."""
    t = _strip_c_comments(text)
    out: Dict[str, int] = {}
    for m in _NAME_RE.finditer(t):
        depth, j = 1, m.end()
        while j < len(t) and depth:
            if t[j] == "(":
                depth += 1
            elif t[j] == ")":
                depth -= 1
            j += 1
        if depth:
            continue
        k = j
        while k < len(t) and t[k] in " \t\r\n":
            k += 1
        is_def = k < len(t) and t[k] == "{"
        if is_def != want_defs:
            continue
        out["LGBM_" + m.group(1)] = _split_params(t[m.end():j - 1])
    return out


def _adapter_calls(text: str) -> List[Tuple[str, Optional[int]]]:
    """(adapter name, paired Py_BuildValue value count) in cpp order.
    Pairing is sequential: each ``call_adapter`` consumes the nearest
    preceding unconsumed ``Py_BuildValue``."""
    t = _strip_c_comments(text)
    events = [(m.start(), "fmt", m.group(1))
              for m in _BUILDVALUE_RE.finditer(t)]
    events += [(m.start(), "call", m.group(1))
               for m in _ADAPTER_RE.finditer(t)]
    out: List[Tuple[str, Optional[int]]] = []
    pending: Optional[int] = None
    for _, kind, val in sorted(events):
        if kind == "fmt":
            pending = sum(1 for ch in val if ch.isalpha())
        else:
            out.append((val, pending))
            pending = None
    return out


def _directives(ctx: FileContext) -> Dict[str, Tuple[str, int]]:
    """kind -> (normalized relpath, directive line) for one module."""
    out: Dict[str, Tuple[str, int]] = {}
    for i, line in enumerate(ctx.lines, start=1):
        m = _DIRECTIVE_RE.search(line)
        if m and m.group(1) not in out:
            out[m.group(1)] = (resolve_extra_path(ctx.relpath,
                                                  m.group(2)), i)
    return out


def _at_line(line: int) -> ast.AST:
    return ast.Pass(lineno=line, col_offset=0)


def _py_arity(fn: ast.AST) -> int:
    a = fn.args
    return len(a.posonlyargs) + len(a.args)


def _module_defs(project: ProjectContext, mname: str) \
        -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for (m, qual), fi in sorted(project.functions.items()):
        if m == mname and qual == fi.name and fi.class_name is None:
            out[fi.name] = fi.node
    return out


def _forwarded_calls(project: ProjectContext, mname: str):
    """(adapter FuncInfo, call node, LGBM name, n forwarded args,
    forwarded param indices) for each ``_call(C.LGBM_X, ...)``."""
    for key in sorted(project.functions):
        fi = project.functions[key]
        if fi.module != mname:
            continue
        params = [p.arg for p in fi.node.args.posonlyargs
                  + fi.node.args.args]
        for node in project.own_nodes(fi):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Name) \
                    or node.func.id != "_call" or not node.args:
                continue
            d = dotted_name(node.args[0])
            if d is None:
                continue
            cname = d.split(".")[-1]
            if not cname.startswith("LGBM_"):
                continue
            indices: List[int] = []
            for arg in node.args[1:]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in params:
                        indices.append(params.index(sub.id))
                        break
            yield fi, node, cname, len(node.args) - 1, indices


def check_project(project: ProjectContext):
    if project.root is None and not project.extra_files:
        return
    for mname in sorted(project.modules):
        ctx = project.modules[mname].ctx
        dirs = _directives(ctx)
        if not dirs:
            continue
        header_decls = None
        if "header" in dirs:
            text = project.extra_files.get(dirs["header"][0])
            if text is not None:
                header_decls = _scan_c(text, want_defs=False)
        impl_defs = impl_adapters = None
        if "impl" in dirs:
            text = project.extra_files.get(dirs["impl"][0])
            if text is not None:
                impl_defs = _scan_c(text, want_defs=True)
                impl_adapters = _adapter_calls(text)

        defs = _module_defs(project, mname)
        lgbm_defs = {n: f for n, f in defs.items()
                     if n.startswith("LGBM_")}

        # header <-> Python bindings
        if header_decls is not None and lgbm_defs:
            hline = dirs["header"][1]
            for name in sorted(header_decls):
                if name not in lgbm_defs:
                    yield ctx.make_finding(
                        CODE, _at_line(hline),
                        f"`{name}` is declared in "
                        f"`{dirs['header'][0]}` but has no binding in "
                        "this module: add the entry point or drop the "
                        "declaration")
                elif _py_arity(lgbm_defs[name]) != header_decls[name]:
                    yield ctx.make_finding(
                        CODE, lgbm_defs[name],
                        f"`{name}` takes {_py_arity(lgbm_defs[name])} "
                        f"parameters here but the header declares "
                        f"{header_decls[name]}: the native caller and "
                        "this binding disagree on the calling "
                        "convention")

        # header <-> cpp definitions
        if header_decls is not None and impl_defs is not None:
            hline = dirs["impl"][1]
            for name in sorted(header_decls):
                if name not in impl_defs:
                    yield ctx.make_finding(
                        CODE, _at_line(hline),
                        f"`{name}` is declared in "
                        f"`{dirs['header'][0]}` but never defined in "
                        f"`{dirs['impl'][0]}`: the symbol will not "
                        "link")
            for name in sorted(impl_defs):
                if name not in header_decls:
                    yield ctx.make_finding(
                        CODE, _at_line(hline),
                        f"`{name}` is defined in `{dirs['impl'][0]}` "
                        "but not declared in the header: callers "
                        "cannot see it — declare it or remove the "
                        "definition")
                elif impl_defs[name] != header_decls[name]:
                    yield ctx.make_finding(
                        CODE, _at_line(hline),
                        f"`{name}` is defined with {impl_defs[name]} "
                        f"parameters in `{dirs['impl'][0]}` but "
                        f"declared with {header_decls[name]} in the "
                        "header")

        # cpp call_adapter <-> adapter table in this module
        if impl_adapters is not None:
            iline = dirs["impl"][1]
            for name, fmt_count in impl_adapters:
                if name not in defs:
                    yield ctx.make_finding(
                        CODE, _at_line(iline),
                        f"`{dirs['impl'][0]}` calls adapter "
                        f"`{name}` which this module does not define: "
                        "the embedded call will fail at runtime")
                    continue
                arity = _py_arity(defs[name])
                if fmt_count is not None and fmt_count != arity:
                    yield ctx.make_finding(
                        CODE, defs[name],
                        f"adapter `{name}` takes {arity} parameters "
                        f"but `{dirs['impl'][0]}` builds "
                        f"{fmt_count} values for it: the tuple will "
                        "not unpack")

        # adapter forwarding <-> header arity and parameter order
        if impl_adapters is not None and header_decls is not None:
            for fi, node, cname, n_args, indices in \
                    _forwarded_calls(project, mname):
                if cname not in header_decls:
                    continue      # python-only compat entry point
                if n_args != header_decls[cname]:
                    yield ctx.make_finding(
                        CODE, node,
                        f"`{fi.name}` forwards {n_args} arguments to "
                        f"`{cname}` but the header declares "
                        f"{header_decls[cname]} parameters")
                elif any(b <= a for a, b in zip(indices, indices[1:])):
                    yield ctx.make_finding(
                        CODE, node,
                        f"`{fi.name}` forwards its parameters to "
                        f"`{cname}` out of declaration order: a "
                        "swapped position reinterprets the caller's "
                        "buffers — forward in header order")
