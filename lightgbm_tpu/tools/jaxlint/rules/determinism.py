"""JL131 — nondeterminism taint reaching model/checkpoint/digest bytes.

Byte-identical trees across fused/per-iteration/resume and
process-stable ``plan_digest``/``programs_signature`` keys are
load-bearing contracts (CI gates diff model strings across runs).  They
die quietly when a nondeterministic value sneaks into anything that is
serialized or hashed: a wall-clock read in a checkpoint payload, an
unseeded ``np.random`` draw feeding leaf values, a set's hash order
deciding serialization order.  This rule runs a small taint analysis
over the project call graph:

**Sources** — ``time.time/time_ns/monotonic/perf_counter``,
``datetime.now/utcnow/today``, unseeded RNGs (``np.random.<draw>`` on
the global state, ``np.random.default_rng()`` / ``RandomState()`` with
no seed, stdlib ``random.<draw>``, ``uuid.uuid1/uuid4``,
``os.urandom``, ``secrets.*``), and order-unstable collection reads
(``list``/``tuple``/iteration over a set — hash order).  Seeded
constructors (``default_rng(seed)``, ``RandomState(seed)``,
``Random(seed)``) and ``jax.random`` (explicit ``fold_in``-derived
keys) are deterministic and exempt.

**Propagation** — through assignments within a function; through calls:
a function whose return value is taint-derived taints its call sites,
and a tainted argument taints the callee's parameter (summaries are
computed to a fixpoint over the project call graph, so taint crosses
module boundaries).

**Sinks** — arguments of the serialization/keying functions the
contracts depend on: ``plan_digest``/``save_plan``/``cache_plan``,
``programs_signature``/``_config_digest``, the checkpoint writers
(``save_pipeline_checkpoint``/``save_train_state``/
``atomic_write_text``/``atomic_write_bytes``/``save_checkpoint``), and
``model_to_string``/``save_model`` arguments.

Telemetry is deliberately NOT a sink — obs timings/span payloads are
allowed to carry wall-clock values.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..context import FileContext, dotted_name
from ..project import FuncKey, ProjectContext

CODE = "JL131"
SHORT = ("nondeterministic value (wall-clock / unseeded RNG / set "
         "order) flows into model, checkpoint or digest bytes")

PROJECT_RULE = True

_CLOCK_FNS = {"time", "time_ns", "monotonic", "monotonic_ns",
              "perf_counter", "perf_counter_ns", "process_time", "clock"}
_DATETIME_FNS = {"now", "utcnow", "today"}
_RANDOM_DRAWS = {"random", "randint", "randrange", "uniform", "normal",
                 "rand", "randn", "choice", "shuffle", "sample",
                 "bytes", "standard_normal", "permutation", "getrandbits"}
_SEEDED_CTORS = {"default_rng", "RandomState", "Random", "Generator",
                 "SeedSequence", "PRNGKey"}

SINKS = {"plan_digest", "save_plan", "cache_plan", "programs_signature",
         "_config_digest", "save_pipeline_checkpoint", "save_train_state",
         "atomic_write_text", "atomic_write_bytes", "save_checkpoint",
         "model_to_string", "save_model", "dump_model"}


def _is_source(ctx: FileContext, node: ast.AST) -> Optional[str]:
    """Short description when ``node`` is a taint source call/expr."""
    if not isinstance(node, ast.Call):
        return None
    d = dotted_name(node.func)
    if d is None:
        # list(<set>) etc. handled by caller via _unordered_read
        return None
    parts = d.split(".")
    tail = parts[-1]
    root = parts[0]
    if root == "time" and tail in _CLOCK_FNS:
        return f"wall-clock `{d}()`"
    if tail in _DATETIME_FNS and ("datetime" in parts or "date" in parts):
        return f"wall-clock `{d}()`"
    if tail in ("uuid1", "uuid4"):
        return f"`{d}()`"
    if d in ("os.urandom",) or root == "secrets":
        return f"entropy `{d}()`"
    if root in ctx.numpy_aliases and len(parts) >= 2 \
            and parts[1] == "random":
        if tail in _SEEDED_CTORS:
            return None if node.args else \
                f"unseeded `{d}()` (global entropy)"
        if tail in _RANDOM_DRAWS or tail == "seed":
            return f"global-state `{d}(...)` (no fold_in-derived key)"
        return None
    if root == "random" and len(parts) == 2:
        if tail in _SEEDED_CTORS:
            return None if node.args else f"unseeded `{d}()`"
        if tail in _RANDOM_DRAWS:
            return f"global-state `{d}(...)`"
    return None


def _is_set_like(ctx: FileContext, node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("set", "frozenset"):
        return True
    if isinstance(node, ast.Name):
        return node.id in ctx.set_names(node)
    return False


def _unordered_read(ctx: FileContext, node: ast.AST) -> bool:
    """list/tuple(<set>) — hash-order materialization (sorted() is the
    deterministic spelling and is exempt)."""
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("list", "tuple")
            and node.args and _is_set_like(ctx, node.args[0]))


_PARAM_TAINT = "tainted parameter"


class _Summary:
    __slots__ = ("returns_tainted", "sink_params", "return_reason")

    def __init__(self):
        self.returns_tainted = False
        self.return_reason: Optional[str] = None
        #: param names whose taint reaches a sink inside this function
        self.sink_params: Set[str] = set()


def _function_pass(project: ProjectContext, fi,
                   summaries: Dict[FuncKey, _Summary],
                   tainted_params: Set[str],
                   report: Optional[list]) -> _Summary:
    """One abstract-interpretation pass over ``fi``.  With ``report``
    set, sink hits are appended as (node, reason) pairs."""
    ctx = project.ctx_for[fi.module]
    # parameter taint carries its provenance ("tainted parameter:<p>")
    # so an alias (`m = meta`) still attributes a sink hit to `meta`
    env: Dict[str, str] = {p: f"{_PARAM_TAINT}:{p}"
                           for p in tainted_params}
    out = _Summary()

    def expr_taint(node: ast.AST) -> Optional[str]:
        for sub in ast.walk(node):
            src = _is_source(ctx, sub)
            if src is not None:
                return src
            if _unordered_read(ctx, sub):
                return "set hash-order materialization"
            if isinstance(sub, ast.Name) and sub.id in env:
                return env[sub.id]
            if isinstance(sub, ast.Call):
                for callee in project.resolve_call(fi, sub):
                    s = summaries.get(callee)
                    if s is not None and s.returns_tainted:
                        return s.return_reason or "tainted call result"
        return None

    def note_sink_hit(arg: ast.AST, sink_name: str, reason: str):
        """A tainted expression meets a sink: report it (report mode)
        or attribute it to the responsible parameters (summary mode)."""
        if reason.startswith(_PARAM_TAINT):
            out.sink_params.add(reason.split(":", 1)[1])
        elif report is not None:
            report.append((node, sink_name, reason))

    own_scope = project.own_nodes(fi)
    stmts = [n for n in own_scope if isinstance(n, (ast.Assign,
                                                    ast.AugAssign))]
    stmts.sort(key=lambda n: (n.lineno, n.col_offset))
    for _ in range(2):
        for node in stmts:
            reason = expr_taint(node.value)
            if reason is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    env[t.id] = reason

    for node in own_scope:
        if isinstance(node, ast.Return) and node.value is not None:
            reason = expr_taint(node.value)
            if reason is not None \
                    and not reason.startswith(_PARAM_TAINT):
                out.returns_tainted = True
                out.return_reason = reason
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func)
        tail = d.split(".")[-1] if d else None
        if tail in SINKS:
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                reason = expr_taint(a)
                if reason is not None:
                    note_sink_hit(a, tail, reason)
        # taint crossing into a callee that forwards it to a sink
        for callee in project.resolve_call(fi, node):
            s = summaries.get(callee)
            if s is None or not s.sink_params:
                continue
            cfi = project.functions[callee]
            params = [a.arg for a in cfi.node.args.args]
            # method calls pass the receiver implicitly: align
            # positional args past `self`/`cls`
            off = 1 if (params and params[0] in ("self", "cls")
                        and isinstance(node.func, ast.Attribute)) else 0
            pos_args = [(params[i + off] if i + off < len(params)
                         else None, a)
                        for i, a in enumerate(node.args)]
            kw_args = [(kw.arg, kw.value) for kw in node.keywords]
            for pname, a in pos_args + kw_args:
                if pname not in s.sink_params:
                    continue
                reason = expr_taint(a)
                if reason is not None:
                    note_sink_hit(a, cfi.name, reason)
    return out


def _param_sink_summary(project: ProjectContext, fi,
                        summaries: Dict[FuncKey, _Summary]) -> Set[str]:
    """Params of ``fi`` whose taint would reach a sink."""
    params = {a.arg for a in fi.node.args.args} - {"self", "cls"}
    if not params:
        return set()
    s = _function_pass(project, fi, summaries, params, report=None)
    return s.sink_params


def check_project(project: ProjectContext):
    summaries: Dict[FuncKey, _Summary] = {}
    # fixpoint over return-taint and param-to-sink summaries
    for _ in range(3):
        changed = False
        for key, fi in project.functions.items():
            s = _function_pass(project, fi, summaries, set(), report=None)
            s.sink_params = _param_sink_summary(project, fi, summaries)
            prev = summaries.get(key)
            if prev is None or prev.returns_tainted != s.returns_tainted \
                    or prev.sink_params != s.sink_params:
                changed = True
            summaries[key] = s
        if not changed:
            break

    findings: List[Tuple[ast.AST, str, str, str]] = []
    for _key, fi in sorted(project.functions.items()):
        report: list = []
        _function_pass(project, fi, summaries, set(), report=report)
        for node, sink, reason in report:
            findings.append((node, sink, reason, fi.module))
    # module-level statements (outside any function) get a light pass
    for mname, mod in sorted(project.modules.items()):
        ctx = mod.ctx
        for node in project.module_level_nodes(mname):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            tail = d.split(".")[-1] if d else None
            if tail not in SINKS:
                continue
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(a):
                    src = _is_source(ctx, sub)
                    if src is None and _unordered_read(ctx, sub):
                        src = "set hash-order materialization"
                    if src is not None:
                        findings.append((node, tail, src, mname))

    seen = set()
    for node, sink, reason, mname in findings:
        ctx = project.ctx_for[mname]
        dk = (mname, getattr(node, "lineno", 0), sink, reason)
        if dk in seen:
            continue
        seen.add(dk)
        yield ctx.make_finding(
            CODE, node,
            f"{reason} reaches `{sink}(...)`: model bytes, checkpoint "
            "payloads and cache digests must be identical across runs — "
            "derive the value from seeds/fold_in, sort the collection, "
            "or keep it out of the serialized payload")
