"""JL111 — int8/int32 quantization dtype contract, project-wide.

The ``grad_quant_bits=8`` path is only fast (and only byte-stable)
while the data stays integer from quantization to the single dequantize
point in the gain/leaf-value math: int8 stat columns contract on the
MXU's native int8→int32 path, histogram state accumulates in int32, and
ONE ``.astype(float32) * scale`` dequantize ends the integer region.
PR 9's review found exactly the violations this rule now automates: an
f32 dequantize left upstream of the find-best scan, and int8 dots
without ``preferred_element_type`` (which silently accumulate through
f32 and fall off the MXU int path).  Per-function dtype dataflow
(tracking ``astype``/``asarray``/constructor dtypes and contraction
result types) drives three checks:

1. **int8 contraction without ``preferred_element_type``**: any
   ``einsum``/``dot``/``matmul``/``tensordot``/``dot_general`` whose
   operand is int8-typed must pin the int32 accumulator.
2. **Premature f32 upcast**: ``.astype(float32)`` on an int8 value, or
   on int32 *quantized accumulation state* (the result of an int32-
   accumulated contraction and values derived from it), is flagged —
   UNLESS it is the sanctioned dequantize idiom, an immediate multiply
   or divide by a ``*scale*``-named value, or lives in a function whose
   name mentions ``dequant``.
3. **Cross-module f64 leakage** (the repo runs with x64 disabled): a
   module-level constant whose value is float64-marked (``np.float64``,
   ``dtype="float64"``) passed into a ``jnp.``-rooted call — including
   constants imported from another module, which the per-file JL004
   cannot see.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional

from ..context import FileContext, chain_root, dotted_name
from ..project import ProjectContext

CODE = "JL111"
SHORT = ("int8 dtype-contract break: unpinned int8 contraction, "
         "premature f32 upcast of quantized state, or cross-module "
         "f64 into jnp under disabled x64")

PROJECT_RULE = True

_CONTRACTIONS = ("einsum", "dot", "matmul", "tensordot", "dot_general")
_INT8 = "int8"
_INT32Q = "int32q"          # int32 quantized accumulation state
_F32_NAMES = ("float32", "f32")
_SCALE_HINT = ("scale", "qscale", "dequant")


def _dtype_of_node(ctx: FileContext, node: ast.AST) -> Optional[str]:
    """Literal dtype a dtype-expression denotes ("int8", "float32"...)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Attribute):
        root = chain_root(node)
        if root in ctx.jnp_aliases or root in ctx.numpy_aliases \
                or root in ctx.jax_aliases:
            return node.attr
    return None


class _Env:
    """Per-scope inferred dtypes, line-aware: each name maps to its
    binding history so a use at line L sees the binding in effect
    BEFORE L (``m8 = m8.astype(jnp.float32)`` must see the int8 `m8`
    on its right-hand side, not its own result)."""

    def __init__(self):
        self.bindings: Dict[str, List[Tuple[int, str]]] = {}

    def bind(self, name: str, line: int, dtype: str) -> None:
        self.bindings.setdefault(name, []).append((line, dtype))

    def get(self, name: str, line: int) -> Optional[str]:
        best = None
        for bl, dt in self.bindings.get(name, ()):
            if bl < line:
                best = dt
        return best


def _infer(ctx: FileContext, env: _Env, node: ast.AST,
           line: int) -> Optional[str]:
    """Dtype tag of an expression evaluated at ``line``, or None."""
    if isinstance(node, ast.Name):
        return env.get(node.id, line)
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return env.get(f"self.{node.attr}", line)
    if isinstance(node, ast.Subscript):
        return _infer(ctx, env, node.value, line)
    if isinstance(node, ast.BinOp):
        lt = _infer(ctx, env, node.left, line)
        rt = _infer(ctx, env, node.right, line)
        if lt == rt:
            return lt
        pair = {lt, rt}
        if pair == {_INT8, _INT32Q}:
            return _INT32Q
        if None in pair:
            t = lt or rt
            # int arithmetic with an unknown (likely scalar) operand
            # keeps the known integer tag; anything else is unknown
            return t if t in (_INT8, _INT32Q) else None
        return None
    if isinstance(node, ast.UnaryOp):
        return _infer(ctx, env, node.operand, line)
    if isinstance(node, ast.Call):
        return _call_dtype(ctx, env, node, line)
    return None


def _call_dtype(ctx: FileContext, env: _Env, node: ast.Call,
                line: int) -> Optional[str]:
    func = node.func
    # x.astype(D) / x.reshape / x.transpose / dtype-preserving methods
    if isinstance(func, ast.Attribute):
        if func.attr == "astype" and node.args:
            return _dtype_of_node(ctx, node.args[0])
        if func.attr in ("reshape", "transpose", "sum", "cumsum", "at",
                         "set", "add", "squeeze", "ravel", "flatten"):
            base = _infer(ctx, env, func.value, line)
            if base in (_INT8, _INT32Q):
                # integer sums stay integer; .at[...].set/add preserve
                return _INT32Q if func.attr in ("sum", "cumsum") \
                    and base == _INT8 else base
            return base
        d = dotted_name(func)
        if d is not None:
            tail = d.split(".")[-1]
            root = chain_root(func)
            if tail in ("int8",) and (root in ctx.jnp_aliases
                                      or root in ctx.numpy_aliases):
                return _INT8
            if tail in _CONTRACTIONS:
                pet = _pet_dtype(ctx, node)
                if pet is not None:
                    return _INT32Q if "int32" in pet else pet
                ops = [_infer(ctx, env, a, line) for a in node.args]
                if _INT8 in ops:
                    return _INT8
                return None
            if tail in ("zeros", "ones", "full", "empty", "arange",
                        "asarray", "array"):
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        return _dtype_of_node(ctx, kw.value)
                for a in node.args[1:]:
                    dt = _dtype_of_node(ctx, a)
                    if dt is not None:
                        return dt
                return None
            if tail == "where" and len(node.args) == 3:
                a = _infer(ctx, env, node.args[1], line)
                b = _infer(ctx, env, node.args[2], line)
                return a if a == b else None
            if tail == "convert_element_type" and len(node.args) >= 2:
                return _dtype_of_node(ctx, node.args[1])
    return None


def _pet_dtype(ctx: FileContext, node: ast.Call) -> Optional[str]:
    for kw in node.keywords:
        if kw.arg == "preferred_element_type":
            return _dtype_of_node(ctx, kw.value) or "unknown"
    return None


def _scale_multiplied(ctx: FileContext, node: ast.AST) -> bool:
    """True when the astype(...) result is immediately multiplied or
    divided by a value whose source text mentions a scale — the
    sanctioned dequantize idiom."""
    parent = ctx.parent(node)
    if not (isinstance(parent, ast.BinOp)
            and isinstance(parent.op, (ast.Mult, ast.Div))):
        return False
    other = parent.right if parent.left is node else parent.left
    try:
        text = ast.unparse(other).lower()
    except Exception:
        return False
    return any(h in text for h in _SCALE_HINT)


def _scope_walk(root: ast.AST):
    """Walk ``root`` without descending into nested function scopes
    (class bodies are transparent), so each scope is analyzed exactly
    once with its own dtype state."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_function(ctx: FileContext, fn_name: str, body: ast.AST):
    """Run the int8 checks over one scope with fresh dtype state."""
    env = _Env()
    # statement-order pass: the walk is not source-ordered, so collect
    # assignments first by line order for a stable single pass
    assigns = [n for n in _scope_walk(body) if isinstance(n, ast.Assign)
               and len(n.targets) == 1]
    assigns.sort(key=lambda n: (n.lineno, n.col_offset))
    for a in assigns:
        t = a.targets[0]
        dt = _infer(ctx, env, a.value, a.lineno)
        if dt is None:
            continue
        if isinstance(t, ast.Name):
            env.bind(t.id, a.lineno, dt)
        elif isinstance(t, ast.Attribute) \
                and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            env.bind(f"self.{t.attr}", a.lineno, dt)

    dequant_fn = "dequant" in fn_name.lower()
    for node in _scope_walk(body):
        if not isinstance(node, ast.Call):
            continue
        line = getattr(node, "lineno", 0)
        func = node.func
        d = dotted_name(func)
        tail = d.split(".")[-1] if d else None
        # (1) int8 contraction without preferred_element_type
        if tail in _CONTRACTIONS:
            ops = [_infer(ctx, env, a, line) for a in node.args]
            if _INT8 in ops and _pet_dtype(ctx, node) is None:
                yield ctx.make_finding(
                    CODE, node,
                    f"`{tail}` over int8 operands without "
                    "preferred_element_type=jnp.int32: the contraction "
                    "accumulates off the MXU int8->int32 path and the "
                    "histogram loses integer exactness")
        # (2) premature f32 upcast of quantized state
        if isinstance(func, ast.Attribute) and func.attr == "astype" \
                and node.args and not dequant_fn:
            target = _dtype_of_node(ctx, node.args[0])
            if target in _F32_NAMES:
                src = _infer(ctx, env, func.value, line)
                if src in (_INT8, _INT32Q) \
                        and not _scale_multiplied(ctx, node):
                    kind = ("int8-quantized value" if src == _INT8
                            else "int32 quantized accumulation state")
                    yield ctx.make_finding(
                        CODE, node,
                        f"f32 upcast of {kind} outside the dequantize "
                        "point: keep the scan integer and dequantize "
                        "once at the gain/leaf-value math "
                        "(`.astype(jnp.float32) * scale`)")


def _check_f64_leak(project: ProjectContext, mname: str):
    mod = project.modules[mname]
    ctx = mod.ctx
    if not ctx.jnp_aliases:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func)
        if d is None or chain_root(node.func) not in ctx.jnp_aliases:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for leaf in ast.walk(arg):
                value = None
                n = None
                if isinstance(leaf, ast.Name):
                    n = leaf.id
                    value = project.constant_value_node(mname, n)
                elif isinstance(leaf, ast.Attribute):
                    base = dotted_name(leaf.value)
                    m2 = project.resolve_module(mname, base) \
                        if base is not None else None
                    if m2 is not None:
                        n = leaf.attr
                        value = project.modules[m2].assigns.get(n)
                if value is not None and _is_f64_value(value):
                    yield ctx.make_finding(
                        CODE, leaf,
                        f"`{n}` is a float64 constant flowing into "
                        f"`{d}(...)` while x64 is disabled: silently "
                        "truncated to f32 (and a recompile bomb if "
                        "x64 is ever enabled); store it as f32 or "
                        "keep the f64 math on host")


def _is_f64_value(value: ast.AST) -> bool:
    for n in ast.walk(value):
        if isinstance(n, ast.Constant) and n.value == "float64":
            return True
        if isinstance(n, ast.Attribute) and n.attr == "float64":
            return True
    return False


def check_project(project: ProjectContext):
    for mname, mod in project.modules.items():
        ctx = mod.ctx
        # module-level scope plus every function, each with fresh state
        yield from _check_function(ctx, "<module>", ctx.tree)
        for fi in project.functions.values():
            if fi.module != mname:
                continue
            yield from _check_function(ctx, fi.name, fi.node)
        yield from _check_f64_leak(project, mname)
