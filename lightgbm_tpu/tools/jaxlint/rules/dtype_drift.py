"""JL004 — float64 flowing into device code while x64 is disabled.

The package runs with JAX's default x64-disabled config: a
``np.float64``/``"float64"`` dtype handed to a ``jnp.`` constructor is
silently truncated to float32 — the code *reads* like it computes in
double but doesn't, and if x64 were ever enabled the same line would
double every buffer and recompile every consumer.  Host-side float64
(``np.asarray(x, np.float64)`` for metrics/model text) is deliberate
and exempt: only ``jnp.``-rooted calls are checked.
"""

from __future__ import annotations

import ast

from ..context import FileContext, chain_root, dotted_name

CODE = "JL004"
SHORT = ("float64 dtype passed into jnp device code while x64 is "
         "disabled (silent truncation to float32)")


def _is_f64_marker(ctx: FileContext, node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "float64":
        return True
    if isinstance(node, ast.Attribute) and node.attr in ("float64",
                                                         "int64"):
        root = chain_root(node)
        return root in ctx.numpy_aliases or root in ctx.jnp_aliases
    return False


def check(ctx: FileContext):
    if not ctx.jnp_aliases:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func)
        if d is None or chain_root(node.func) not in ctx.jnp_aliases:
            continue
        if d.split(".")[-1] in ("float64", "int64"):
            yield ctx.make_finding(
                CODE, node,
                f"`{d}(...)` under disabled x64 silently produces 32-bit "
                "values; use the 32-bit dtype explicitly or keep the "
                "value on host")
            continue
        for sub in list(node.args) + [kw.value for kw in node.keywords]:
            for leaf in ast.walk(sub):
                if _is_f64_marker(ctx, leaf):
                    yield ctx.make_finding(
                        CODE, leaf,
                        f"64-bit dtype passed into `{d}(...)` while x64 "
                        "is disabled: the array is silently truncated to "
                        "32-bit; spell the 32-bit dtype or do the f64 "
                        "math on host")
