"""JL121 — lock discipline across the threaded subsystems.

The pipeline prep thread, the serve micro-batch worker, the
stream-loader reader and the breaker re-probe all run concurrently with
the main training thread, and the locks they touch live in different
modules (``serve/engine.py``, ``pipeline/core.py``, ``robust/*``,
``c_api.py``, ``ops/grow.py``).  JL006's per-file name heuristic cannot
see either of the two real hazards:

1. **Lock-order inversion**: function A acquires lock L1 and (possibly
   through project calls) lock L2 while holding it; function B acquires
   them in the other order — a classic cross-thread deadlock.  The rule
   builds a project-wide lock-acquisition-order graph (lock identity =
   ``module:Class.attr`` for ``self._lock``-style locks,
   ``module:NAME`` for module-level locks) with an edge L1→L2 for every
   "L2 acquired while L1 is held", including acquisitions inside
   transitively called project functions, and flags every edge that
   participates in a cycle.
2. **Thread-shared state without a lock**: from every thread entry
   point (a ``target=`` handed to ``threading.Thread``) the rule walks
   the call graph; a reachable mutation of *another module's*
   module-level mutable container (invisible to JL006's single-file
   view), or a bare ``self.<attr> = ...`` write inside the entry
   function of a class that owns a lock, is flagged unless it happens
   under a ``with <...lock...>:`` block.

Queues, events and thread-local state are the sanctioned lock-free
channels; anything else shared between threads takes the owning lock or
a written ``# jaxlint: disable=JL121`` justification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..context import dotted_name
from ..project import FuncKey, ProjectContext
from .global_state import _MUTATORS, _module_mutables, _under_lock

CODE = "JL121"
SHORT = ("lock-order inversion or thread-reachable shared-state "
         "mutation outside a lock (cross-module deadlock/race)")

PROJECT_RULE = True

LockId = str


def _lock_id(project: ProjectContext, fi, expr: ast.AST) \
        -> Optional[LockId]:
    """Stable identity for a lock context expression, or None when the
    expression is not lock-like."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    d = dotted_name(expr)
    if d is None or "lock" not in d.lower():
        return None
    parts = d.split(".")
    if parts[0] == "self" and fi is not None and fi.class_name:
        return f"{fi.module}:{fi.class_name}.{'.'.join(parts[1:])}"
    if len(parts) == 1:
        r = project.resolve_symbol(fi.module, parts[0]) \
            if fi is not None else None
        if r is not None:
            return f"{r[0]}:{r[1]}"
        return f"{fi.module if fi else '?'}:{d}"
    m2 = project.resolve_module(fi.module, parts[0]) \
        if fi is not None else None
    if m2 is not None:
        return f"{m2}:{'.'.join(parts[1:])}"
    return f"{fi.module if fi else '?'}:{d}"


def _direct_locks(project: ProjectContext) \
        -> Dict[FuncKey, List[Tuple[LockId, ast.With]]]:
    out: Dict[FuncKey, List[Tuple[LockId, ast.With]]] = {}
    for key, fi in project.functions.items():
        acquired = []
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.With):
                continue
            if project.enclosing_function(fi.module, node) is not fi:
                continue
            for item in node.items:
                lid = _lock_id(project, fi, item.context_expr)
                if lid is not None:
                    acquired.append((lid, node))
        out[key] = acquired
    return out


def _locks_reachable(project: ProjectContext,
                     direct: Dict[FuncKey, List[Tuple[LockId, ast.With]]]
                     ) -> Dict[FuncKey, Set[LockId]]:
    """Fixpoint: locks a call into each function may end up acquiring."""
    out = {k: {lid for lid, _ in v} for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for key in project.functions:
            agg = set(out.get(key, set()))
            for callee in project.calls.get(key, ()):
                agg |= out.get(callee, set())
            if agg != out.get(key, set()):
                out[key] = agg
                changed = True
    return out


def _order_edges(project: ProjectContext):
    """(outer_lock, inner_lock, site_module, site_node) for every
    "inner acquired while outer held" relation in the project."""
    direct = _direct_locks(project)
    reach = _locks_reachable(project, direct)
    edges: List[Tuple[LockId, LockId, str, ast.AST]] = []
    for key, fi in project.functions.items():
        # `with A_LOCK, B_LOCK:` acquires left-to-right — each earlier
        # item orders before every later one
        seen_with = set()
        for lid, with_node in direct.get(key, ()):
            if id(with_node) not in seen_with:
                seen_with.add(id(with_node))
                ids = [_lock_id(project, fi, it.context_expr)
                       for it in with_node.items]
                ids = [i for i in ids if i is not None]
                for a in range(len(ids)):
                    for b in range(a + 1, len(ids)):
                        if ids[a] != ids[b]:
                            edges.append((ids[a], ids[b], fi.module,
                                          with_node))
        for lid, with_node in direct.get(key, ()):
            for node in ast.walk(with_node):
                if node is with_node:
                    continue
                if isinstance(node, ast.With):
                    inner_fi = project.enclosing_function(fi.module, node)
                    if inner_fi is not fi:
                        continue
                    for item in node.items:
                        lid2 = _lock_id(project, fi, item.context_expr)
                        if lid2 is not None and lid2 != lid:
                            edges.append((lid, lid2, fi.module, node))
                elif isinstance(node, ast.Call):
                    if project.enclosing_function(fi.module, node) \
                            is not fi:
                        continue
                    for callee in project.resolve_call(fi, node):
                        for lid2 in reach.get(callee, ()):
                            if lid2 != lid:
                                edges.append((lid, lid2, fi.module,
                                              node))
    return edges


def _cycle_edges(edges) -> Set[Tuple[LockId, LockId]]:
    """Edges participating in any cycle of the lock-order graph (edges
    inside one strongly connected component with >1 node, plus
    self-loops)."""
    graph: Dict[LockId, Set[LockId]] = {}
    for a, b, _, _ in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    # Tarjan SCC, iterative
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on: Set[LockId] = set()
    comp: Dict[LockId, int] = {}
    stack: List[LockId] = []
    counter = [0]
    ncomp = [0]

    def strongconnect(v0):
        work = [(v0, iter(sorted(graph[v0])))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp[w] = ncomp[0]
                    if w == v:
                        break
                ncomp[0] += 1

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    comp_sizes: Dict[int, int] = {}
    for c in comp.values():
        comp_sizes[c] = comp_sizes.get(c, 0) + 1
    bad: Set[Tuple[LockId, LockId]] = set()
    for a, b, _, _ in edges:
        if a == b or (comp.get(a) == comp.get(b)
                      and comp_sizes.get(comp.get(a), 0) > 1):
            bad.add((a, b))
    return bad


def _thread_entry_points(project: ProjectContext) -> Set[FuncKey]:
    out: Set[FuncKey] = set()
    for mname, mod in project.modules.items():
        ctx = mod.ctx
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is None or d.split(".")[-1] != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                r = project._callable_ref(mname, ctx, kw.value)
                if r is not None:
                    out.add(r)
                elif isinstance(kw.value, ast.Name):
                    # a nested `def worker()` in the enclosing function
                    fi = project.enclosing_function(mname, node)
                    if fi is not None:
                        k = (mname,
                             f"{fi.qualname}.<locals>.{kw.value.id}")
                        if k in project.functions:
                            out.add(k)
    return out


def check_project(project: ProjectContext):
    # (1) lock-order inversions
    edges = _order_edges(project)
    bad = _cycle_edges(edges)
    seen: Set[Tuple[LockId, LockId, str, int]] = set()
    for a, b, mname, node in edges:
        if (a, b) not in bad:
            continue
        ctx = project.ctx_for[mname]
        key = (a, b, mname, getattr(node, "lineno", 0))
        if key in seen:
            continue
        seen.add(key)
        yield ctx.make_finding(
            CODE, node,
            f"lock-order inversion: `{b}` can be acquired here while "
            f"`{a}` is held, but elsewhere the opposite order occurs — "
            "establish one global order or release the outer lock first "
            "(deadlock risk across threads)")

    # (2) thread-reachable unguarded mutation
    entries = _thread_entry_points(project)
    reachable = project.reachable_from(entries)
    for key in sorted(reachable):
        fi = project.functions[key]
        ctx = project.ctx_for[fi.module]
        for node in ast.walk(fi.node):
            if project.enclosing_function(fi.module, node) is not fi:
                continue
            # cross-module container mutation: other.STATE[...] = x /
            # other.STATE.append(x) — invisible to JL006's file view
            tgt = None
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                tgt = node.func.value
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                ts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in ts:
                    if isinstance(t, ast.Subscript):
                        tgt = t.value
            if tgt is not None and isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name):
                m2 = project.resolve_module(fi.module, tgt.value.id)
                if m2 is not None and m2 != fi.module \
                        and tgt.attr in _module_mutables(
                            project.ctx_for[m2]) \
                        and not _under_lock(ctx, node):
                    yield ctx.make_finding(
                        CODE, node,
                        f"thread-reachable mutation of "
                        f"`{m2}.{tgt.attr}` outside a lock (reached "
                        "from a threading.Thread target): guard it "
                        "with the owning module's lock")
            # bare-Name mutation of a same-module mutable is JL006's
            # finding already; not re-reported here

    # (2b) self-attribute writes inside the thread entry itself
    for key in sorted(entries):
        fi = project.functions[key]
        if fi.class_name is None:
            continue
        ctx = project.ctx_for[fi.module]
        cls_node = project.modules[fi.module].classes.get(fi.class_name)
        if cls_node is None or not _class_has_lock(cls_node):
            continue
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            if project.enclosing_function(fi.module, node) is not fi:
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" \
                        and not _under_lock(ctx, node):
                    yield ctx.make_finding(
                        CODE, node,
                        f"`self.{t.attr}` written in a thread entry "
                        f"point while {fi.class_name} owns a lock: "
                        "other threads read this attribute — take the "
                        "lock (or use a Queue/Event)")


def _class_has_lock(cls_node: ast.ClassDef) -> bool:
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) \
                        and "lock" in t.attr.lower():
                    return True
    return False
