"""JL161 — fault-site registry coverage.

The chaos harness (``robust/faults.py``) can only break what the code
arms: every injection point names a site string that must exist in the
``KNOWN_SITES`` registry, and the registry in turn promises each entry
is wired into real code.  Both directions drift silently — a typo'd
site never fires, a removed call leaves a dead registry entry, and a
new background worker that never passes near a fault site ships
outside the chaos harness entirely (ROADMAP item 4's composed soak
assumes otherwise).

The rule activates when some project module assigns a top-level
``KNOWN_SITES`` tuple/list of string literals (``robust/faults.py`` in
this repo); with no registry in view — single-file runs, the analyzer
scanning itself — it stays silent.  A *use* is any call that passes a
string literal for a parameter named ``site``: keyword form
(``with_retries(fn, site="net.connect")``) is recognized anywhere,
positional form (``faults.check("io.read")``, ``_netop(sock,
"net.send", ...)``) wherever the call graph resolves the callee.
Checks:

1. every used site string must exist in ``KNOWN_SITES`` — an unknown
   site arms nothing;
2. every ``KNOWN_SITES`` entry must be used somewhere — dead entries
   make chaos specs silently vacuous;
3. every ``threading.Thread`` entry point must reach at least one
   fault site through its transitive call closure, so each background
   worker can be exercised by the harness.

Escapes: register the site, delete the dead entry, or justify with
``# jaxlint: disable=JL161`` on the spawn/def line.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..project import FuncKey, ProjectContext
from .lock_order import _thread_entry_points

CODE = "JL161"
SHORT = ("fault-site string not in KNOWN_SITES, dead registry entry, "
         "or thread worker unreachable from every fault site")

PROJECT_RULE = True

_REGISTRY_NAME = "KNOWN_SITES"


def _registry(project: ProjectContext) \
        -> List[Tuple[str, ast.AST, Set[str]]]:
    """(module, assign value node, site strings) per registry module."""
    out = []
    for mname in sorted(project.modules):
        val = project.modules[mname].assigns.get(_REGISTRY_NAME)
        if not isinstance(val, (ast.Tuple, ast.List)) or not val.elts:
            continue
        sites: Set[str] = set()
        ok = True
        for e in val.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                sites.add(e.value)
            else:
                ok = False
        if ok:
            out.append((mname, val, sites))
    return out


def _site_of_call(project: ProjectContext, mname: str,
                  node: ast.Call) -> Optional[str]:
    for kw in node.keywords:
        if kw.arg == "site" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    fi = project.enclosing_function(mname, node)
    if fi is None:
        return None
    for callee in sorted(project.resolve_call(fi, node)):
        tfi = project.functions[callee]
        params = [p.arg for p in tfi.node.args.posonlyargs
                  + tfi.node.args.args]
        if params and params[0] == "self":
            params = params[1:]
        if "site" not in params:
            continue
        idx = params.index("site")
        if idx < len(node.args):
            a = node.args[idx]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                return a.value
    return None


def _site_uses(project: ProjectContext) \
        -> List[Tuple[str, str, ast.Call]]:
    uses: List[Tuple[str, str, ast.Call]] = []
    for mname in sorted(project.modules):
        ctx = project.modules[mname].ctx
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            s = _site_of_call(project, mname, node)
            if s is not None:
                uses.append((s, mname, node))
    return uses


def check_project(project: ProjectContext):
    registries = _registry(project)
    if not registries:
        return
    sites: Set[str] = set()
    for _, _, s in registries:
        sites |= s
    uses = _site_uses(project)

    # (1) used site strings must be registered
    for s, mname, node in uses:
        if s not in sites:
            ctx = project.ctx_for[mname]
            yield ctx.make_finding(
                CODE, node,
                f"fault site `{s}` is not in {_REGISTRY_NAME}: the "
                "chaos harness can never arm it — register the site "
                "or fix the typo")

    # (2) registered sites must be used
    used = {s for s, _, _ in uses}
    for mname, val, s in registries:
        ctx = project.ctx_for[mname]
        for dead in sorted(s - used):
            yield ctx.make_finding(
                CODE, val,
                f"{_REGISTRY_NAME} entry `{dead}` is wired into no "
                "with_retries/breaker/fault-check call: a chaos spec "
                "naming it is silently vacuous — delete the entry or "
                "arm the site in code")

    # (3) every thread worker must pass near some fault site
    use_keys: Set[FuncKey] = set()
    for _, mname, node in uses:
        fi = project.enclosing_function(mname, node)
        if fi is not None:
            use_keys.add(fi.key)
    for entry in sorted(_thread_entry_points(project)):
        closure = project.reachable_from([entry])
        if closure & use_keys:
            continue
        fi = project.functions[entry]
        ctx = project.ctx_for[fi.module]
        yield ctx.make_finding(
            CODE, fi.node,
            f"thread worker `{fi.qualname}` is reachable from no "
            "fault site or breaker: the chaos harness cannot "
            "exercise this background thread — arm a site on its "
            "path (faults.check/with_retries) or justify the "
            "exemption")
