"""Whole-repo analysis context: symbol table, import and call graphs.

PR 2's jaxlint was strictly per-file, but the repo's load-bearing
contracts are cross-module: a constant defined in ``ops/histogram.py``
shapes a trace built in ``ops/grow.py``; a lock acquired in
``serve/engine.py`` is ordered against one in ``robust/retry.py``; a
wall-clock read in one function reaches a checkpoint writer three calls
away.  :class:`ProjectContext` builds the shared machinery the JL1xx
rule families need on top of the per-file :class:`FileContext`s:

* a **module table** keyed by dotted module name (derived from the
  relative path), with each module's top-level constants, functions,
  classes/methods and import-alias table (relative imports resolved);
* a **call graph** over ``(module, qualname)`` function keys, resolving
  bare names, ``self.method``, imported modules/symbols, and locals
  assigned from project-class constructors;
* the **traced-region set**: functions whose bodies run under a jax
  trace (jit-decorated/bound, passed to ``lax.scan``-family combinators,
  nested inside either) closed transitively over the call graph;
* **reachability** helpers used by the lock-discipline and determinism
  rules.

Like the per-file layer, everything here is pure ``ast`` — analyzed
code is never imported.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .context import FileContext, dotted_name

#: lax combinators whose callable arguments run inside a trace
_TRACE_COMBINATORS = ("scan", "cond", "while_loop", "fori_loop", "switch",
                      "map", "vmap", "pmap", "remat", "checkpoint", "jit",
                      "custom_jvp", "custom_vjp")

FuncKey = Tuple[str, str]          # (module dotted name, qualname)


class FuncInfo:
    """One function or method in the project."""

    __slots__ = ("module", "qualname", "node", "class_name")

    def __init__(self, module: str, qualname: str,
                 node: ast.AST, class_name: Optional[str]):
        self.module = module
        self.qualname = qualname
        self.node = node
        self.class_name = class_name

    @property
    def key(self) -> FuncKey:
        return (self.module, self.qualname)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


class ModuleInfo:
    """Symbol table of one analyzed module."""

    def __init__(self, name: str, ctx: FileContext):
        self.name = name
        self.ctx = ctx
        #: top-level NAME = <expr> assignments (constants, jit bindings)
        self.assigns: Dict[str, ast.AST] = {}
        #: local alias -> (module dotted name, symbol-or-None)
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.assigns[t.id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                    and isinstance(stmt.target, ast.Name):
                self.assigns[stmt.target.id] = stmt.value
            elif isinstance(stmt, ast.ClassDef):
                self.classes[stmt.name] = stmt
        self._collect_imports()

    def _collect_imports(self):
        pkg_parts = self.name.split(".")[:-1]
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = \
                        (a.name, None)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                    mod = ".".join(base + ([node.module]
                                           if node.module else []))
                else:
                    mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.imports[a.asname or a.name] = (mod, a.name)


def module_name_for(relpath: str) -> str:
    p = relpath.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[:-len("/__init__")]
    return p.replace("/", ".")


class ProjectContext:
    """Cross-module view over a set of :class:`FileContext`s."""

    def __init__(self, contexts: Iterable[FileContext],
                 root: Optional[str] = None,
                 extra_files: Optional[Dict[str, str]] = None):
        #: analysis root (str path) when the run has one; single-source
        #: runs (``analyze_source``) leave it ``None`` and rules that
        #: need sibling non-Python inputs stay silent.
        self.root = root
        #: root-relative path -> text for non-Python inputs pulled in by
        #: ``# jaxlint: abi-*`` directives (C headers, .cpp sources).
        self.extra_files: Dict[str, str] = dict(extra_files or {})
        self.modules: Dict[str, ModuleInfo] = {}
        self.ctx_for: Dict[str, FileContext] = {}
        for ctx in contexts:
            name = module_name_for(ctx.relpath)
            self.modules[name] = ModuleInfo(name, ctx)
            self.ctx_for[name] = ctx
        self.functions: Dict[FuncKey, FuncInfo] = {}
        self._collect_functions()
        #: function key -> resolved callee keys
        self.calls: Dict[FuncKey, Set[FuncKey]] = {}
        #: per function: locals assigned from project-class constructors,
        #: plus self-attrs assigned that way anywhere in the class
        self._instance_types: Dict[FuncKey, Dict[str, Tuple[str, str]]] = {}
        self._self_attr_types: Dict[Tuple[str, str],
                                    Dict[str, Tuple[str, str]]] = {}
        self._collect_instance_types()
        self._build_call_graph()
        self.jit_bound: Set[FuncKey] = set()
        #: attribute / top-level names bound to jit callables, per module
        self.jit_bound_names: Dict[str, Set[str]] = {}
        self._collect_jit_bindings()
        self.traced: Set[FuncKey] = self._traced_closure()

    # ------------------------------------------------------------------
    def _collect_functions(self):
        self._node_func: Dict[int, FuncInfo] = {}

        def visit(mname, body, prefix: str, class_name: Optional[str]):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    q = f"{prefix}{stmt.name}"
                    fi = FuncInfo(mname, q, stmt, class_name)
                    self.functions[(mname, q)] = fi
                    self._node_func[id(stmt)] = fi
                    visit(mname, stmt.body, f"{q}.<locals>.", class_name)
                elif isinstance(stmt, ast.ClassDef):
                    visit(mname, stmt.body, f"{stmt.name}.", stmt.name)

        for mname, mod in self.modules.items():
            visit(mname, mod.ctx.tree.body, "", None)

    def enclosing_function(self, module: str, node: ast.AST) \
            -> Optional[FuncInfo]:
        ctx = self.ctx_for.get(module)
        if ctx is None:
            return None
        chain: List[ast.AST] = [node] + list(ctx.ancestors(node))
        for n in chain:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self._node_func.get(id(n))
        return None

    # ------------------------------------------------------------------
    def resolve_module(self, module: str, alias: str) -> Optional[str]:
        """Project module a bare name refers to in ``module``, if any."""
        mod = self.modules.get(module)
        if mod is None:
            return None
        tgt = mod.imports.get(alias)
        if tgt is None:
            return None
        full, sym = tgt
        if sym is None:
            return full if full in self.modules else None
        # `from pkg import mod` where pkg.mod is a project module
        cand = f"{full}.{sym}" if full else sym
        return cand if cand in self.modules else None

    def resolve_symbol(self, module: str, name: str) \
            -> Optional[Tuple[str, str]]:
        """(defining module, symbol) for a bare name used in ``module``."""
        mod = self.modules.get(module)
        if mod is None:
            return None
        if name in mod.assigns or (module, name) in self.functions \
                or name in mod.classes:
            return (module, name)
        tgt = mod.imports.get(name)
        if tgt is not None:
            full, sym = tgt
            if sym is not None and full in self.modules:
                return (full, sym)
        return None

    def constant_value_node(self, module: str, name: str) \
            -> Optional[ast.AST]:
        """Top-level value expression of a (possibly imported) constant."""
        resolved = self.resolve_symbol(module, name)
        if resolved is None:
            return None
        dmod, sym = resolved
        return self.modules[dmod].assigns.get(sym)

    # ------------------------------------------------------------------
    def _collect_instance_types(self):
        """Map locals / self-attrs assigned from project-class calls."""
        for key, fi in self.functions.items():
            local: Dict[str, Tuple[str, str]] = {}
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.value, ast.Call)):
                    continue
                cls = self._class_of_call(fi.module, node.value)
                if cls is None:
                    continue
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    local[t.id] = cls
                elif isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" and fi.class_name:
                    self._self_attr_types.setdefault(
                        (fi.module, fi.class_name), {})[t.attr] = cls
            self._instance_types[key] = local

    def _class_of_call(self, module: str, call: ast.Call) \
            -> Optional[Tuple[str, str]]:
        d = dotted_name(call.func)
        if d is None:
            return None
        parts = d.split(".")
        if len(parts) == 1:
            r = self.resolve_symbol(module, parts[0])
            if r is not None and parts[0][:1].isupper() \
                    and r[1] in self.modules[r[0]].classes:
                return r
        elif len(parts) == 2:
            m2 = self.resolve_module(module, parts[0])
            if m2 is not None and parts[1] in self.modules[m2].classes:
                return (m2, parts[1])
        return None

    # ------------------------------------------------------------------
    def resolve_call(self, fi: FuncInfo, call: ast.Call) -> Set[FuncKey]:
        """Project functions a call site may invoke (best effort)."""
        out: Set[FuncKey] = set()
        func = call.func
        if isinstance(func, ast.Name):
            r = self.resolve_symbol(fi.module, func.id)
            if r is not None:
                if r in self.functions:
                    out.add(r)
                elif r[1] in self.modules[r[0]].classes:
                    init = (r[0], f"{r[1]}.__init__")
                    if init in self.functions:
                        out.add(init)
            # nested function defined in an enclosing scope
            for k in ((fi.module, f"{fi.qualname}.<locals>.{func.id}"),):
                if k in self.functions:
                    out.add(k)
            return out
        d = dotted_name(func)
        if d is None:
            return out
        parts = d.split(".")
        if parts[0] == "self" and fi.class_name is not None \
                and len(parts) == 2:
            k = (fi.module, f"{fi.class_name}.{parts[1]}")
            if k in self.functions:
                out.add(k)
            return out
        if parts[0] == "self" and fi.class_name is not None \
                and len(parts) == 3:
            # self.<attr>.<meth> where attr's class is known
            attrs = self._self_attr_types.get((fi.module, fi.class_name),
                                              {})
            cls = attrs.get(parts[1])
            if cls is not None:
                k = (cls[0], f"{cls[1]}.{parts[2]}")
                if k in self.functions:
                    out.add(k)
            return out
        if len(parts) == 2:
            # local var of a known project class
            cls = self._instance_types.get(fi.key, {}).get(parts[0])
            if cls is not None:
                k = (cls[0], f"{cls[1]}.{parts[1]}")
                if k in self.functions:
                    out.add(k)
                return out
            m2 = self.resolve_module(fi.module, parts[0])
            if m2 is not None:
                k = (m2, parts[1])
                if k in self.functions:
                    out.add(k)
                elif parts[1] in self.modules[m2].classes:
                    init = (m2, f"{parts[1]}.__init__")
                    if init in self.functions:
                        out.add(init)
            return out
        return out

    def _build_call_graph(self):
        for key, fi in self.functions.items():
            callees: Set[FuncKey] = set()
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    sub = self.enclosing_function(fi.module, node)
                    if sub is not None and sub.key != key:
                        continue        # belongs to a nested function
                    callees |= self.resolve_call(fi, node)
            self.calls[key] = callees

    def reachable_from(self, roots: Iterable[FuncKey]) -> Set[FuncKey]:
        seen: Set[FuncKey] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            k = stack.pop()
            if k in seen:
                continue
            seen.add(k)
            stack.extend(self.calls.get(k, ()))
        return seen

    # ------------------------------------------------------------------
    def _collect_jit_bindings(self):
        """Functions and names bound to jitted callables, plus functions
        handed to trace combinators."""
        direct: Set[FuncKey] = set()
        for mname, mod in self.modules.items():
            ctx = mod.ctx
            names = self.jit_bound_names.setdefault(mname, set())
            for node in ast.walk(ctx.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if ctx.jit_decorator_statics(dec) is not None:
                            fi = self._func_by_node(mname, node)
                            if fi is not None:
                                direct.add(fi.key)
                            names.add(node.name)
                elif isinstance(node, ast.Assign) \
                        and len(node.targets) == 1:
                    tgt = dotted_name(node.targets[0])
                    if tgt is None:
                        continue
                    for jc in self._jit_payloads(ctx, node.value):
                        names.add(tgt.rsplit(".", 1)[-1])
                        for a in ast.walk(jc):
                            r = self._callable_ref(mname, ctx, a)
                            if r is not None:
                                direct.add(r)
                elif isinstance(node, ast.Call):
                    d = dotted_name(node.func)
                    if d is not None \
                            and d.split(".")[-1] in _TRACE_COMBINATORS:
                        for arg in node.args[:2]:
                            r = self._callable_ref(mname, ctx, arg)
                            if r is not None:
                                direct.add(r)
        self.jit_bound = direct

    def _jit_payloads(self, ctx: FileContext, value: ast.AST) -> list:
        """jit-call nodes inside an assigned value (handles
        ``obs.track_jit("n", jax.jit(f))`` and plain ``jax.jit(f)``)."""
        out = []
        for n in ast.walk(value):
            if ctx.is_jit_call(n):
                out.append(n)
        d = dotted_name(value.func) if isinstance(value, ast.Call) else None
        if d is not None and d.split(".")[-1] == "track_jit" \
                and not out and len(value.args) >= 2:
            # track_jit("name", already_jitted_fn): the rebound callable
            out.append(value)
        return out

    def _callable_ref(self, module: str, ctx: FileContext,
                      node: ast.AST) -> Optional[FuncKey]:
        """FuncKey a Name/Attribute/partial argument refers to."""
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d is not None and d.split(".")[-1] == "partial" \
                    and node.args:
                return self._callable_ref(module, ctx, node.args[0])
            return None
        d = dotted_name(node)
        if d is None:
            return None
        parts = d.split(".")
        if parts[0] == "self" and len(parts) == 2:
            ctx2 = self.ctx_for.get(module)
            fi0 = self.enclosing_function(module, node) \
                if ctx2 is not None else None
            if fi0 is not None and fi0.class_name is not None:
                k = (module, f"{fi0.class_name}.{parts[1]}")
                if k in self.functions:
                    return k
            for fi in self.functions.values():
                if fi.module == module and fi.name == parts[1] \
                        and fi.class_name is not None:
                    return fi.key
            return None
        if len(parts) == 1:
            # a nested def referenced from its enclosing scope
            fi0 = self.enclosing_function(module, node)
            while fi0 is not None:
                k = (module, f"{fi0.qualname}.<locals>.{parts[0]}")
                if k in self.functions:
                    return k
                up = fi0.qualname.rsplit(".<locals>.", 1)
                fi0 = self.functions.get((module, up[0])) \
                    if len(up) == 2 else None
        r = self.resolve_symbol(module, parts[0])
        if r is None:
            return None
        if len(parts) == 1:
            return r if r in self.functions else None
        k = (r[0], ".".join([r[1]] + parts[1:])) \
            if r[1] not in self.modules else None
        return k if k in self.functions else None

    def _func_by_node(self, module: str, node: ast.AST) \
            -> Optional[FuncInfo]:
        return self._node_func.get(id(node))

    def _traced_closure(self) -> Set[FuncKey]:
        """jit-bound / combinator-fed functions, their nested defs, and
        everything they (transitively) call."""
        roots: Set[FuncKey] = set(self.jit_bound)
        # nested defs inside a traced function body are traced too
        for key in list(roots):
            prefix = key[1] + ".<locals>."
            for k2 in self.functions:
                if k2[0] == key[0] and k2[1].startswith(prefix):
                    roots.add(k2)
        return self.reachable_from(roots)

    def is_traced_node(self, module: str, node: ast.AST) -> bool:
        fi = self.enclosing_function(module, node)
        return fi is not None and fi.key in self.traced

    # ------------------------------------------------------------------
    def own_nodes(self, fi: FuncInfo) -> List[ast.AST]:
        """Nodes lexically inside ``fi`` but NOT inside a nested
        function — each function's own scope, computed once per module
        with a single DFS (the taint fixpoint re-reads these a lot)."""
        if not hasattr(self, "_scope_nodes"):
            self._scope_nodes: Dict[FuncKey, List[ast.AST]] = {}
            self._module_nodes: Dict[str, List[ast.AST]] = {}
            for mname, mod in self.modules.items():
                top: List[ast.AST] = []
                self._module_nodes[mname] = top

                def dfs(node, owner_key):
                    for child in ast.iter_child_nodes(node):
                        child_owner = owner_key
                        if isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)):
                            fi2 = self._node_func.get(id(child))
                            child_owner = fi2.key if fi2 is not None \
                                else owner_key
                        if child_owner is None:
                            top.append(child)
                        else:
                            self._scope_nodes.setdefault(
                                child_owner, []).append(child)
                        dfs(child, child_owner)
                dfs(mod.ctx.tree, None)
        return self._scope_nodes.get(fi.key, [])

    def module_level_nodes(self, module: str) -> List[ast.AST]:
        """Nodes outside any function in ``module`` (class bodies
        included)."""
        if not hasattr(self, "_module_nodes"):
            for fi in self.functions.values():
                self.own_nodes(fi)
                break
            if not hasattr(self, "_module_nodes"):
                self._module_nodes = {}
                for mname, mod in self.modules.items():
                    self._module_nodes[mname] = [
                        n for n in ast.walk(mod.ctx.tree)
                        if self.enclosing_function(mname, n) is None]
        return self._module_nodes.get(module, [])
