"""jaxlint driver: walk files, run rules, apply inline suppressions.

Pure static analysis — files are parsed with :mod:`ast`, never
imported, so the analyzer is fast and safe to run on code whose
dependencies are absent.  Two rule tiers run here:

* per-file rules (JL0xx) see one :class:`FileContext` at a time and
  are cached per file-content hash;
* project rules (JL1xx) see the whole-repo
  :class:`~.project.ProjectContext` (symbol table, import/call graph)
  and are cached against the tree hash — any content change re-runs
  them, because a cross-module finding can move between files.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from .cache import (LintCache, extra_input_hashes, file_sha,
                    scan_extra_inputs, tree_sha)
from .context import FileContext, Finding
from .rules import FILE_RULES, PROJECT_RULES

EXCLUDED_DIRS = {"__pycache__", ".git", ".venv", "build", "dist",
                 ".jaxlint_cache"}


class AnalysisResult:
    """Findings plus bookkeeping from one analyzer run."""

    __slots__ = ("findings", "suppressed", "files_scanned", "errors",
                 "cache_hits", "cache_misses", "from_cache")

    def __init__(self):
        self.findings: List[Finding] = []
        self.suppressed: List[Finding] = []
        self.files_scanned: int = 0
        self.errors: List[Tuple[str, str]] = []   # (path, message)
        self.cache_hits: int = 0
        self.cache_misses: int = 0
        self.from_cache: bool = False


def iter_python_files(paths: Sequence[str]) -> Iterable[Path]:
    for raw in paths:
        p = Path(raw)
        if p.is_file():
            if p.suffix == ".py":
                yield p
        elif p.is_dir():
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in EXCLUDED_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield Path(dirpath) / fn


def _run_file_rules(ctx: FileContext, select: Optional[Set[str]],
                    findings: List[Finding],
                    suppressed: List[Finding]) -> None:
    for code, rule in FILE_RULES.items():
        if select is not None and code not in select:
            continue
        for finding in rule.check(ctx):
            if ctx.is_suppressed(finding.rule, finding.line):
                suppressed.append(finding)
            else:
                findings.append(finding)


def _run_project_rules(contexts: Sequence[FileContext],
                       select: Optional[Set[str]],
                       findings: List[Finding],
                       suppressed: List[Finding],
                       root: Optional[str] = None,
                       extra_files=None) -> None:
    from .project import ProjectContext
    if not any(select is None or code in select
               for code in PROJECT_RULES):
        return
    project = ProjectContext(contexts, root=root, extra_files=extra_files)
    ctx_by_path = {c.relpath: c for c in contexts}
    for code, rule in PROJECT_RULES.items():
        if select is not None and code not in select:
            continue
        for finding in rule.check_project(project):
            ctx = ctx_by_path.get(finding.path)
            if ctx is not None \
                    and ctx.is_suppressed(finding.rule, finding.line):
                suppressed.append(finding)
            else:
                findings.append(finding)


def analyze_source(src: str, relpath: str,
                   select: Optional[Set[str]] = None,
                   result: Optional[AnalysisResult] = None,
                   project_rules: bool = True) \
        -> AnalysisResult:
    """Run all (or ``select``ed) rules over one source string.  Project
    rules see a single-file project (their intra-module checks still
    apply)."""
    result = result if result is not None else AnalysisResult()
    try:
        ctx = FileContext(src, relpath)
    except SyntaxError as e:
        result.errors.append((relpath, f"syntax error: {e.msg} "
                              f"(line {e.lineno})"))
        return result
    result.files_scanned += 1
    _run_file_rules(ctx, select, result.findings, result.suppressed)
    if project_rules:
        _run_project_rules([ctx], select, result.findings,
                           result.suppressed)
    result.findings.sort(key=Finding.sort_key)
    return result


def analyze_paths(paths: Sequence[str], root: Optional[str] = None,
                  select: Optional[Set[str]] = None,
                  cache_dir: Optional[str] = None) -> AnalysisResult:
    """Analyze every ``.py`` file under ``paths``.  Finding paths are
    reported relative to ``root`` (default: cwd) when possible, so the
    baseline is position-independent.  With ``cache_dir``, unchanged
    files (per-file rules) and an unchanged tree (project rules) replay
    cached findings without re-parsing; ``--select`` runs filter the
    cached full-run results and never write."""
    rootp = Path(root) if root is not None else Path.cwd()
    result = AnalysisResult()

    sources: List[Tuple[str, str]] = []          # (relpath, src)
    for path in iter_python_files(paths):
        try:
            rel = path.resolve().relative_to(rootp.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        try:
            src = path.read_text(encoding="utf-8")
        except OSError as e:
            result.errors.append((rel, str(e)))
            continue
        sources.append((rel, src))

    cache = LintCache(cache_dir) if cache_dir is not None else None
    hashes = [(rel, file_sha(src)) for rel, src in sources]
    # non-Python inputs named by abi-* directives (C header / .cpp)
    # content-hash into the tree key: a header edit invalidates the
    # project tier even though no .py file changed
    extra = scan_extra_inputs(sources, rootp)
    tree = tree_sha(hashes + extra_input_hashes(extra))

    def keep(fs: Iterable[Finding]) -> List[Finding]:
        if select is None:
            return list(fs)
        return [f for f in fs if f.rule in select]

    contexts: List[FileContext] = []
    need_project = any(select is None or code in select
                       for code in PROJECT_RULES)
    project_cached = None
    if cache is not None and need_project:
        project_cached = cache.lookup_project(tree)
    parse_all = need_project and project_cached is None

    all_cached = True
    for (rel, src), (_, sha) in zip(sources, hashes):
        cached = cache.lookup_file(rel, sha) if cache is not None else None
        if cached is not None and not parse_all:
            result.files_scanned += 1
            result.findings.extend(keep(cached[0]))
            result.suppressed.extend(keep(cached[1]))
            continue
        try:
            ctx = FileContext(src, rel)
        except SyntaxError as e:
            result.errors.append((rel, f"syntax error: {e.msg} "
                                  f"(line {e.lineno})"))
            all_cached = False
            continue
        contexts.append(ctx)
        result.files_scanned += 1
        if cached is not None:
            # file unchanged but the tree changed: replay the per-file
            # findings, keep the context for the project rules
            result.findings.extend(keep(cached[0]))
            result.suppressed.extend(keep(cached[1]))
            continue
        all_cached = False
        f_new: List[Finding] = []
        s_new: List[Finding] = []
        # a --select run never writes the cache, so there is no reason
        # to pay for the unselected rules on a miss
        _run_file_rules(ctx, select, f_new, s_new)
        if cache is not None and select is None:
            # cache the FULL per-file result so later --select runs
            # can filter it
            cache.store_file(rel, sha, f_new, s_new)
        result.findings.extend(keep(f_new))
        result.suppressed.extend(keep(s_new))

    if need_project:
        if project_cached is not None:
            result.findings.extend(keep(project_cached[0]))
            result.suppressed.extend(keep(project_cached[1]))
        else:
            pf: List[Finding] = []
            ps: List[Finding] = []
            _run_project_rules(
                contexts, select, pf, ps, root=str(rootp),
                extra_files={k: v for k, v in extra.items()
                             if v is not None})
            if cache is not None and select is None \
                    and not result.errors:
                cache.store_project(tree, pf, ps)
            result.findings.extend(keep(pf))
            result.suppressed.extend(keep(ps))

    if cache is not None:
        result.cache_hits = cache.hits
        result.cache_misses = cache.misses
        result.from_cache = all_cached and (project_cached is not None
                                            or not need_project)
        if select is None and not result.errors:
            # carry over untouched entries so a partial-path run does
            # not evict other files — but drop entries whose file no
            # longer exists, or deletions/renames would accumulate in
            # cache.json forever
            dirty = bool(cache.files) or cache.project is not None
            for rel, entry in cache._old.get("files", {}).items():
                if rel in cache.files:
                    continue
                if (rootp / rel).is_file():
                    cache.files[rel] = entry
                else:
                    dirty = True
            if cache.project is None:
                cache.project = cache._old.get("project")
            if dirty:
                # a fully-warm run changed nothing: stay read-only
                cache.write()

    result.findings.sort(key=Finding.sort_key)
    result.suppressed.sort(key=Finding.sort_key)
    return result
