"""jaxlint driver: walk files, run rules, apply inline suppressions.

Pure static analysis — files are parsed with :mod:`ast`, never imported,
so the analyzer is fast (~60 files in well under a second) and safe to
run on code whose dependencies are absent.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from .context import FileContext, Finding
from .rules import RULES

EXCLUDED_DIRS = {"__pycache__", ".git", ".venv", "build", "dist"}


class AnalysisResult:
    """Findings plus bookkeeping from one analyzer run."""

    __slots__ = ("findings", "suppressed", "files_scanned", "errors")

    def __init__(self):
        self.findings: List[Finding] = []
        self.suppressed: List[Finding] = []
        self.files_scanned: int = 0
        self.errors: List[Tuple[str, str]] = []   # (path, message)


def iter_python_files(paths: Sequence[str]) -> Iterable[Path]:
    for raw in paths:
        p = Path(raw)
        if p.is_file():
            if p.suffix == ".py":
                yield p
        elif p.is_dir():
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in EXCLUDED_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield Path(dirpath) / fn


def analyze_source(src: str, relpath: str,
                   select: Optional[Set[str]] = None,
                   result: Optional[AnalysisResult] = None) \
        -> AnalysisResult:
    """Run all (or ``select``ed) rules over one source string."""
    result = result if result is not None else AnalysisResult()
    try:
        ctx = FileContext(src, relpath)
    except SyntaxError as e:
        result.errors.append((relpath, f"syntax error: {e.msg} "
                              f"(line {e.lineno})"))
        return result
    result.files_scanned += 1
    for code, rule in RULES.items():
        if select is not None and code not in select:
            continue
        for finding in rule.check(ctx):
            if ctx.is_suppressed(finding.rule, finding.line):
                result.suppressed.append(finding)
            else:
                result.findings.append(finding)
    result.findings.sort(key=Finding.sort_key)
    return result


def analyze_paths(paths: Sequence[str], root: Optional[str] = None,
                  select: Optional[Set[str]] = None) -> AnalysisResult:
    """Analyze every ``.py`` file under ``paths``.  Finding paths are
    reported relative to ``root`` (default: cwd) when possible, so the
    baseline is position-independent."""
    rootp = Path(root) if root is not None else Path.cwd()
    result = AnalysisResult()
    for path in iter_python_files(paths):
        try:
            rel = path.resolve().relative_to(rootp.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        try:
            src = path.read_text(encoding="utf-8")
        except OSError as e:
            result.errors.append((rel, str(e)))
            continue
        analyze_source(src, rel, select=select, result=result)
    result.findings.sort(key=Finding.sort_key)
    return result
