"""jaxlint command line: ``python -m lightgbm_tpu.tools.jaxlint [paths]``.

Exit codes: 0 clean (every finding baselined or none), 1 new findings,
2 usage/parse errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter
from typing import List, Optional

from . import baseline as baseline_mod
from .core import analyze_paths
from .rules import RULE_DOCS, RULE_EXPLAIN


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="jaxlint",
        description="Repo-aware static analysis for host-sync, recompile, "
                    "dtype, trace-key, lock-discipline and determinism "
                    "hazards in JAX code.")
    p.add_argument("paths", nargs="*", default=["lightgbm_tpu"],
                   help="files/directories to analyze "
                        "(default: lightgbm_tpu)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline JSON of accepted findings (default: "
                        f"./{baseline_mod.DEFAULT_BASELINE} when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline; report every finding")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings as the baseline and "
                        "exit 0 (with --select, entries of unselected "
                        "rules are preserved from the existing baseline)")
    p.add_argument("--select", default=None, metavar="CODES",
                   help="comma-separated rule codes to run "
                        "(e.g. JL001,JL005); the baseline is filtered "
                        "to the selected rules")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--statistics", action="store_true",
                   help="print per-rule counts")
    p.add_argument("--list-rules", action="store_true",
                   help="list rule codes and exit")
    p.add_argument("--explain", default=None, metavar="RULE",
                   help="print a rule's full documentation and exit")
    p.add_argument("--root", default=None, metavar="DIR",
                   help="directory finding paths are reported relative "
                        "to (default: cwd)")
    p.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="incremental cache directory (content-hash "
                        "keyed; unchanged files/tree replay without "
                        "re-analysis).  CI uses .jaxlint_cache")
    p.add_argument("--no-cache", action="store_true",
                   help="ignore --cache-dir; always analyze cold")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for code, doc in RULE_DOCS.items():
            print(f"{code}  {doc}")
        return 0

    if args.explain:
        code = args.explain.strip().upper()
        doc = RULE_EXPLAIN.get(code)
        if doc is None:
            print(f"jaxlint: unknown rule {code!r} (see --list-rules)",
                  file=sys.stderr)
            return 2
        print(f"{code} — {RULE_DOCS[code]}\n\n{doc}")
        return 0

    select = None
    if args.select:
        select = {c.strip().upper() for c in args.select.split(",")}
        unknown = select - set(RULE_DOCS)
        if unknown:
            print(f"jaxlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    cache_dir = None if args.no_cache else args.cache_dir
    result = analyze_paths(args.paths, root=args.root, select=select,
                           cache_dir=cache_dir)
    if result.errors:
        for path, msg in result.errors:
            print(f"{path}: error: {msg}", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        default = baseline_mod.DEFAULT_BASELINE
        if args.root is not None:
            default = os.path.join(args.root, default)
        if os.path.exists(default):
            baseline_path = default

    if args.write_baseline:
        out = baseline_path or (
            os.path.join(args.root, baseline_mod.DEFAULT_BASELINE)
            if args.root else baseline_mod.DEFAULT_BASELINE)
        preserved = {}
        if select is not None and os.path.exists(out):
            # a rule-filtered run only holds the selected findings:
            # carry every other rule's accepted entries over unchanged
            # instead of silently erasing them
            try:
                loaded = baseline_mod.load(out)
            except (OSError, ValueError, KeyError) as e:
                print(f"jaxlint: cannot read baseline {out}: {e}",
                      file=sys.stderr)
                return 2
            preserved = {k: n for k, n in loaded.items()
                         if k[1] not in select}
        baseline_mod.write(out, result.findings, extra=preserved)
        kept = f" (+{sum(preserved.values())} preserved)" \
            if preserved else ""
        print(f"jaxlint: wrote {len(result.findings)} finding(s){kept} "
              f"to {out}")
        return 0

    accepted = {}
    if baseline_path and not args.no_baseline:
        try:
            accepted = baseline_mod.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"jaxlint: cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        if select is not None:
            # a filtered run must only be judged against the selected
            # rules' entries — the others would all read as stale
            accepted = {k: n for k, n in accepted.items()
                        if k[1] in select}
    new, stale = baseline_mod.apply(result.findings, accepted)

    if args.format == "json":
        print(json.dumps({
            "files_scanned": result.files_scanned,
            "total": len(result.findings),
            "new": [f.to_dict() for f in new],
            "baselined": len(result.findings) - len(new),
            "suppressed": len(result.suppressed),
            "cache": {"hits": result.cache_hits,
                      "misses": result.cache_misses,
                      "warm": result.from_cache},
            "stale_baseline_entries": [
                {"file": k[0], "rule": k[1], "snippet": k[2], "count": n}
                for k, n in stale],
        }, indent=1))
    else:
        for f in new:
            print(f"{f.path}:{f.line}:{f.col + 1}: {f.rule} {f.message}")
        if args.statistics and result.findings:
            counts = Counter(f.rule for f in result.findings)
            for code in sorted(counts):
                print(f"{code}: {counts[code]} total")
        summary = (f"jaxlint: {result.files_scanned} file(s), "
                   f"{len(result.findings)} finding(s): {len(new)} new, "
                   f"{len(result.findings) - len(new)} baselined, "
                   f"{len(result.suppressed)} suppressed")
        if cache_dir is not None:
            summary += (" [cache: warm]" if result.from_cache else
                        f" [cache: {result.cache_hits} hit(s), "
                        f"{result.cache_misses} miss(es)]")
        if stale:
            summary += (f"; {sum(n for _, n in stale)} stale baseline "
                        "entr(ies) — regenerate with --write-baseline")
        print(summary)

    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
