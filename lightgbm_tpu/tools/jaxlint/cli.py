"""jaxlint command line: ``python -m lightgbm_tpu.tools.jaxlint [paths]``.

Exit codes: 0 clean (every finding baselined or none), 1 new findings,
2 usage/parse errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter
from typing import List, Optional

from . import baseline as baseline_mod
from .core import analyze_paths
from .rules import RULE_DOCS


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="jaxlint",
        description="Repo-aware static analysis for host-sync, recompile "
                    "and dtype hazards in JAX code.")
    p.add_argument("paths", nargs="*", default=["lightgbm_tpu"],
                   help="files/directories to analyze "
                        "(default: lightgbm_tpu)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline JSON of accepted findings (default: "
                        f"./{baseline_mod.DEFAULT_BASELINE} when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline; report every finding")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings as the baseline and "
                        "exit 0")
    p.add_argument("--select", default=None, metavar="CODES",
                   help="comma-separated rule codes to run "
                        "(e.g. JL001,JL005)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--statistics", action="store_true",
                   help="print per-rule counts")
    p.add_argument("--list-rules", action="store_true",
                   help="list rule codes and exit")
    p.add_argument("--root", default=None, metavar="DIR",
                   help="directory finding paths are reported relative "
                        "to (default: cwd)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for code, doc in RULE_DOCS.items():
            print(f"{code}  {doc}")
        return 0

    select = None
    if args.select:
        select = {c.strip().upper() for c in args.select.split(",")}
        unknown = select - set(RULE_DOCS)
        if unknown:
            print(f"jaxlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    result = analyze_paths(args.paths, root=args.root, select=select)
    if result.errors:
        for path, msg in result.errors:
            print(f"{path}: error: {msg}", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        default = baseline_mod.DEFAULT_BASELINE
        if args.root is not None:
            default = os.path.join(args.root, default)
        if os.path.exists(default):
            baseline_path = default

    if args.write_baseline:
        if select is not None:
            # a rule-filtered run only holds the selected findings;
            # writing it would silently drop every other accepted entry
            print("jaxlint: --write-baseline cannot be combined with "
                  "--select (it would erase the other rules' baseline "
                  "entries); run without --select", file=sys.stderr)
            return 2
        out = baseline_path or (
            os.path.join(args.root, baseline_mod.DEFAULT_BASELINE)
            if args.root else baseline_mod.DEFAULT_BASELINE)
        baseline_mod.write(out, result.findings)
        print(f"jaxlint: wrote {len(result.findings)} finding(s) to {out}")
        return 0

    accepted = {}
    if baseline_path and not args.no_baseline:
        try:
            accepted = baseline_mod.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"jaxlint: cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    new, stale = baseline_mod.apply(result.findings, accepted)

    if args.format == "json":
        print(json.dumps({
            "files_scanned": result.files_scanned,
            "total": len(result.findings),
            "new": [f.to_dict() for f in new],
            "baselined": len(result.findings) - len(new),
            "suppressed": len(result.suppressed),
            "stale_baseline_entries": [
                {"file": k[0], "rule": k[1], "snippet": k[2], "count": n}
                for k, n in stale],
        }, indent=1))
    else:
        for f in new:
            print(f"{f.path}:{f.line}:{f.col + 1}: {f.rule} {f.message}")
        if args.statistics and result.findings:
            counts = Counter(f.rule for f in result.findings)
            for code in sorted(counts):
                print(f"{code}: {counts[code]} total")
        summary = (f"jaxlint: {result.files_scanned} file(s), "
                   f"{len(result.findings)} finding(s): {len(new)} new, "
                   f"{len(result.findings) - len(new)} baselined, "
                   f"{len(result.suppressed)} suppressed")
        if stale:
            summary += (f"; {sum(n for _, n in stale)} stale baseline "
                        "entr(ies) — regenerate with --write-baseline")
        print(summary)

    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
