"""Shared per-file analysis context for the jaxlint rules.

One :class:`FileContext` is built per analyzed file: the parsed AST with
parent links, the import alias tables (``numpy``/``jax``/``jax.numpy``
under whatever names the module bound them to), per-scope "device name"
dataflow (names assigned from ``jnp.``/``jax.``-rooted expressions),
loop-nesting queries with comprehension-aware semantics, inline
suppression comments, and the hot-path classification that scopes JL001.

The context is pure ``ast`` — no imports of the analyzed code are ever
executed, so the analyzer runs on files with unimportable dependencies
and never pays jax start-up cost per file.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: modules whose loops are the retrain-every-window hot path (PAPER.md's
#: LRB harness drives these once per window); JL001 only fires here.  A
#: module can opt in from outside this list with a ``# jaxlint: hot-path``
#: marker comment anywhere in the file.
HOT_PATH_SUFFIXES = (
    "lightgbm_tpu/boosting/gbdt.py",
    "lightgbm_tpu/tree/learner.py",
    "lightgbm_tpu/engine.py",
    "lightgbm_tpu/capi_embed.py",
)

HOT_MARKER = "jaxlint: hot-path"

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*(disable|disable-next)\s*=\s*"
    r"(all|[A-Za-z]{2}\d{3}(?:\s*,\s*[A-Za-z]{2}\d{3})*)")

_COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Module)


class Finding:
    """One rule violation at a source location."""

    __slots__ = ("rule", "path", "line", "col", "message", "snippet")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, snippet: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.snippet = snippet

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "file": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet}

    def __repr__(self):
        return (f"Finding({self.rule} {self.path}:{self.line}:{self.col} "
                f"{self.message!r})")


def normalize_snippet(line: str, width: int = 200) -> str:
    """Whitespace-collapsed source line: the line-number-independent
    baseline key, stable across pure line moves."""
    return " ".join(line.split())[:width]


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def chain_root(node: ast.AST) -> Optional[str]:
    """Base Name id of a Call/Attribute/Subscript chain, else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


class FileContext:
    """Everything the rule modules need to know about one source file."""

    def __init__(self, src: str, relpath: str):
        self.src = src
        self.relpath = relpath.replace("\\", "/")
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=relpath)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.numpy_aliases: Set[str] = set()
        self.jax_aliases: Set[str] = set()
        self.jnp_aliases: Set[str] = set()
        self.partial_names: Set[str] = set()    # functools.partial bindings
        self.jit_names: Set[str] = set()        # `from jax import jit` names
        self._collect_imports()
        self.is_hot = (HOT_MARKER in src
                       or any(self.relpath.endswith(s)
                              for s in HOT_PATH_SUFFIXES))
        self.suppressions: Dict[int, Set[str]] = {}
        self._collect_suppressions()
        self._device_cache: Dict[int, Set[str]] = {}
        self._set_cache: Dict[int, Set[str]] = {}

    # ------------------------------------------------------------------
    def _collect_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    bound = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        self.numpy_aliases.add(bound)
                    elif a.name == "jax.numpy" and a.asname:
                        self.jnp_aliases.add(bound)
                    elif a.name.split(".")[0] == "jax":
                        self.jax_aliases.add(bound)
                    elif a.name == "functools":
                        self.partial_names.add(f"{bound}.partial")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "numpy":
                            self.jnp_aliases.add(a.asname or "numpy")
                        elif a.name == "jit":
                            self.jit_names.add(a.asname or "jit")
                elif node.module == "functools":
                    for a in node.names:
                        if a.name == "partial":
                            self.partial_names.add(a.asname or "partial")

    def _collect_suppressions(self):
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            codes = {c.strip().upper() for c in m.group(2).split(",")}
            target = i if m.group(1) == "disable" else i + 1
            self.suppressions.setdefault(target, set()).update(codes)

    def is_suppressed(self, rule: str, line: int) -> bool:
        codes = self.suppressions.get(line)
        return bool(codes) and (rule in codes or "ALL" in codes)

    # ------------------------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        p = self.parents.get(node)
        while p is not None:
            yield p
            p = self.parents.get(p)

    def is_ancestor(self, maybe_ancestor: ast.AST, node: ast.AST) -> bool:
        return any(a is maybe_ancestor for a in self.ancestors(node))

    def enclosing_scope(self, node: ast.AST) -> ast.AST:
        for a in self.ancestors(node):
            if isinstance(a, _SCOPES):
                return a
        return self.tree

    def loop_depth(self, node: ast.AST) -> int:
        """Number of enclosing loops whose BODY re-evaluates ``node``
        each iteration, up to the nearest function boundary.  A ``for``
        statement's iterable and a comprehension's FIRST source iterable
        are evaluated once, so they don't count."""
        depth = 0
        child = node
        for p in self.ancestors(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                break
            if isinstance(p, ast.For):
                if child is not p.iter:
                    depth += 1
            elif isinstance(p, ast.While):
                depth += 1
            elif isinstance(p, _COMPREHENSIONS):
                if not (p.generators and child is p.generators[0].iter):
                    depth += 1
            child = p
        return depth

    # ------------------------------------------------------------------
    def rooted_in(self, node: ast.AST, roots: Set[str]) -> bool:
        r = chain_root(node)
        return r is not None and r in roots

    def device_names(self, node: ast.AST) -> Set[str]:
        """Names in ``node``'s scope assigned from ``jnp.``/``jax.``-rooted
        expressions — a cheap local dataflow for "this is (probably) a
        device array"."""
        scope = self.enclosing_scope(node)
        cached = self._device_cache.get(id(scope))
        if cached is not None:
            return cached
        roots = self.jnp_aliases | self.jax_aliases
        names: Set[str] = set()
        for n in ast.walk(scope):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and self.rooted_in(n.value, roots):
                names.add(n.targets[0].id)
        self._device_cache[id(scope)] = names
        return names

    def set_names(self, node: ast.AST) -> Set[str]:
        """Names in ``node``'s scope assigned from set expressions."""
        scope = self.enclosing_scope(node)
        cached = self._set_cache.get(id(scope))
        if cached is not None:
            return cached
        names: Set[str] = set()
        for n in ast.walk(scope):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                v = n.value
                if isinstance(v, (ast.Set, ast.SetComp)) or (
                        isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Name)
                        and v.func.id in ("set", "frozenset")):
                    names.add(n.targets[0].id)
        self._set_cache[id(scope)] = names
        return names

    # ------------------------------------------------------------------
    def is_jit_expr(self, node: ast.AST) -> bool:
        """``jax.jit`` (or an imported alias of it) as an expression."""
        if isinstance(node, ast.Name):
            return node.id in self.jit_names
        d = dotted_name(node)
        return d is not None and any(d == f"{j}.jit"
                                     for j in self.jax_aliases)

    def is_jit_call(self, node: ast.AST) -> bool:
        """``jax.jit(...)`` call expression."""
        return isinstance(node, ast.Call) and self.is_jit_expr(node.func)

    def jit_decorator_statics(
            self, dec: ast.AST) -> Optional[Tuple[Set[int], Set[str]]]:
        """(static_argnums, static_argnames) when ``dec`` is a jit-family
        decorator: ``@jax.jit``, ``@jax.jit(...)`` or
        ``@functools.partial(jax.jit, ...)``; None otherwise."""
        if self.is_jit_expr(dec):
            return set(), set()
        if not isinstance(dec, ast.Call):
            return None
        if self.is_jit_expr(dec.func):
            return self._parse_statics(dec.keywords)
        d = dotted_name(dec.func)
        if d in self.partial_names and dec.args \
                and self.is_jit_expr(dec.args[0]):
            return self._parse_statics(dec.keywords)
        return None

    @staticmethod
    def _parse_statics(keywords) -> Tuple[Set[int], Set[str]]:
        nums: Set[int] = set()
        names: Set[str] = set()
        for kw in keywords:
            if kw.arg == "static_argnums":
                nums |= set(_literal_ints(kw.value))
            elif kw.arg == "static_argnames":
                names |= set(_literal_strs(kw.value))
        return nums, names

    # ------------------------------------------------------------------
    def make_finding(self, rule: str, node: ast.AST, message: str) \
            -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = normalize_snippet(self.lines[line - 1]) \
            if 0 < line <= len(self.lines) else ""
        return Finding(rule, self.relpath, line, col, message, snippet)


def _literal_ints(node) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_literal_ints(e))
        return out
    return []


def _literal_strs(node) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_literal_strs(e))
        return out
    return []
