"""On-disk incremental cache: skip re-analysis of unchanged files.

``check.sh`` runs jaxlint on every push; with the JL1xx project rules
the cold analysis parses the whole package and builds the symbol/call
graphs.  The cache makes the common case — nothing changed — nearly
free, keyed so it can never serve stale results:

* ``tool_hash``: sha256 over every source file of the jaxlint package
  itself.  Editing any rule invalidates everything.
* per-file entries keyed by the file's content sha256: findings of the
  per-file (JL0xx) rules, replayable without re-parsing.
* one project entry keyed by the *tree hash* (sha256 over the sorted
  (relpath, file sha) list): findings of the cross-module JL1xx rules.
  Any content change re-runs the project rules — their findings can
  legitimately move between files, so per-file reuse would be unsound.

The cache file lives under ``.jaxlint_cache/cache.json`` and is written
atomically (temp + rename); a corrupt/missing/mismatched cache means a
cold run, never an error.  ``--select`` runs may *read* (findings are
filtered per rule afterwards) but never write, so a filtered run can't
poison the full-run cache.
"""

from __future__ import annotations

import hashlib
import json
import os
import posixpath
import re
from typing import Dict, List, Optional, Sequence, Tuple

from .context import Finding

CACHE_VERSION = 2
CACHE_FILENAME = "cache.json"
DEFAULT_CACHE_DIR = ".jaxlint_cache"


def file_sha(src: str) -> str:
    return hashlib.sha256(src.encode("utf-8", "replace")).hexdigest()


def tree_sha(file_hashes: Sequence[Tuple[str, str]]) -> str:
    h = hashlib.sha256()
    for rel, sha in sorted(file_hashes):
        h.update(rel.encode())
        h.update(b"\0")
        h.update(sha.encode())
        h.update(b"\n")
    return h.hexdigest()


#: ``# jaxlint: abi-header=...`` / ``abi-impl=...`` directives name
#: non-Python inputs (C header / .cpp) that project rules read.  Paths
#: are relative to the *directive-carrying file*, so a fixture corpus
#: copied elsewhere keeps resolving its own sibling header.
EXTRA_INPUT_DIRECTIVE_RE = re.compile(
    r"#\s*jaxlint:\s*abi-(?:header|impl)\s*=\s*(\S+)")


def resolve_extra_path(relpath: str, target: str) -> str:
    """Normalize a directive ``target`` against its declaring file."""
    return posixpath.normpath(
        posixpath.join(posixpath.dirname(relpath.replace("\\", "/")),
                       target))


def scan_extra_inputs(sources: Sequence[Tuple[str, str]],
                      root) -> Dict[str, Optional[str]]:
    """Collect ``abi-*`` directive targets from ``(relpath, src)`` pairs.

    Returns normalized-relpath -> file text, or ``None`` when the
    target is missing/unreadable (the rules then stay silent for it,
    but the sentinel still feeds the tree hash so creating the file
    later invalidates the project cache).
    """
    out: Dict[str, Optional[str]] = {}
    for rel, src in sources:
        for m in EXTRA_INPUT_DIRECTIVE_RE.finditer(src):
            key = resolve_extra_path(rel, m.group(1))
            if key in out:
                continue
            path = (key if os.path.isabs(key)
                    else os.path.join(str(root), key))
            try:
                with open(path, encoding="utf-8") as fh:
                    out[key] = fh.read()
            except OSError:
                out[key] = None
    return out


def extra_input_hashes(extra: Dict[str, Optional[str]]) \
        -> List[Tuple[str, str]]:
    """Hash pairs for the tree key: C inputs invalidate like sources."""
    return [("extra::" + rel,
             file_sha(text) if text is not None else "<missing>")
            for rel, text in extra.items()]


def tool_hash() -> str:
    """sha256 of the analyzer's own sources (this package)."""
    root = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as fh:
                h.update(hashlib.sha256(fh.read()).digest())
    return h.hexdigest()


def _finding_to_dict(f: Finding) -> Dict:
    return f.to_dict()


def _finding_from_dict(d: Dict) -> Finding:
    return Finding(d["rule"], d["file"], int(d["line"]), int(d["col"]),
                   d["message"], d["snippet"])


class LintCache:
    """Loaded cache state plus the entries for the next write."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, CACHE_FILENAME)
        self._tool = tool_hash()
        self._old: Dict = {}
        self.files: Dict[str, Dict] = {}
        self.project: Optional[Dict] = None
        self.hits = 0
        self.misses = 0
        try:
            with open(self.path) as fh:
                doc = json.load(fh)
            if doc.get("version") == CACHE_VERSION \
                    and doc.get("tool_hash") == self._tool:
                self._old = doc
        except (OSError, ValueError):
            self._old = {}

    # -- per-file (JL0xx) ------------------------------------------------
    def lookup_file(self, rel: str, sha: str) \
            -> Optional[Tuple[List[Finding], List[Finding]]]:
        e = self._old.get("files", {}).get(rel)
        if e is None or e.get("sha") != sha:
            self.misses += 1
            return None
        self.hits += 1
        return ([_finding_from_dict(d) for d in e.get("findings", [])],
                [_finding_from_dict(d) for d in e.get("suppressed", [])])

    def store_file(self, rel: str, sha: str, findings: List[Finding],
                   suppressed: List[Finding]) -> None:
        self.files[rel] = {
            "sha": sha,
            "findings": [_finding_to_dict(f) for f in findings],
            "suppressed": [_finding_to_dict(f) for f in suppressed],
        }

    # -- project (JL1xx) -------------------------------------------------
    def lookup_project(self, tree: str) \
            -> Optional[Tuple[List[Finding], List[Finding]]]:
        e = self._old.get("project")
        if not e or e.get("tree_sha") != tree:
            return None
        return ([_finding_from_dict(d) for d in e.get("findings", [])],
                [_finding_from_dict(d) for d in e.get("suppressed", [])])

    def store_project(self, tree: str, findings: List[Finding],
                      suppressed: List[Finding]) -> None:
        self.project = {
            "tree_sha": tree,
            "findings": [_finding_to_dict(f) for f in findings],
            "suppressed": [_finding_to_dict(f) for f in suppressed],
        }

    # --------------------------------------------------------------------
    def write(self) -> None:
        doc = {"version": CACHE_VERSION, "tool": "jaxlint",
               "tool_hash": self._tool, "files": self.files,
               "project": self.project}
        os.makedirs(self.directory, exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, self.path)
