"""jaxlint: repo-aware static analysis for JAX performance hazards.

Rules (docs/StaticAnalysis.md has bad/good examples for each):

Per-file rules:

- **JL001** host-device sync inside hot-path loops
- **JL002** recompile hazards around ``jax.jit``
- **JL003** jitted callables not registered with ``obs.track_jit``
- **JL004** float64 flowing into device code while x64 is disabled
- **JL005** set iteration order leaking into output
- **JL006** unguarded mutation of module-level state

Cross-module dataflow rules (whole-repo symbol table + call graph,
``project.py``):

- **JL101** trace-key completeness around ``programs_signature``
- **JL111** int8 quantization dtype-contract flow
- **JL121** lock-order inversions and thread-shared state
- **JL131** determinism taint into model/checkpoint/digest bytes

CLI: ``python -m lightgbm_tpu.tools.jaxlint [paths] [--baseline ...]``.
Inline suppression: ``# jaxlint: disable=JL001`` (same line) or
``# jaxlint: disable-next=JL001`` (next line).  Pre-existing findings
live in the committed ``jaxlint_baseline.json``; new ones fail CI
(``scripts/check.sh``, ``tests/test_jaxlint.py``).
"""

from .baseline import DEFAULT_BASELINE, apply, dump, finding_key, load, write
from .context import FileContext, Finding
from .core import AnalysisResult, analyze_paths, analyze_source
from .rules import RULE_DOCS, RULES

__all__ = [
    "AnalysisResult", "DEFAULT_BASELINE", "FileContext", "Finding",
    "RULES", "RULE_DOCS", "analyze_paths", "analyze_source", "apply",
    "dump", "finding_key", "load", "write",
]
