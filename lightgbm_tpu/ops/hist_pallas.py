"""Pallas TPU kernel for the wave histogram (SURVEY §7: THE kernel).

The XLA formulation in ``ops/grow.py`` builds a per-chunk one-hot of the
bin codes and contracts it with the leaf-mask x stat columns on the MXU.
Measured at ~37% of MXU peak — the one-hot operand's generation/layout
inside the fused dot dominates.  This kernel owns the whole pipeline in
VMEM instead (the analog of the reference's workgroup-local OpenCL
histograms, ``src/treelearner/ocl/histogram256.cl:343-360``, minus the
atomics TPU doesn't have):

* grid over row chunks; per step the chunk's bin codes (CH, G) u8,
  leaf ids (CH, 1) i32 and stat columns (CH, K) are DMA'd in;
* the leaf mask and the B = K*W stat-column matrix are built on the VPU;
* groups are processed in PAIRS so each one-hot tile is (CH, 128) —
  a full MXU tile — and contracted with the (CH, 128) stat matrix:
  out[pair] += one_hotᵀ @ bmat, accumulated in a VMEM-resident
  (G*NB, 128) output revisited across all grid steps.

Two stat-column representations share the kernel body:

* **bf16** (default training path): bf16 operands, f32 accumulators —
  the hi/lo column trick reconstructs f32-exact histograms;
* **int8** (``grad_quant_bits=8``): int8 stochastic-rounded g/h columns
  (plain [g_q, h_q, mask] or the striped six-column layout past
  ``ops/grow.COUNT_SPLIT_ROWS``) contracted on the MXU's native
  int8->int32 path with int32 accumulators.  Integer accumulation is
  associative, so the kernel is BYTE-identical to the int8 einsum
  formulation — gated on CPU via interpret mode (tests/test_quant.py,
  scripts/check_quant.py).

Layout: B columns are K-major (column k*W + w holds stat k of wave slot
w), so no 3D intermediates touch the minor-most dimension.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import obs

_LANES = 128


def fits_single_tile(w: int, k: int) -> bool:
    """Whether a (wave width, stat columns) pair packs into one
    128-lane VMEM tile — the kernel's eligibility condition.  The ONE
    routing gate shared by the grower's dispatch site, its
    ``hist_kernel_tag`` attribution and the bench suites, so the
    counter-reported kernel can never diverge from the kernel that
    actually ran (both the plain and the fused find-best wave route
    their histogram product through this same check)."""
    return w * k <= _LANES


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _operand_dtypes(ghk_dtype):
    """(operand dtype, accumulator dtype) for the stat-column dtype;
    rejects anything the MXU has no native accumulation path for."""
    if ghk_dtype == jnp.int8:
        return jnp.int8, jnp.int32
    if ghk_dtype == jnp.bfloat16:
        return jnp.bfloat16, jnp.float32
    raise ValueError(
        f"pallas wave-histogram supports bf16 or int8 stat columns, "
        f"got {ghk_dtype} (build bf16 hi/lo or grad_quant_bits=8 int8 "
        f"columns, or route to the einsum with hist_kernel=einsum)")


def _build_bmat(leaf_ref, pend_ref, gh_ref, ch, k, w, b, mdtype):
    """K-major (CH, B) stat matrix (column kk*W + slot holds stat kk of
    wave slot), zero-padded to ``b`` lanes.  ``mdtype`` is the operand
    dtype (bf16 or int8; mask x int8 products stay within int8: the
    mask is 0/1 and |q| <= 127).  Shared by both kernels."""
    leaf = leaf_ref[:]                                  # (CH, 1) i32
    pend = pend_ref[0:1, :w]                            # (1, W) i32
    lm = (leaf == pend).astype(mdtype)                  # (CH, W)
    gh = gh_ref[:]                                      # (CH, K)
    cols = [lm * gh[:, kk:kk + 1] for kk in range(k)]
    pad = b - k * w
    if pad:
        cols.append(jnp.zeros((ch, pad), mdtype))
    return jnp.concatenate(cols, axis=1)                # (CH, B)


def _pair_one_hot(bins, iota, g0, g, mdtype):
    """(CH, 2*NB) one-hot tile for group pair (g0, g0+1); the casts
    happen before the concat — Mosaic cannot bitcast i1 vregs through a
    concatenate."""
    if g0 + 1 < g:
        return jnp.concatenate(
            [(bins[:, g0:g0 + 1] == iota).astype(mdtype),
             (bins[:, g0 + 1:g0 + 2] == iota).astype(mdtype)],
            axis=1)
    return (bins[:, g0:g0 + 1] == iota).astype(mdtype)


def _kernel(binned_ref, leaf_ref, gh_ref, pend_ref, out_ref, *,
            ch: int, g: int, nb: int, k: int, w: int, mdtype, adtype):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bmat = _build_bmat(leaf_ref, pend_ref, gh_ref, ch, k, w, _LANES,
                       mdtype)
    bins = binned_ref[:].astype(jnp.int32)              # (CH, G)
    iota = jax.lax.broadcasted_iota(jnp.int32, (ch, nb), 1)
    for g0 in range(0, g, 2):
        oh = _pair_one_hot(bins, iota, g0, g, mdtype)
        acc = jax.lax.dot_general(
            oh, bmat, (((0,), (0,)), ((), ())),
            preferred_element_type=adtype)              # (2*NB, 128)
        r0 = g0 * nb
        r1 = r0 + acc.shape[0]
        out_ref[r0:r1, :] = out_ref[r0:r1, :] + acc


def _kernel_v2(binned_ref, leaf_ref, gh_ref, pend_ref, out_ref, oh_ref, *,
               ch: int, g: int, nb: int, k: int, w: int, b: int):
    """v2: build the FULL (CH, G*NB) one-hot in a VMEM scratch, then ONE
    dot per grid step — v1's 14 tiny pair-dots starved the MXU (each
    (CH,128)x(CH,128) is ~0.2 us of peak work vs its issue overhead).

    MEASURED (10.5M rows, v5e): w42 132 ms / w128 182-211 ms / w4 132 ms
    — 1.8-7x SLOWER than the XLA einsum (40 / 107 / 18 ms).  The
    width-independent ~132 ms floor shows the scratch write + dot-from-
    scratch serialize; Mosaic does not overlap the VPU one-hot build
    with the MXU.  Kept as a documented negative result: the einsum's
    fused one-hot is the best known formulation on this hardware.
    bf16-only (the scratch layout was never ported to int8)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bmat = _build_bmat(leaf_ref, pend_ref, gh_ref, ch, k, w, b,
                       jnp.bfloat16)
    bins = binned_ref[:].astype(jnp.int32)              # (CH, G)
    iota = jax.lax.broadcasted_iota(jnp.int32, (ch, nb), 1)
    for g0 in range(0, g, 2):
        tile = _pair_one_hot(bins, iota, g0, g, jnp.bfloat16)
        oh_ref[:, g0 * nb:g0 * nb + tile.shape[1]] = tile
    acc = jax.lax.dot_general(
        oh_ref[:], bmat, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # (G*NB, B)
    out_ref[:] = out_ref[:] + acc


@functools.partial(jax.jit,
                   static_argnames=("g", "nb", "k", "w", "ch",
                                    "interpret"))
def _wave_hist_pallas_v2(binned, leaf_id, ghk, pending, *, g: int,
                         nb: int, k: int, w: int, ch: int = 4096,
                         interpret: bool = False):
    """(n_pad, G) u8, (n_pad,) i32, (n_pad, K) bf16, (W,) i32
    -> (G*NB, K, W) f32 histogram.  B = k*w rounded up to a lane tile."""
    if ghk.dtype != jnp.bfloat16:
        raise ValueError(
            f"pallas wave-histogram v2 is bf16-only (documented negative "
            f"result), got {ghk.dtype}; use wave_hist_pallas")
    n = binned.shape[0]
    if n % ch:
        raise ValueError(
            f"pallas wave-histogram needs rows ({n}) divisible by its "
            f"chunk ({ch})")
    b = _ceil_to(k * w, _LANES)
    grid = (n // ch,)
    leaf2 = leaf_id.reshape(n, 1)
    pend2 = pending.reshape(1, w)
    out = pl.pallas_call(
        functools.partial(_kernel_v2, ch=ch, g=g, nb=nb, k=k, w=w, b=b),
        out_shape=jax.ShapeDtypeStruct((g * nb, b), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ch, g), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ch, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ch, ghk.shape[1]), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((g * nb, b), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((ch, g * nb), jnp.bfloat16)],
        interpret=interpret,
        # the one-hot scratch alone is ch*G*NB bf16 (14.7 MB at ch=4096);
        # the default 16 MB scoped-vmem budget needs raising
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        cost_estimate=pl.CostEstimate(
            flops=2 * n * g * nb * b,
            bytes_accessed=n * (g + 4 + 2 * k) + g * nb * b * 4,
            transcendentals=0,
        ),
    )(binned, leaf2, ghk, pend2)
    return out[:, :k * w].reshape(g * nb, k, w)


wave_hist_pallas_v2 = obs.track_jit("wave_hist_pallas_v2",
                                    _wave_hist_pallas_v2)


@functools.partial(jax.jit,
                   static_argnames=("g", "nb", "k", "w", "ch",
                                    "interpret"))
def _wave_hist_pallas(binned, leaf_id, ghk, pending, *, g: int, nb: int,
                      k: int, w: int, ch: int = 1024,
                      interpret: bool = False):
    """(n_pad, G) u8 bins, (n_pad,) i32 leaf ids, (n_pad, K) stat
    columns, (W,) i32 pending -> (G*NB, K, W) histogram.

    Stat columns are bf16 (f32 accumulators; the caller's hi/lo column
    split reconstructs f32-exact sums) or int8 (``grad_quant_bits=8``:
    int32 accumulators on the MXU's native int8->int32 path, including
    the striped six-column layout — BYTE-identical to the int8 einsum
    because integer accumulation is associative).  The output dtype
    follows the accumulator (f32 or int32)."""
    mdtype, adtype = _operand_dtypes(ghk.dtype)
    n = binned.shape[0]
    if n % ch:
        raise ValueError(
            f"pallas wave-histogram needs rows ({n}) divisible by its "
            f"chunk ({ch}); pad rows to a multiple (LGBM_TPU_CHUNK must "
            f"be a multiple of {ch} when using hist_kernel=pallas)")
    if not fits_single_tile(w, k):
        # a ValueError, not an assert: asserts vanish under `python -O`
        # and this is a caller-reachable configuration error (the grower
        # only routes w * k <= 128 waves here, but direct callers can
        # pass anything)
        raise ValueError(
            f"pallas wave-histogram needs stat columns x wave width "
            f"({k} x {w} = {k * w}) to fit one {_LANES}-lane tile; "
            f"use a narrower wave or the einsum path "
            f"(hist_kernel=einsum) for multi-tile waves")
    grid = (n // ch,)
    leaf2 = leaf_id.reshape(n, 1)
    pend2 = pending.reshape(1, w)
    out = pl.pallas_call(
        functools.partial(_kernel, ch=ch, g=g, nb=nb, k=k, w=w,
                          mdtype=mdtype, adtype=adtype),
        out_shape=jax.ShapeDtypeStruct((g * nb, _LANES), adtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ch, g), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ch, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ch, ghk.shape[1]), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((g * nb, _LANES), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * n * g * nb * _LANES,
            bytes_accessed=n * (g + 4 + 2 * k) + g * nb * _LANES * 4,
            transcendentals=0,
        ),
    )(binned, leaf2, ghk, pend2)
    # (G*NB, 128) -> (G*NB, K, W) -> caller reshapes to (W, S, 3)
    return out[:, :k * w].reshape(g * nb, k, w)


wave_hist_pallas = obs.track_jit("wave_hist_pallas", _wave_hist_pallas)
