"""Leaf partition + score application on device.

Replaces the reference's multithreaded stable partition
(``src/treelearner/data_partition.hpp:109-200``) with a key-sort compaction:
rows of the split leaf get key 0 (left) / 1 (right), padded tail rows key 2,
and a stable argsort yields the partitioned order with the tail untouched —
so the padded window can be written back with ``dynamic_update_slice``
without corrupting neighbouring leaf regions.

Row routing mirrors ``DenseBin::Split`` (``src/io/dense_bin.hpp:190-250``):

* rows whose group slot lies outside the split feature's slot range, or at
  the feature's default bin, go to the "default" side — ``default_left`` for
  MissingType::Zero, else by ``default_bin <= threshold``;
* the NaN bin (MissingType::NaN) follows ``default_left``;
* everything else compares ``bin <= threshold``;
* categorical rows go left iff their bin is in the chosen category set
  (default-bin rows included via membership of the default bin).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .. import obs


@jax.jit
def _partition_kernel(binned, indices, start, count, group, offset, width,
                      default_bin, num_bin, missing, threshold, default_left,
                      is_cat, cat_member):
    m = indices.shape[0]
    pos = jnp.arange(m, dtype=jnp.int32)
    valid = (pos >= start) & (pos < start + count)
    idx = jnp.where(valid, indices, 0)
    slot = binned[idx, group].astype(jnp.int32)

    shift = jnp.where(default_bin == 0, 1, 0)
    in_range = (slot >= offset) & (slot < offset + width)
    bin_ = jnp.where(in_range, slot - offset + shift, default_bin)

    is_default = bin_ == default_bin
    is_na = (missing == 2) & (bin_ == num_bin - 1)
    default_goes_left = jnp.where(missing == 1, default_left,
                                  default_bin <= threshold)
    left_num = jnp.where(is_default, default_goes_left,
                         jnp.where(is_na, default_left, bin_ <= threshold))
    left_cat = cat_member[jnp.clip(bin_, 0, 255)]
    goes_left = jnp.where(is_cat, left_cat, left_num)

    # head-foreign rows (pos < start) sort first, then left, right, tail
    key = jnp.where(pos < start, 0,
                    jnp.where(valid, jnp.where(goes_left, 1, 2), 3))
    order = jnp.argsort(key.astype(jnp.int32), stable=True)
    return indices[order], (valid & goes_left).sum().astype(jnp.int32)


_partition_kernel = obs.track_jit("partition_kernel",
                                  _partition_kernel)


def partition_leaf(binned, indices, count, *, group, offset, width,
                   default_bin, num_bin, missing, threshold, default_left,
                   is_cat, cat_member, start=0):
    """Stable-partition one leaf's (padded) index window.

    Returns (reordered indices (M,), left_count scalar) as device values.
    All split parameters are traced scalars: one compiled program per padded
    window size M.
    """
    i32 = lambda v: jnp.asarray(v, jnp.int32)
    return _partition_kernel(
        binned, indices, i32(start), i32(count), i32(group), i32(offset),
        i32(width), i32(default_bin), i32(num_bin), i32(missing),
        i32(threshold), jnp.asarray(default_left, bool),
        jnp.asarray(is_cat, bool), jnp.asarray(cat_member, bool))


@functools.partial(jax.jit, donate_argnums=(0,))
def apply_leaf_outputs(score, indices, leaf_begin, leaf_values, valid_count):
    """score[indices[p]] += leaf_values[leaf containing position p].

    ``leaf_begin`` are the ascending region starts in partition-position
    space, ``leaf_values`` the matching leaf outputs.  Positions at or past
    ``valid_count`` (out-of-bag rows under bagging) receive no update.  This
    is the train-side ``ScoreUpdater::AddScore`` via leaf partitions
    (``score_updater.hpp``).
    """
    n = indices.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    leaf = jnp.searchsorted(leaf_begin, pos, side="right") - 1
    addend = jnp.where(pos < valid_count, leaf_values[leaf], 0.0)
    return score.at[indices].add(addend.astype(score.dtype))


apply_leaf_outputs = obs.track_jit("apply_leaf_outputs",
                                   apply_leaf_outputs)


@jax.jit
def goes_left_matrix(binned_rows, group, offset, width, default_bin, num_bin,
                     missing, threshold, default_left, is_cat, cat_member):
    """Vectorized left/right decision for arbitrary binned rows (used by the
    on-device tree traversal in prediction)."""
    slot = binned_rows[:, group].astype(jnp.int32)
    shift = jnp.where(default_bin == 0, 1, 0)
    in_range = (slot >= offset) & (slot < offset + width)
    bin_ = jnp.where(in_range, slot - offset + shift, default_bin)
    is_default = bin_ == default_bin
    is_na = (missing == 2) & (bin_ == num_bin - 1)
    default_goes_left = jnp.where(missing == 1, default_left,
                                  default_bin <= threshold)
    left_num = jnp.where(is_default, default_goes_left,
                         jnp.where(is_na, default_left, bin_ <= threshold))
    left_cat = cat_member[jnp.clip(bin_, 0, 255)]
    return jnp.where(is_cat, left_cat, left_num)


goes_left_matrix = obs.track_jit("goes_left_matrix", goes_left_matrix)
