"""On-device tree traversal over a binned matrix.

Vectorized replacement for the reference's per-row ``Tree::GetLeaf``
traversal (``include/LightGBM/tree.h:487-508``, ``DecisionInner``): every row
carries a node pointer; one ``lax.while_loop`` iteration advances all rows a
level (gather node metadata, decode the feature bin from the group slot,
branch).  Terminates at the true tree depth.  Used for validation-score
updates, DART score subtraction and out-of-bag score updates — places where
the training partition is unavailable.

This is the TRAINING-side traversal: one tree per dispatch over the binned
matrix, which needs the live dataset's bin mappers.  Batch prediction and
serving route through ``lightgbm_tpu/serve/packed.py`` instead — the whole
ensemble packed into flat arrays keyed on RAW feature values, one dispatch
for any (rows x trees) batch, no dataset required (docs/Serving.md).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs as _obs
from ..tree.tree import K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK, Tree


class DeviceTree(NamedTuple):
    """Flat device arrays for one tree, sized (max_nodes,) / (max_leaves,)."""
    split_group: jnp.ndarray
    offset: jnp.ndarray
    width: jnp.ndarray
    default_bin: jnp.ndarray
    num_bin: jnp.ndarray
    missing: jnp.ndarray
    threshold: jnp.ndarray
    default_left: jnp.ndarray
    is_cat: jnp.ndarray
    cat_bitset: jnp.ndarray      # (max_nodes, 8) uint32 over inner bins
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    leaf_value: jnp.ndarray


def device_tree(tree: Tree, dataset, max_leaves: int) -> DeviceTree:
    """Build device arrays from a host tree + dataset feature metadata."""
    mn = max(max_leaves - 1, 1)
    n = tree.num_leaves - 1
    sg = np.zeros(mn, np.int32)
    off = np.zeros(mn, np.int32)
    wid = np.ones(mn, np.int32)
    db = np.zeros(mn, np.int32)
    nb = np.ones(mn, np.int32)
    mi = np.zeros(mn, np.int32)
    thr = np.zeros(mn, np.int32)
    dl = np.zeros(mn, bool)
    ic = np.zeros(mn, bool)
    cb = np.zeros((mn, 8), np.uint32)
    lc = np.full(mn, -1, np.int32)
    rc = np.full(mn, -1, np.int32)
    for node in range(n):
        f = int(tree.split_feature_inner[node])
        sg[node] = dataset.f_group[f]
        off[node] = dataset.f_offset[f]
        nbin = int(dataset.f_num_bin[f])
        dbin = int(dataset.f_default_bin[f])
        nb[node] = nbin
        db[node] = dbin
        wid[node] = nbin - (1 if dbin == 0 else 0)
        dt = int(tree.decision_type[node])
        ic[node] = bool(dt & K_CATEGORICAL_MASK)
        dl[node] = bool(dt & K_DEFAULT_LEFT_MASK)
        mi[node] = (dt >> 2) & 3
        if ic[node]:
            cat_idx = int(tree.threshold_in_bin[node])
            lo = tree.cat_boundaries_inner[cat_idx]
            hi = tree.cat_boundaries_inner[cat_idx + 1]
            words = tree.cat_threshold_inner[lo:hi][:8]
            cb[node, :len(words)] = words
        else:
            thr[node] = int(tree.threshold_in_bin[node])
        lc[node] = tree.left_child[node]
        rc[node] = tree.right_child[node]
    lv = np.zeros(max_leaves, np.float64)
    lv[:tree.num_leaves] = tree.leaf_value[:tree.num_leaves]
    as_j = jnp.asarray
    return DeviceTree(as_j(sg), as_j(off), as_j(wid), as_j(db), as_j(nb),
                      as_j(mi), as_j(thr), as_j(dl), as_j(ic), as_j(cb),
                      as_j(lc), as_j(rc), as_j(lv, jnp.float32))


@jax.jit
def traverse(binned: jnp.ndarray, t: DeviceTree) -> jnp.ndarray:
    """Leaf index per row of a (N, G) binned device matrix."""
    n = binned.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)

    def decide(node):
        grp = t.split_group[node]
        slot = binned[rows, grp].astype(jnp.int32)
        off = t.offset[node]
        db = t.default_bin[node]
        shift = jnp.where(db == 0, 1, 0)
        in_range = (slot >= off) & (slot < off + t.width[node])
        bin_ = jnp.where(in_range, slot - off + shift, db)
        missing = t.missing[node]
        is_default = bin_ == db
        is_na = (missing == 2) & (bin_ == t.num_bin[node] - 1)
        default_goes_left = jnp.where(missing == 1, t.default_left[node],
                                      db <= t.threshold[node])
        left_num = jnp.where(is_default, default_goes_left,
                             jnp.where(is_na, t.default_left[node],
                                       bin_ <= t.threshold[node]))
        word = t.cat_bitset[node, jnp.clip(bin_ >> 5, 0, 7)]
        left_cat = ((word >> (bin_ & 31).astype(jnp.uint32)) & 1) == 1
        return jnp.where(t.is_cat[node], left_cat, left_num)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        act = node >= 0
        cur = jnp.maximum(node, 0)
        left = decide(cur)
        nxt = jnp.where(left, t.left_child[cur], t.right_child[cur])
        return jnp.where(act, nxt, node)

    leaf_code = jax.lax.while_loop(cond, body,
                                   jnp.zeros(n, jnp.int32)
                                   if t.left_child.shape[0] > 0 else
                                   jnp.full(n, -1, jnp.int32))
    return ~leaf_code


traverse = _obs.track_jit("traverse", traverse)


@jax.jit
def add_tree_score(score, binned, t: DeviceTree, multiplier):
    """score += multiplier * leaf_value[traverse(binned)]."""
    leaf = traverse(binned, t)
    return score + multiplier * t.leaf_value[leaf]


# recompile tracking for the device predict/eval path (a new row-count
# or leaf-count shape recompiles the traversal program)
add_tree_score = _obs.track_jit("add_tree_score", add_tree_score)


@jax.jit
def add_constant_score(score, value):
    return score + value


add_constant_score = _obs.track_jit("add_constant_score",
                                    add_constant_score)
