"""Gradient-histogram construction on TPU.

The reference's hot loop is a scalar gather-accumulate
(``src/io/dense_bin.hpp:106-175``: ``hist[bin[idx]] += (g, h, 1)``) and its
GPU analog uses local-memory atomics (``src/treelearner/ocl/histogram256.cl``).
TPUs have no cheap atomics; the TPU-native formulation is a **one-hot
matmul** that runs on the MXU: for every feature group, the (rows x 256)
one-hot of the bin column times the (rows x 3) [grad, hess, 1] matrix yields
the (256 x 3) histogram.  XLA fuses the iota-compare one-hot into the matmul
operand, so nothing of size rows*256 is ever materialised in HBM; a
``lax.scan`` over fixed-size row chunks bounds VMEM pressure and keeps one
compiled program per (chunk, groups) shape.

Accumulation is float32 (like the reference GPU learner's single-precision
histograms, ``gpu_tree_learner.h:73-77``); per-bin partial sums come out of
the MXU's float32 accumulators so there is no bf16 accumulation error.

Under the fused find-best-in-wave layout (``find_best_fusion``,
ops/grow.py) the wave histograms these builders produce never leave the
growth program: the per-feature gain scan consumes them in place and
only packed winner records plus the parent-minus-sibling residuals
survive to HBM, so the (2W, S, 3) stack the two-pass layout materialises
between its two dispatches is XLA-fusible intermediate state here.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .. import obs

# rows per scan chunk: 8 MXU passes of 1024x256 per group keeps VMEM happy
_CHUNK = 8192

# int8 quantization range for grad_quant_bits=8: symmetric [-127, 127]
# (the -128 code is unused so negation stays exact)
QUANT_MAX = 127.0


def quant_scales(grad, hess, eps: float = 1e-30):
    """Per-dispatch global scales mapping max|g| / max|h| onto the int8
    range (Shi et al., *Quantized Training of Gradient Boosting Decision
    Trees*, NeurIPS 2022, use one global scale per iteration — enough
    because GBDT gradients are bounded by the loss curvature, not
    heavy-tailed per-feature like DNN activations)."""
    sg = jnp.maximum(jnp.max(jnp.abs(grad)), eps) / QUANT_MAX
    sh = jnp.maximum(jnp.max(jnp.abs(hess)), eps) / QUANT_MAX
    return sg, sh


def stochastic_round_with(x, scale, u):
    """:func:`stochastic_round_int8` with the uniform noise supplied by
    the caller — the sharded grower draws it at the canonical GLOBAL
    shape and slices its shard's block (jax's threefry stream is keyed
    on the draw shape, so per-row noise only matches the single-device
    path when the drawn shape matches too)."""
    q = jnp.floor(x / scale + u)
    return jnp.clip(q, -QUANT_MAX, QUANT_MAX).astype(jnp.int8)


def stochastic_round_int8(x, scale, key):
    """Unbiased stochastic rounding of ``x / scale`` to int8:
    ``floor(v + u)`` with u ~ U[0, 1) has expectation exactly v, so the
    quantization error is zero-mean noise the histogram bin sums average
    out (variance ~ rows_in_bin) instead of a systematic bias."""
    return stochastic_round_with(x, scale,
                                 jax.random.uniform(key, x.shape))


def quantize_gh(grad, hess, key):
    """(scale_g, scale_h, g_int8, h_int8) for one tree's gradients.
    ``key`` must derive from the global tree index (fold_in) so the
    fused scan and the per-iteration path draw bit-identical rounding
    noise for the same tree — the quantized fused-parity contract."""
    kg, kh = jax.random.split(key)
    sg, sh = quant_scales(grad, hess)
    return sg, sh, stochastic_round_int8(grad, sg, kg), \
        stochastic_round_int8(hess, sh, kh)


def num_chunks_for(m: int) -> int:
    """Scan chunk count for a window of static size m: chunked only when
    evenly divisible (power-of-two buckets always are above _CHUNK)."""
    return m // _CHUNK if (m > _CHUNK and m % _CHUNK == 0) else 1


def _chunk_histogram(bins_u8: jnp.ndarray, gh: jnp.ndarray,
                     dp: bool = False) -> jnp.ndarray:
    """(C, G) uint8 bins x (C, 3) [g, h, 1] -> (G, 256, 3) partial sums.

    TPU: one-hot matmul on the MXU.  Precision HIGHEST keeps the gradient
    operand in full float32 (TPU default would round it to bfloat16; the
    one-hot operand is exact in any dtype, but 0.4%-level gradient rounding
    visibly moves split gains).

    CPU (tests / virtual mesh): XLA CPU would materialise the one-hot and
    run the f32 matmul through the slow 6-pass emulation, so use a
    scatter-add instead — same result, ~100x faster there.

    ``dp`` is unused at chunk level (kept for signature symmetry); the
    double-precision option acts on the cross-chunk accumulation, see
    ``_histogram_scan``.
    """
    if jax.default_backend() == "tpu":
        oh = jax.nn.one_hot(bins_u8, 256, dtype=jnp.float32)  # (C, G, 256)
        return jnp.einsum("cgb,ck->gbk", oh, gh,
                          precision=jax.lax.Precision.HIGHEST,
                          preferred_element_type=jnp.float32)
    g = bins_u8.shape[1]
    flat_idx = (jnp.arange(g, dtype=jnp.int32)[None, :] * 256
                + bins_u8.astype(jnp.int32))                  # (C, G)
    updates = jnp.broadcast_to(gh[:, None, :],
                               (gh.shape[0], g, 3))           # (C, G, 3)
    hist = jnp.zeros((g * 256, 3), jnp.float32)
    hist = hist.at[flat_idx.reshape(-1)].add(
        updates.reshape(-1, 3))
    return hist.reshape(g, 256, 3)


@functools.partial(jax.jit, static_argnames=("num_chunks", "dp"))
def _histogram_scan(bins: jnp.ndarray, gh: jnp.ndarray,
                    num_chunks: int, dp: bool = False) -> jnp.ndarray:
    """Chunked histogram accumulation.

    ``dp`` realises the reference's ``gpu_use_dp``
    (gpu_tree_learner.h:73-77): double-precision-equivalent accumulation
    without x64 (JAX runs with it disabled).  Two ingredients: the
    accumulation granule shrinks to 512 rows, so each partial sum is
    accurate in f32, and the cross-granule running total is Kahan
    compensated, keeping the final error O(ulp) instead of
    O(num_granules * ulp(total)) — the billion-row f32 accumulation
    concern from SURVEY §7.  Costs extra scan steps; accuracy mode only.
    """
    g = bins.shape[1]
    if num_chunks == 1 and not dp:
        return _chunk_histogram(bins, gh, dp)

    if not dp:
        bins_c = bins.reshape(num_chunks, -1, g)
        gh_c = gh.reshape(num_chunks, -1, 3)

        def body(acc, xs):
            b, w = xs
            return acc + _chunk_histogram(b, w), None

        init = jnp.zeros((g, 256, 3), jnp.float32)
        acc, _ = jax.lax.scan(body, init, (bins_c, gh_c))
        return acc

    rows = bins.shape[0]
    sub = 512
    n_sub = rows // sub
    tail = rows - n_sub * sub

    def kahan_step(carry, h):
        acc, comp = carry
        y = h - comp
        t = acc + y
        comp = (t - acc) - y
        return t, comp

    z = jnp.zeros((g, 256, 3), jnp.float32)
    carry = (z, z)
    if n_sub:
        bins_c = bins[:n_sub * sub].reshape(n_sub, sub, g)
        gh_c = gh[:n_sub * sub].reshape(n_sub, sub, 3)

        def body_kahan(c, xs):
            b, w = xs
            return kahan_step(c, _chunk_histogram(b, w)), None

        carry, _ = jax.lax.scan(body_kahan, carry, (bins_c, gh_c))
    if tail:
        # odd tail: one EXTRA compensated step (collapsing the whole
        # window to a single uncompensated chunk would silently drop the
        # promised double-precision-equivalent behaviour for windows not
        # divisible by the granule)
        carry = kahan_step(carry, _chunk_histogram(bins[n_sub * sub:],
                                                   gh[n_sub * sub:]))
    return carry[0]


_histogram_scan = obs.track_jit("histogram_scan", _histogram_scan)


@functools.partial(jax.jit, donate_argnums=())
def _gather_rows(binned: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                 indices: jnp.ndarray, start: jnp.ndarray, count: jnp.ndarray):
    """Gather bin rows and masked [g, h, 1] rows for one leaf's window.

    Valid rows are positions [start, start + count); the window may carry
    foreign rows at its head when the leaf region sits near the end of the
    index buffer (the slide-back trick keeps every dynamic_slice in bounds).
    """
    m = indices.shape[0]
    pos = jnp.arange(m, dtype=jnp.int32)
    valid = (pos >= start) & (pos < start + count)
    idx = jnp.where(valid, indices, 0)
    bins = binned[idx]                                         # (M, G) uint8
    vf = valid.astype(jnp.float32)
    gh = jnp.stack([grad[idx] * vf, hess[idx] * vf, vf], axis=1)
    return bins, gh


def build_histogram(binned: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                    indices: jnp.ndarray, count, start=0) -> jnp.ndarray:
    """Histogram of one leaf.

    binned  : (N, G) uint8 device matrix (HBM resident, grouped bins)
    grad/hess : (N,) float32
    indices : (M,) int32, M static (padded bucket size)
    count   : scalar number of valid entries beginning at ``start``

    Returns (G, 256, 3) float32 [sum_grad, sum_hess, count] per group slot.
    """
    m = int(indices.shape[0])
    bins, gh = _gather_rows(binned, grad, hess, indices,
                            jnp.asarray(start, jnp.int32),
                            jnp.asarray(count, jnp.int32))
    # bucket sizes are powers of two, so m is chunk-divisible whenever
    # m > _CHUNK; any odd shape falls back to a single chunk
    num_chunks = m // _CHUNK if (m > _CHUNK and m % _CHUNK == 0) else 1
    return _histogram_scan(bins, gh, num_chunks)


_gather_rows = obs.track_jit("gather_rows", _gather_rows)


@jax.jit
def subtract_histogram(parent: jnp.ndarray, sibling: jnp.ndarray) -> jnp.ndarray:
    """Larger child = parent - smaller child (the reference's histogram
    subtraction trick, ``serial_tree_learner.cpp:508-513``)."""
    return parent - sibling


subtract_histogram = obs.track_jit("subtract_histogram",
                                   subtract_histogram)


def bucket_size(count: int, minimum: int = 1024) -> int:
    """Static padded size for a dynamic leaf row count.

    Powers of two bound the number of distinct compiled programs to
    ~log2(N) while wasting < 2x compute on the padding.
    """
    b = minimum
    while b < count:
        b <<= 1
    return b
