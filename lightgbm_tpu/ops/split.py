"""Fused best-split scan over all features of one leaf.

TPU-native replacement for the reference's per-feature scalar threshold scans
(``src/treelearner/feature_histogram.hpp:84-273,505-653``): instead of
bidirectional loops per feature, every (feature, direction, threshold)
candidate is evaluated at once with prefix sums over the 256-bin axis and a
single argmax picks the winner.  Semantics mirror the reference:

* default-bin reconstruction from leaf totals (``FixHistogram``,
  ``src/io/dataset.cpp:802-822``) — the grouped storage never records the
  default bin, so ``hist[default] = leaf_total - sum(others)``;
* missing handling: the two scan directions become two candidate variants —
  missing stats placed right (``default_left=False``) or left (True), with
  the reference's skipped-threshold rules for MissingType::Zero and the
  NaN-bin exclusions for MissingType::NaN;
* L1/L2-regularized leaf outputs with ``max_delta_step`` clamping and
  monotone-constraint zeroing (``GetSplitGains``), per-leaf output value
  constraints from monotone midpoint propagation;
* categorical one-hot mode (``num_bin <= max_cat_to_onehot``) and
  sorted-by-gradient-ratio subset scan from both ends with ``cat_smooth`` /
  ``cat_l2`` / ``max_cat_threshold`` (``FindBestThresholdCategorical``,
  feature_histogram.hpp:113-273).  The reference's sequential
  ``cnt_cur_group`` gate (an extra thinning of candidates by
  ``min_data_per_group``) is relaxed to the equivalent right-count bound,
  which vectorizes; accuracy-level behaviour is covered by the test suite.

Tie-breaking is deterministic: first-max argmax = the reference's strict
``operator>`` sequential updates (lower feature index, dir=-1 first).

The scan is factored into composable stages so the distributed learners can
reuse it (SURVEY.md §2.3-2.4):

* ``feature_histograms``  — flat slots -> per-feature (F,256,3) with
  default-bin reconstruction;
* ``per_feature_best``    — the vectorized threshold/categorical scans,
  returning each feature's best candidate (no argmax);
* ``select_and_pack``     — masked argmax + the packed 13-float record.

Serial chains all three on the full feature set; feature-parallel runs them
per device on its feature shard and allreduces the packed record; voting
runs ``per_feature_best`` on local histograms for the vote, then again on
the psum-reduced elected features.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs

K_EPSILON = 1e-15
NEG_INF = -1e30


# indices into the packed best-split vector returned by find_best
(F_GAIN, F_FEATURE, F_THRESHOLD, F_DEFAULT_LEFT, F_IS_CAT,
 F_LEFT_G, F_LEFT_H, F_LEFT_C, F_RIGHT_G, F_RIGHT_H, F_RIGHT_C,
 F_LEFT_OUT, F_RIGHT_OUT) = range(13)


class SplitHyper(NamedTuple):
    """Traced hyper-parameters (no recompilation when values change)."""
    lambda_l1: jnp.ndarray
    lambda_l2: jnp.ndarray
    min_data_in_leaf: jnp.ndarray
    min_sum_hessian_in_leaf: jnp.ndarray
    min_gain_to_split: jnp.ndarray
    max_delta_step: jnp.ndarray
    cat_smooth: jnp.ndarray
    cat_l2: jnp.ndarray
    max_cat_threshold: jnp.ndarray
    max_cat_to_onehot: jnp.ndarray
    min_data_per_group: jnp.ndarray

    @classmethod
    def from_config(cls, c) -> "SplitHyper":
        f = lambda v: jnp.asarray(v, jnp.float32)
        return cls(f(c.lambda_l1), f(c.lambda_l2), f(c.min_data_in_leaf),
                   f(c.min_sum_hessian_in_leaf), f(c.min_gain_to_split),
                   f(c.max_delta_step), f(c.cat_smooth), f(c.cat_l2),
                   f(c.max_cat_threshold), f(c.max_cat_to_onehot),
                   f(c.min_data_per_group))


class FeatureMeta(NamedTuple):
    """Per-feature static metadata as device arrays.

    ``global_id`` carries each feature's index in the full (unsharded)
    feature list: the serial learner's identity mapping, a shard's
    assignment for feature-parallel.  All split records report global ids.
    """
    slot_idx: jnp.ndarray        # (F, 256) int32, flat index into the hist
    valid_nondefault: jnp.ndarray  # (F, 256) bool
    num_bin: jnp.ndarray         # (F,) int32
    default_bin: jnp.ndarray     # (F,) int32
    missing: jnp.ndarray         # (F,) int32 0/1/2 none/zero/nan
    is_cat: jnp.ndarray          # (F,) int32
    mono: jnp.ndarray            # (F,) int32
    penalty: jnp.ndarray         # (F,) float32
    global_id: jnp.ndarray       # (F,) int32

    @classmethod
    def from_dataset(cls, dataset, feature_subset=None,
                     slot_base: int = 0,
                     slot_stride: int = 256) -> "FeatureMeta":
        """Build metadata arrays; ``feature_subset`` (host int array) keeps
        only those used-feature indices (feature-parallel shards).  Entries
        of -1 in the subset are padding (masked via num_bin=1).
        ``slot_base`` shifts slot indices into a device-local histogram
        (feature-parallel: the shard owning groups [base/256, ...) sees only
        its own slots).  ``slot_stride`` is the per-group slot pitch of the
        flat histogram (256 for the host path; the device grower packs
        groups at the smallest power-of-two that fits, e.g. 64 for
        max_bin=63, to keep the one-hot matmul narrow)."""
        nb = dataset.f_num_bin.astype(np.int32)
        db = dataset.f_default_bin.astype(np.int32)
        off = dataset.f_offset.astype(np.int64)
        grp = dataset.f_group.astype(np.int64)
        miss = dataset.f_missing_type.astype(np.int32)
        cat = dataset.f_is_categorical.astype(np.int32)
        mono = np.asarray(dataset.monotone_constraints, np.int32)
        pen = np.asarray(dataset.feature_penalty, np.float32)
        gid = np.arange(len(nb), dtype=np.int32)
        if feature_subset is not None:
            fs = np.asarray(feature_subset, np.int64)
            pad = fs < 0
            fs = np.where(pad, 0, fs)
            take = lambda a: np.where(pad, 0, a[fs])
            nb = np.where(pad, 1, nb[fs]).astype(np.int32)  # num_bin=1 => off
            db, off, grp = take(db), take(off), take(grp)
            miss, cat, mono = take(miss), take(cat), take(mono)
            pen = np.where(pad, 0.0, pen[fs]).astype(np.float32)
            gid = np.where(pad, -1, gid[fs]).astype(np.int32)

        b = np.arange(256, dtype=np.int64)[None, :]
        shift = (db == 0).astype(np.int64)
        slot = grp[:, None] * int(slot_stride) + off[:, None] + b \
            - shift[:, None] - int(slot_base)
        valid = (b < nb[:, None]) & (b != db[:, None])
        slot = np.where(valid, slot, 0)
        return cls(jnp.asarray(slot, jnp.int32), jnp.asarray(valid),
                   jnp.asarray(nb), jnp.asarray(db), jnp.asarray(miss),
                   jnp.asarray(cat), jnp.asarray(mono), jnp.asarray(pen),
                   jnp.asarray(gid))


def _threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def _calc_output(g, h, l1, l2, max_delta_step):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:447-455)."""
    out = -_threshold_l1(g, l1) / (h + l2)
    clipped = jnp.clip(out, -max_delta_step, max_delta_step)
    return jnp.where(max_delta_step <= 0.0, out, clipped)


def _gain_given_output(g, h, l1, l2, out):
    """GetLeafSplitGainGivenOutput (feature_histogram.hpp:495-498)."""
    sg = _threshold_l1(g, l1)
    return -(2.0 * sg * out + (h + l2) * out * out)


def _split_gain(gl, hl, gr, hr, l1, l2, mds, cmin, cmax, mono):
    """GetSplitGains: child-gain sum with monotone violation -> 0."""
    ol = jnp.clip(_calc_output(gl, hl, l1, l2, mds), cmin, cmax)
    orr = jnp.clip(_calc_output(gr, hr, l1, l2, mds), cmin, cmax)
    gain = (_gain_given_output(gl, hl, l1, l2, ol)
            + _gain_given_output(gr, hr, l1, l2, orr))
    violates = ((mono > 0) & (ol > orr)) | ((mono < 0) & (ol < orr))
    return jnp.where(violates, 0.0, gain)


# ---------------------------------------------------------------------------
# stage 1: flat histogram slots -> per-feature histograms
# ---------------------------------------------------------------------------
def gather_feature_histograms(flat_hist, meta: FeatureMeta):
    """(S, 3) flat slots -> raw (F, 256, 3) per-feature histograms (default
    bin still zero).  The voting learner psum-reduces this raw form for the
    elected features before reconstruction."""
    return flat_hist[meta.slot_idx] * meta.valid_nondefault[..., None]


def reconstruct_default(fh, total, meta: FeatureMeta):
    """Fill each feature's default bin as leaf_total - sum(other bins)
    (FixHistogram, src/io/dataset.cpp:802-822).  dtype-generic: the
    int32 quantized scan reconstructs EXACTLY (integer subtraction),
    where the f32 path carries the usual accumulation rounding."""
    b = jnp.arange(256, dtype=jnp.int32)[None, :]
    default_vals = total[None, :] - fh.sum(axis=1)
    default_vals = default_vals.at[:, 2].set(
        jnp.maximum(default_vals[:, 2], 0))
    is_default = (b == meta.default_bin[:, None]) & (b < meta.num_bin[:, None])
    return jnp.where(is_default[..., None], default_vals[:, None, :], fh)


def feature_histograms(flat_hist, total, meta: FeatureMeta):
    """(S, 3) flat slots -> (F, 256, 3) with the default bin reconstructed
    from leaf totals."""
    return reconstruct_default(
        gather_feature_histograms(flat_hist, meta), total, meta)


# ---------------------------------------------------------------------------
# stage 2: the vectorized scans, one best candidate per feature
# ---------------------------------------------------------------------------
class PerFeatureBest(NamedTuple):
    gain: jnp.ndarray        # (F,) raw child-gain sum, NEG_INF when invalid
    threshold: jnp.ndarray   # (F,) int32 numerical threshold bin
    default_left: jnp.ndarray  # (F,) bool
    left: jnp.ndarray        # (F, 3) left-child (g, h, c); int32 in
    #                          quantized units under the int32 scan
    is_cat: jnp.ndarray      # (F,) bool
    cat_member: jnp.ndarray  # (F, 256) bool membership of the cat candidate
    cat_extra_l2: jnp.ndarray  # (F,) additional l2 for the winning cat mode


def per_feature_best(fh, total, constraint, meta: FeatureMeta,
                     hp: SplitHyper, has_cat: bool,
                     min_gain_shift, scales=None) -> PerFeatureBest:
    """``scales`` switches on the int32 quantized scan
    (``grad_quant_bits=8``, ROUND8_NOTES.md): ``fh`` is then the int32
    [g_q, h_q, count] histogram and ``scales`` the (3,) [scale_g,
    scale_h, 1] dequantization vector, while ``total`` is ALWAYS in
    real (dequantized) units.  All prefix sums run in int32 — EXACT,
    no f32 accumulation error across the 256-bin axis — and values are
    dequantized only where the gain/output math needs real units.
    ``pf.left`` keeps the raw integer units so the caller can carry
    exact child totals."""
    tg, th, tc = total[0], total[1] + 2.0 * K_EPSILON, total[2]
    cmin, cmax = constraint[0], constraint[1]
    l1, l2, mds = hp.lambda_l1, hp.lambda_l2, hp.max_delta_step

    nb = meta.num_bin[:, None].astype(jnp.float32)       # (F,1)
    db = meta.default_bin[:, None]
    miss = meta.missing[:, None]
    b = jnp.arange(256, dtype=jnp.int32)[None, :]        # (1,256)
    nf = fh.shape[0]

    # =====================================================================
    # numerical
    # =====================================================================
    in_feat = b < meta.num_bin[:, None]
    na_mask = (miss == 2) & (b == meta.num_bin[:, None] - 1)
    zero_sep = (miss == 1) & (nb > 2)                    # zero-as-missing
    zero_mask = zero_sep & (b == db)
    miss_mask = (na_mask | zero_mask) & in_feat
    base = fh * (in_feat & ~miss_mask)[..., None]
    prefix = jnp.cumsum(base, axis=1)                    # (F,256,3)
    miss_stats = (fh * miss_mask[..., None]).sum(axis=1)  # (F,3)

    # variant 0 = missing left (default_left=True, reference dir=-1 scan)
    # variant 1 = missing right (default_left=False, dir=+1)
    left0 = prefix + miss_stats[:, None, :]
    left1 = prefix
    lefts = jnp.stack([left0, left1], axis=1)            # (F,2,256,3)
    # int32 scan: candidate stats leave the integer domain HERE — one
    # multiply per candidate, after the exact prefix sums
    lefts_f = lefts if scales is None \
        else lefts.astype(jnp.float32) * scales

    t_ok = b < meta.num_bin[:, None] - 1                 # right side real bins
    two_dir = ((miss == 2) & (nb > 2)) | zero_sep
    na_small = (miss == 2) & (nb <= 2)                   # forced dl=False
    v0_ok = t_ok & ~na_small & ~((miss == 2)
                                 & (b >= meta.num_bin[:, None] - 2))
    v0_ok = v0_ok & ~(zero_sep & (b == db - 1))
    v0_ok = v0_ok | (t_ok & (miss == 0))                 # plain scan -> v0
    v1_ok = t_ok & (two_dir | na_small)
    v1_ok = v1_ok & ~(zero_sep & (b == db))
    var_ok = jnp.stack([v0_ok, v1_ok], axis=1)           # (F,2,256)

    gl = lefts_f[..., 0]
    hl = lefts_f[..., 1] + K_EPSILON
    cl = lefts_f[..., 2]
    gr, hr, cr = tg - gl, th - hl, tc - cl
    data_ok = ((cl >= hp.min_data_in_leaf) & (cr >= hp.min_data_in_leaf)
               & (hl >= hp.min_sum_hessian_in_leaf)
               & (hr >= hp.min_sum_hessian_in_leaf))
    mono = meta.mono[:, None, None]
    gains = _split_gain(gl, hl, gr, hr, l1, l2, mds, cmin, cmax, mono)
    num_gains = jnp.where(var_ok & data_ok & (gains > min_gain_shift),
                          gains, NEG_INF)                # (F,2,256)

    flat_ng = num_gains.reshape(nf, -1)
    num_arg = jnp.argmax(flat_ng, axis=1)                # first max: dir=-1
    num_best_gain = jnp.take_along_axis(flat_ng, num_arg[:, None], 1)[:, 0]
    num_dl = num_arg < 256                               # v0 => default_left
    num_thr = (num_arg % 256).astype(jnp.int32)
    num_left = jnp.take_along_axis(
        lefts.reshape(nf, 512, 3), num_arg[:, None, None], 1)[:, 0]

    if not has_cat:
        return PerFeatureBest(
            num_best_gain, num_thr, num_dl, num_left,
            jnp.zeros(nf, bool), jnp.zeros((nf, 256), bool),
            jnp.zeros(nf, jnp.float32))

    # =====================================================================
    # categorical
    # =====================================================================
    fh_f = fh if scales is None else fh.astype(jnp.float32) * scales
    cnt = fh[..., 2]
    used_bin_mask = b < (meta.num_bin[:, None] - 1 + (miss == 0))
    # one-hot mode: left = single bin t (regular l2); single-bin stats
    # dequantize exactly (one multiply, no summation)
    oh_gl, oh_hl, oh_cl = fh_f[..., 0], fh_f[..., 1] + K_EPSILON, \
        fh_f[..., 2]
    oh_gr, oh_hr, oh_cr = tg - oh_gl, th - oh_hl, tc - oh_cl
    oh_ok = (used_bin_mask & (oh_cl >= hp.min_data_in_leaf)
             & (oh_cr >= hp.min_data_in_leaf)
             & (oh_hl >= hp.min_sum_hessian_in_leaf)
             & (oh_hr >= hp.min_sum_hessian_in_leaf))
    oh_gains = _split_gain(oh_gl, oh_hl, oh_gr, oh_hr, l1, l2, mds,
                           cmin, cmax, 0)
    oh_gains = jnp.where(oh_ok & (oh_gains > min_gain_shift), oh_gains,
                         NEG_INF)
    oh_arg = jnp.argmax(oh_gains, axis=1)
    oh_best = jnp.take_along_axis(oh_gains, oh_arg[:, None], 1)[:, 0]

    # sorted-subset mode (l2 + cat_l2, ratio = g / (h + cat_smooth))
    l2c = l2 + hp.cat_l2
    eligible = used_bin_mask & (cnt >= hp.cat_smooth)
    n_used = eligible.sum(axis=1).astype(jnp.float32)    # (F,)
    ratio = jnp.where(eligible,
                      fh_f[..., 0] / (fh_f[..., 1] + hp.cat_smooth),
                      jnp.inf)
    order = jnp.argsort(ratio, axis=1, stable=True)      # (F,256)
    sorted_fh = jnp.take_along_axis(fh, order[..., None], 1)
    sorted_el = jnp.take_along_axis(eligible, order, 1)
    sorted_fh = sorted_fh * sorted_el[..., None]
    rank = b.astype(jnp.float32)                         # sorted position
    max_num_cat = jnp.minimum(hp.max_cat_threshold,
                              jnp.floor((n_used + 1.0) / 2.0))[:, None]

    def _cat_scan(sfh):
        ps = jnp.cumsum(sfh, axis=1)                     # exact when int
        psf = ps if scales is None else ps.astype(jnp.float32) * scales
        k = rank + 1.0                                   # bins taken
        sgl, shl, scl = psf[..., 0], psf[..., 1] + K_EPSILON, psf[..., 2]
        sgr, shr, scr = tg - sgl, th - shl, tc - scl
        ok = ((k <= max_num_cat)
              & (k <= jnp.maximum(n_used[:, None] - 1.0, 0.0))
              & (scl >= hp.min_data_in_leaf)
              & (scr >= jnp.maximum(hp.min_data_in_leaf,
                                    hp.min_data_per_group))
              & (shl >= hp.min_sum_hessian_in_leaf)
              & (shr >= hp.min_sum_hessian_in_leaf))
        g = _split_gain(sgl, shl, sgr, shr, l1, l2c, mds, cmin, cmax, 0)
        g = jnp.where(ok & (g > min_gain_shift), g, NEG_INF)
        return g, ps

    fwd_gains, _ = _cat_scan(sorted_fh)
    rev_fh = jnp.flip(jnp.where(sorted_el[..., None], sorted_fh, 0), axis=1)
    # reversed order: take from the high-ratio end of the eligible prefix;
    # roll so eligible entries lead
    shift_amt = (256 - n_used.astype(jnp.int32))
    rev_fh = jax.vmap(lambda x, s: jnp.roll(x, -s, axis=0))(rev_fh, shift_amt)
    rev_gains, _ = _cat_scan(rev_fh)
    both = jnp.stack([fwd_gains, rev_gains], axis=1)     # (F,2,256)
    flat_cg = both.reshape(nf, -1)
    srt_arg = jnp.argmax(flat_cg, axis=1)
    srt_best = jnp.take_along_axis(flat_cg, srt_arg[:, None], 1)[:, 0]
    srt_dir_fwd = srt_arg < 256
    srt_k = (srt_arg % 256) + 1

    use_onehot = nb[:, 0] <= hp.max_cat_to_onehot
    cat_best_gain = jnp.where(use_onehot, oh_best, srt_best)

    # membership mask over bins for the winning candidate of each feature
    inv_pos = jnp.argsort(order, axis=1, stable=True)    # bin -> sorted pos
    fwd_member = inv_pos < srt_k[:, None]
    rev_member = ((inv_pos >= (n_used[:, None].astype(jnp.int32)
                               - srt_k[:, None]))
                  & (inv_pos < n_used[:, None].astype(jnp.int32)))
    srt_member = (jnp.where(srt_dir_fwd[:, None], fwd_member, rev_member)
                  & eligible)
    oh_member = b == oh_arg[:, None]
    cat_member = jnp.where(use_onehot[:, None], oh_member, srt_member)
    # raw-unit left stats (exact int32 sums under the quantized scan)
    cat_left = jnp.einsum("fb,fbk->fk", cat_member.astype(fh.dtype), fh)
    cat_extra_l2 = jnp.where(use_onehot, 0.0, hp.cat_l2)

    is_cat = meta.is_cat == 1
    return PerFeatureBest(
        jnp.where(is_cat, cat_best_gain, num_best_gain),
        num_thr, num_dl,
        jnp.where(is_cat[:, None], cat_left, num_left),
        is_cat, cat_member, cat_extra_l2)


# ---------------------------------------------------------------------------
# stage 3: masked argmax over features + the packed record
# ---------------------------------------------------------------------------
def masked_feature_gain(pf: PerFeatureBest, meta: FeatureMeta, feature_mask,
                        min_gain_shift):
    """Per-feature shifted gains with penalty and masking applied; NEG_INF
    for excluded features (used both by the serial argmax and the voting
    learner's local top-k)."""
    g = (pf.gain - min_gain_shift) * meta.penalty
    ok = feature_mask & (meta.num_bin > 1) & (meta.global_id >= 0)
    return jnp.where(ok, g, NEG_INF)


def pack_best(best_f, feat_gain, pf: PerFeatureBest, total, constraint,
              hp: SplitHyper, meta: FeatureMeta, scales=None):
    """Pack the winning feature's split into the 13-float record (+ its
    categorical membership row).  ``best_f`` is a traced local index.
    Under the int32 quantized scan ``pf.left`` carries quantized-unit
    integers and ``scales`` dequantizes them, so the packed record
    always reports REAL units (host tree replay is scan-agnostic)."""
    tg, th, tc = total[0], total[1] + 2.0 * K_EPSILON, total[2]
    cmin, cmax = constraint[0], constraint[1]
    l1, l2, mds = hp.lambda_l1, hp.lambda_l2, hp.max_delta_step
    left = pf.left[best_f]
    if scales is not None:
        left = left.astype(jnp.float32) * scales
    best_is_cat = pf.is_cat[best_f]
    lg, lh, lc = left[0], left[1] + K_EPSILON, left[2]
    rg = tg - lg
    use_l2 = l2 + jnp.where(best_is_cat, pf.cat_extra_l2[best_f], 0.0)
    left_out = jnp.clip(_calc_output(lg, lh, l1, use_l2, mds), cmin, cmax)
    rh = th - lh
    right_out = jnp.clip(_calc_output(rg, rh, l1, use_l2, mds), cmin, cmax)
    packed = jnp.stack([
        feat_gain[best_f],
        meta.global_id[best_f].astype(jnp.float32),
        pf.threshold[best_f].astype(jnp.float32),
        pf.default_left[best_f].astype(jnp.float32),
        best_is_cat.astype(jnp.float32),
        lg, left[1], lc,
        rg, th - 2.0 * K_EPSILON - left[1], tc - lc,
        left_out, right_out,
    ])
    return packed, pf.cat_member[best_f]


def min_gain_shift_of(total, hp: SplitHyper):
    """Parent gain + min_gain_to_split: the bar every candidate must clear
    (GetLeafSplitGain on the leaf totals)."""
    tg, th = total[0], total[1] + 2.0 * K_EPSILON
    l1, l2, mds = hp.lambda_l1, hp.lambda_l2, hp.max_delta_step
    parent_out = _calc_output(tg, th, l1, l2, mds)
    return (_gain_given_output(tg, th, l1, l2, parent_out)
            + hp.min_gain_to_split)


def find_best_split_impl(flat_hist, total, constraint, feature_mask,
                         meta: FeatureMeta, hp: SplitHyper, has_cat: bool):
    """The full serial chain (also the per-shard body for feature-parallel;
    shard-level reduction happens in the caller)."""
    shift = min_gain_shift_of(total, hp)
    fh = feature_histograms(flat_hist, total, meta)
    pf = per_feature_best(fh, total, constraint, meta, hp, has_cat, shift)
    feat_gain = masked_feature_gain(pf, meta, feature_mask, shift)
    best_f = jnp.argmax(feat_gain)
    return pack_best(best_f, feat_gain, pf, total, constraint, hp, meta)


def find_best_split_quant(flat_hist, total, scales, constraint,
                          feature_mask, meta: FeatureMeta, hp: SplitHyper,
                          has_cat: bool):
    """Quantized-unit serial chain (``grad_quant_bits=8``): the int32
    [g_q, h_q, count] histogram stays INTEGER through default-bin
    reconstruction and every prefix sum — both numerical scan variants
    and both categorical scan directions — and is dequantized only at
    the gain / leaf-output math.  Counts never leave the integer
    domain, so the histogram-subtraction trick and leaf totals are
    exact (the f32 path's accumulation-order sensitivity disappears).

    ``flat_hist`` (S, 3) int32, ``total`` (3,) int32 quantized units,
    ``scales`` (2,) f32 [scale_g, scale_h].  Returns (packed (13,) f32
    real units, cat_member (256,) bool, left_int (3,) int32 — the
    winner's exact quantized-unit left-child totals; the caller derives
    the right child by integer subtraction from the parent total).

    Overflow contract: every intermediate is bounded by |sum| <=
    127 * num_data, so int32 is exact for num_data <=
    ``ops.grow.INT32_SCAN_ROWS``; larger datasets keep the dequantized
    f32 scan (ROUND8_NOTES.md)."""
    svec = jnp.concatenate([scales, jnp.ones((1,), jnp.float32)])
    total_f = total.astype(jnp.float32) * svec
    shift = min_gain_shift_of(total_f, hp)
    fh = feature_histograms(flat_hist, total, meta)      # int32 exact
    pf = per_feature_best(fh, total_f, constraint, meta, hp, has_cat,
                          shift, scales=svec)
    feat_gain = masked_feature_gain(pf, meta, feature_mask, shift)
    best_f = jnp.argmax(feat_gain)
    packed, catm = pack_best(best_f, feat_gain, pf, total_f, constraint,
                             hp, meta, scales=svec)
    return packed, catm, pf.left[best_f]


def find_best_split_stack(hists, totals, constraint, feature_mask,
                          meta: FeatureMeta, hp: SplitHyper,
                          has_cat: bool, scales=None):
    """vmapped gain scan over a (B, S, 3) histogram STACK — the device
    grower's per-wave reduction unit.  Under ``find_best_fusion=fused``
    the wave calls this once on the fresh histogram product and once on
    the parent-minus-sibling residual, so the two stacks are consumed
    IN PLACE by the same traced program that produced them and no
    concatenated ``(2 * wave, slots, 3)`` tensor ever materializes
    between the histogram contraction and the scan; the two-pass layout
    calls it once on the concatenated stack.  vmap semantics are
    per-lane, so the halves are bitwise the rows the concatenated scan
    yields — this shared body is what makes the fused/two-pass
    byte-identity contract structural rather than numerical.

    ``scales`` switches to the quantized-unit scan
    (:func:`find_best_split_quant`); the third return is then the (B, 3)
    exact integer left totals, else None."""
    if scales is not None:
        packed, catm, lint = jax.vmap(
            lambda h, t: find_best_split_quant(
                h, t, scales, constraint, feature_mask, meta, hp,
                has_cat))(hists, totals)
        return packed, catm, lint
    packed, catm = jax.vmap(
        lambda h, t: find_best_split_impl(
            h, t, constraint, feature_mask, meta, hp, has_cat))(
        hists, totals)
    return packed, catm, None


@functools.partial(jax.jit, static_argnames=("has_cat",))
def _find_best_split(flat_hist, total, constraint, feature_mask,
                     meta: FeatureMeta, hp: SplitHyper, has_cat: bool):
    return find_best_split_impl(flat_hist, total, constraint, feature_mask,
                                meta, hp, has_cat)


_find_best_split = obs.track_jit("find_best_split", _find_best_split)


class SplitContext:
    """Static per-dataset device metadata + the jitted best-split kernel.

    One instance per (dataset, config); reused across all leaves and trees.
    """

    def __init__(self, dataset, config):
        self.num_features = dataset.num_features
        self.has_categorical = bool(
            np.asarray(dataset.f_is_categorical).any())
        self.meta = FeatureMeta.from_dataset(dataset)
        self.hyper = SplitHyper.from_config(config)

    def find_best(self, flat_hist, total, constraint, feature_mask):
        """flat_hist (G*256, 3); total (3,) [g,h,c]; constraint (2,)
        [min,max]; feature_mask (F,) bool.  Returns (packed (13,) f32 — see
        F_* indices — and cat-member mask (256,) bool) as device values
        (fetch async)."""
        return _find_best_split(
            flat_hist, jnp.asarray(total, jnp.float32),
            jnp.asarray(constraint, jnp.float32), feature_mask,
            self.meta, self.hyper, self.has_categorical)


def find_best_split(ctx: SplitContext, flat_hist, total, constraint,
                    feature_mask) -> Dict:
    return ctx.find_best(flat_hist, total, constraint, feature_mask)
