"""Fully on-device wave-synchronized leaf-wise tree growth.

Why this exists: the host-driven learner (``tree/learner.py``) needs one
host<->device round trip per split.  On real TPU hardware behind a network
tunnel that round trip measures ~120 ms and async dispatch ~1 ms, so a
255-leaf tree costs ~30 s in latency alone — three orders of magnitude over
the compute.  Measurement also shows every irregular memory op on TPU
(gather ~10-50 ns/elem, scatter/sort ~30 ns/elem) runs far below HBM
bandwidth, which rules out the reference's index-permutation design
(``DataPartition``, ``dense_bin.hpp:106-175``) entirely: maintaining sorted
leaf windows costs more than the histograms they would save.

The TPU-native formulation is **dense**:

* a per-row ``leaf_id`` vector replaces the row permutation; a split
  updates it with one elementwise pass over a contiguous feature column
  (the ``(G, N)`` transposed copy of the binned matrix);
* histograms for a whole *wave* of fresh leaves are built in ONE pass over
  all rows: per feature-group, ``one_hot(bins) . (leaf_mask x [g,h,1])`` —
  the leaf-mask columns widen the matmul's N dimension to fill the MXU's
  128-lane tiles (a single leaf's 3 stat columns would waste 97% of them);
* the gradient operand is split hi/lo into two bfloat16 columns whose
  float32-accumulated sum reconstructs float32-accurate histograms at
  bfloat16 matmul speed (counts are exact: 0/1 products, f32 accumulation);
* growth is best-first like the reference (``serial_tree_learner.cpp:
  157-221``) but *wave-synchronized*: each wave evaluates the newest leaves
  (smaller sibling by direct histogram, larger by parent subtraction,
  ``serial_tree_learner.cpp:508-513``) and then applies up to ``wave_width``
  best-gain splits.  With an unlimited wave budget this is exactly
  leaf-wise order except near the num_leaves budget boundary, where the
  reference might prefer a just-created child over an older leaf; waves
  only batch *independent* splits, never reorder by gain.
* the whole tree grows inside one ``lax.while_loop`` — a boosting
  iteration is ONE device dispatch with nothing fetched; split records are
  copied to host asynchronously and replayed into ``Tree`` objects lazily.

Supports: numerical features, missing-value routing (None/Zero/NaN),
categorical optimal splits (the winning category set travels as an
8-word bin bitset), feature_fraction masks, bagging/GOSS via a 0/1
row-mask column, multiclass (one dispatch per class),
L1/L2/max_delta_step, DART/RF (driven from boosting/).  Still host-only:
monotone constraints, forced splits, renew-tree-output objectives.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from .split import (F_DEFAULT_LEFT, F_FEATURE, F_GAIN, F_IS_CAT, F_LEFT_C,
                    F_LEFT_G, F_LEFT_H, F_LEFT_OUT, F_RIGHT_C, F_RIGHT_G,
                    F_RIGHT_H, F_RIGHT_OUT, F_THRESHOLD, FeatureMeta,
                    NEG_INF, SplitHyper, find_best_split_impl)

# rows per histogram chunk: large chunks amortize MXU ramp-up; the
# per-chunk one-hot (CH, G, NB) bf16 stays fusable into the dot operand
import os as _os
_CHUNK = int(_os.environ.get("LGBM_TPU_CHUNK", 32768))

# record field layout (host replay reads these)
REC_I_FIELDS = 5    # leaf, right, feature, threshold, default_left
REC_F_FIELDS = 9    # gain, lg, lh, lc, rg, rh, rc, left_out, right_out

# above this many rows a single f32 count cell can exceed 2^24 and lose
# integer exactness; the wave matmul then carries TWO striped count
# columns (each stripe < 2^24 rows, summed after accumulation — final
# count error <= 1 ulp instead of unbounded drift).  Module-level so
# tests can force the striped path on small data.
COUNT_SPLIT_ROWS = 1 << 24




def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def feature_fraction_mask(seed: int, tree_idx, nf: int, k: int):
    """(nf,) bool mask selecting ``k`` features without replacement:
    ``fold_in(PRNGKey(seed), tree_idx)`` then the k smallest of nf
    uniforms.  Shared by the per-iteration device path and the fused
    scan (``tree_idx`` may be traced) so both draw bit-identical masks
    for the same global tree index — the property the fused-parity
    tests pin."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), tree_idx)
    u = jax.random.uniform(key, (nf,))
    thr = jnp.sort(u)[k - 1]
    return u <= thr


def _combine_hist_cols(h, k: int):
    """Collapse the K accumulated stat columns (last axis) to [g, h, cnt].
    K=3: passthrough.  K=4: striped counts summed.  K=5: hi/lo g,h.
    K=6: hi/lo g,h + striped counts."""
    import jax.numpy as _jnp
    if k == 5:
        return _jnp.stack([h[..., 0] + h[..., 1], h[..., 2] + h[..., 3],
                           h[..., 4]], axis=-1)
    if k == 4:
        # each stripe accumulated < 2^24 rows exactly; the sum is exact
        # to <= 1 ulp at up to 2 * COUNT_SPLIT_ROWS rows
        return _jnp.stack([h[..., 0], h[..., 1], h[..., 2] + h[..., 3]],
                          axis=-1)
    if k == 6:
        return _jnp.stack([h[..., 0] + h[..., 1], h[..., 2] + h[..., 3],
                           h[..., 4] + h[..., 5]], axis=-1)
    return h


class DeviceGrower:
    """Grows whole trees on device; one dispatch per boosting iteration.

    Parameters mirror the serial learner's (dataset, config) pair.  The
    instance owns device copies of the binned matrix in both layouts and
    the jitted grow function (compiled once per dataset/config shape).
    """

    def __init__(self, dataset, config):
        self.config = config
        self.dataset = dataset
        self.num_data = int(dataset.num_data)
        self.num_groups = int(dataset.num_groups)
        self.num_leaves = int(config.num_leaves)

        # per-group slot pitch: smallest power of two covering every group
        nb = 64
        for g in dataset.groups:
            while g.num_total_bin > nb:
                nb *= 2
        self.nb = nb
        self.num_slots = self.num_groups * nb

        self.n_pad = _ceil_to(max(self.num_data, _CHUNK), _CHUNK)
        pad = self.n_pad - self.num_data
        if getattr(dataset, "device_binned", False):
            # matrix already lives in HBM (construct_from_device_matrix)
            binned_d = dataset.binned
            if pad:
                binned_d = jnp.pad(binned_d, ((0, pad), (0, 0)))
            self.binned = binned_d
        else:
            binned = np.asarray(dataset.binned)  # (N, G) uint8
            if pad:
                binned = np.pad(binned, ((0, pad), (0, 0)))
            self.binned = jnp.asarray(binned)
        # the (G, N) copy is a device-side transpose: uploading it
        # separately doubled the host->device transfer and the host
        # ascontiguousarray pass (~seconds at 10M rows)
        self.binned_t = jnp.transpose(self.binned)

        self.meta = FeatureMeta.from_dataset(dataset, slot_stride=nb)
        self.hyper = SplitHyper.from_config(config)
        # per-feature partition tables (device)
        i32 = lambda a: jnp.asarray(np.asarray(a, np.int32))
        nbins = np.asarray(dataset.f_num_bin, np.int64)
        dbins = np.asarray(dataset.f_default_bin, np.int64)
        self.p_group = i32(dataset.f_group)
        self.p_offset = i32(dataset.f_offset)
        self.p_width = i32(nbins - (dbins == 0))
        self.p_default_bin = i32(dbins)
        self.p_num_bin = i32(nbins)
        self.p_missing = i32(dataset.f_missing_type)

        # stat columns per leaf in the wave matmul.  Default 3 — bf16
        # g/h + exact count: per-term bf16 rounding (rel ~2^-8) is
        # uncorrelated across a bin's rows, so bin sums stay accurate to
        # ~1e-5 relative (measured; cf. the reference GPU learner's f32
        # histograms, docs/GPU-Performance.rst:128-161).  gpu_use_dp
        # restores the hi/lo split (g,h each as two bf16 columns whose
        # f32-accumulated sum reconstructs f32-exact values).
        dp = bool(getattr(config, "gpu_use_dp", False))
        striped = self.num_data >= COUNT_SPLIT_ROWS
        if dp:
            # 6 = hi/lo g,h + striped counts: dp must not reintroduce
            # the single-column count overflow it exists to avoid
            self.hist_cols = 6 if striped else 5
        else:
            self.hist_cols = 4 if striped else 3
        # Wave cost measured on the chip (scripts/ubench_hist.py,
        # 10.5M rows): ~15.9 ms fixed (the one-hot operand generation
        # over all N, width-independent) + ~0.203 ms per stat column —
        # LINEAR in columns, not column-tile-quantized, and 72% of MXU
        # peak at 2 tiles (hist3_w84: 67.1 ms, 141.7 TF).  Since a wave
        # can split at most the current frontier, the cheapest plan
        # width-matches each stage to the frontier (doubling) and ends
        # with one very wide multi-tile wave for the tail: for L=255,
        # [4,16,32,64,128] costs ~290 ms/tree of histogram vs ~355 for
        # the old single-tile cap at W=42.  gpu_use_dp (k=5) scales each
        # width down by 3/k to hold the column budget.
        scale = 3.0 / self.hist_cols
        wmax = max(int(128 * scale), 4)
        self.wave_width = min(wmax, max(self.num_leaves - 1, 1))
        self.stage_plan = [
            (ws, cap) for ws, cap in
            ((4, 8), (16, 32), (max(int(32 * scale), 4), 64),
             (max(int(64 * scale), 4), 128))
            if ws < self.wave_width and cap < self.num_leaves
        ] + [(self.wave_width, None)]
        # hist_kernel: "auto"/"einsum" use the XLA einsum formulation —
        # the best measured (both Pallas kernels lost to it, see
        # ops/hist_pallas.py); "pallas" opts into the VMEM kernel on
        # hardware, "interpret" runs it in interpreter mode (CPU tests).
        mode = str(getattr(config, "hist_kernel", "auto")
                   or "auto").lower()
        self.pallas_interpret = mode == "interpret"
        # v1 of the Pallas kernel measured 2x slower than the einsum
        # (108.9 vs 53.9 ms/tree, 1M-row quick bench) - grid-step and
        # block-layout overheads dominate at ch<=1024 VMEM budgets - so
        # auto stays on the einsum until the kernel beats it
        self.use_pallas = mode in ("pallas", "interpret")
        self.lr = float(config.learning_rate)
        # recompile tracking: every fresh DeviceGrower owns fresh jit
        # caches, so in the retrain-every-window pattern each window
        # recompiles these — obs.track_jit counts and attributes that
        # per shape signature (near-free when obs is disabled)
        self._grow = obs.track_jit(
            "grow", jax.jit(functools.partial(self._grow_impl,
                                              with_mask=False)))
        self._grow_masked = obs.track_jit(
            "grow_masked", jax.jit(functools.partial(self._grow_impl,
                                                     with_mask=True)))
        self._fused = {}   # scan length -> jitted multi-iteration program
        # sampling state for device-side draws (feature_fraction masks,
        # fused bagging): seeds mirror the host learner's derivation
        # (learner.py _rng / GBDT.bagging) so fused and per-iteration
        # paths stay bit-identical
        self._ff_frac = float(config.feature_fraction)
        nf = int(dataset.num_features)
        self._ff_nf = nf
        self._ff_k = max(1, int(np.ceil(nf * self._ff_frac)))
        self._ff_seed = int(config.feature_fraction_seed
                            if config.feature_fraction_seed
                            else config.seed + 2) & 0x7FFFFFFF
        self._bag_fraction = float(config.bagging_fraction)
        self._bag_freq = int(config.bagging_freq)
        self._bag_seed = int(config.bagging_seed) & 0x7FFFFFFF
        from .histogram import bucket_size
        self._bag_npad = bucket_size(max(self.num_data, 1))

    # ------------------------------------------------------------------
    def feature_mask_for(self, tree_idx):
        """Deterministic per-tree feature_fraction mask (device array).
        ``tree_idx`` is the global tree index (iter * num_model + k);
        accepts traced values inside the fused scan."""
        if self._ff_frac >= 1.0 or self._ff_nf <= 1:
            return jnp.ones(self._ff_nf, dtype=bool)
        return feature_fraction_mask(self._ff_seed, tree_idx,
                                     self._ff_nf, self._ff_k)

    # ------------------------------------------------------------------
    # wave histogram: one dense pass for up to W pending leaves
    # ------------------------------------------------------------------
    def _wave_hist(self, binned, leaf_id, ghk, pending):
        """(n_pad,) leaf ids, (n_pad, K) bf16 stat columns (K=3:
        [g,h,1]; K=5: [g_hi,g_lo,h_hi,h_lo,1]), (W,) pending leaf ids
        (-1 = empty slot) -> (W, S, 3) f32.

        The one-hot must stay a bare iota-compare so XLA fuses its
        generation into the dot operand (a multi-hot built as
        ``one_hot(..).sum()`` materializes in HBM measured 3.5x slower;
        fusing the leaf-id split application into this scan also measured
        2x slower - the extra data dependency breaks matmul pipelining)."""
        g, nb = self.num_groups, self.nb
        w = pending.shape[0]
        k = self.hist_cols
        if self.use_pallas and w == self.wave_width and w * k <= 128:
            # the VMEM kernel packs all stat columns into one 128-lane
            # tile; wider (multi-tile) waves stay on the einsum
            # full-width stage: MXU cost is tile-bound regardless of W,
            # so the VMEM-resident kernel wins; narrow early stages stay
            # on the einsum (XLA lowers small-N contractions cheaper)
            from .hist_pallas import wave_hist_pallas
            out = wave_hist_pallas(binned, leaf_id, ghk, pending,
                                   g=g, nb=nb, k=k, w=w,
                                   interpret=self.pallas_interpret)
            h = out.reshape(g, nb, k, w).transpose(3, 0, 1, 2) \
                .reshape(w, self.num_slots, k)
            return _combine_hist_cols(h, k)
        ch = _CHUNK
        n_chunks = self.n_pad // ch
        binned_c = binned.reshape(n_chunks, ch, g)
        leaf_c = leaf_id.reshape(n_chunks, ch)
        ghk_c = ghk.reshape(n_chunks, ch, k)

        def body(acc, xs):
            b, l, gk = xs
            lm = (l[:, None] == pending[None, :]).astype(jnp.bfloat16)
            bmat = (lm[:, :, None] * gk[:, None, :]).reshape(ch, w * k)
            # bin tiling: a one-hot wider than 64 breaks XLA's
            # operand fusion (max_bin=255 measured 10x the max_bin=63
            # wave, not the expected 4x) — strips of 64 keep each
            # einsum in the known-fused regime; out-of-strip bins make
            # all-zero one-hot rows, so the concat reassembles exactly
            bi = b.astype(jnp.int32)
            outs = []
            for off in range(0, nb, 64):
                oh = jax.nn.one_hot(bi - off, min(nb, 64),
                                    dtype=jnp.bfloat16)        # (CH,G,64)
                outs.append(jnp.einsum("cgn,cb->gnb", oh, bmat,
                                       preferred_element_type=jnp.float32))
            out = outs[0] if len(outs) == 1 \
                else jnp.concatenate(outs, axis=1)
            return acc + out, None

        acc0 = jnp.zeros((g, nb, w * k), jnp.float32)
        acc, _ = jax.lax.scan(body, acc0, (binned_c, leaf_c, ghk_c))
        acc = acc.reshape(g, nb, w, k)
        hist = _combine_hist_cols(acc, k)                        # (G,NB,W,3)
        return hist.transpose(2, 0, 1, 3).reshape(w, self.num_slots, 3)

    # ------------------------------------------------------------------
    def _leaf_output(self, g, h):
        hp = self.hyper
        s = jnp.sign(g) * jnp.maximum(jnp.abs(g) - hp.lambda_l1, 0.0)
        out = -s / (h + hp.lambda_l2 + 1e-35)
        clipped = jnp.clip(out, -hp.max_delta_step, hp.max_delta_step)
        return jnp.where(hp.max_delta_step <= 0.0, out, clipped)

    def _splittable(self, total, depth):
        cfg = self.config
        ok = (total[..., 2] > 2 * cfg.min_data_in_leaf) \
            & (total[..., 1] > 2 * cfg.min_sum_hessian_in_leaf)
        if cfg.max_depth > 0:
            ok = ok & (depth < cfg.max_depth)
        return ok

    # ------------------------------------------------------------------
    def _grow_impl(self, binned, binned_t, score, grad, hess, feature_mask,
                   lr, row_mask, *, with_mask):
        """One boosting iteration on device.  Returns (new_score, rec_i
        (L-1,5) i32, rec_f (L-1,9) f32, num_leaves i32, root_value f32).
        ``lr`` is traced so callbacks may reset the learning rate without
        recompiling.  The binned matrices are arguments, not closures: a
        closed-over array becomes an XLA constant and ships inside the
        compile request (fatal at 10M-row scale on a remote-compile
        backend)."""
        L, W, S = self.num_leaves, self.wave_width, self.num_slots
        n = self.n_pad
        npad_rows = n - self.num_data

        grad = jnp.pad(grad, (0, npad_rows))
        hess = jnp.pad(hess, (0, npad_rows))
        one_f = jnp.where(jnp.arange(n) < self.num_data, 1.0, 0.0)
        if with_mask:
            # bagging/GOSS: 0/1 in-bag indicator. Out-of-bag rows drop out
            # of histograms and counts (their grad/hess are already zeroed
            # by the caller) but still get leaf-routed, so the score
            # update reaches them - the reference's OOB traversal update
            # (gbdt.cpp:451-471) falls out for free.
            one_f = one_f * jnp.pad(row_mask, (0, npad_rows))
        one = one_f.astype(jnp.bfloat16)
        ghi = grad.astype(jnp.bfloat16)
        hhi = hess.astype(jnp.bfloat16)
        k = self.hist_cols
        if k in (5, 6):
            glo = (grad - ghi.astype(jnp.float32)).astype(jnp.bfloat16)
            hlo = (hess - hhi.astype(jnp.float32)).astype(jnp.bfloat16)
            gcols = [ghi * one, glo * one, hhi * one, hlo * one]
        else:
            gcols = [ghi * one, hhi * one]
        if k in (4, 6):
            # two striped count columns (< 2^24 rows each) keep counts
            # integer-exact beyond the single-column f32 limit
            stripe = (jnp.arange(n) < (n // 2)).astype(jnp.bfloat16)
            gcols += [one * stripe, one * (1.0 - stripe)]
        else:
            gcols += [one]
        gh5 = jnp.stack(gcols, 1)

        leaf_id0 = jnp.where(jnp.arange(n, dtype=jnp.int32) < self.num_data,
                             0, -1)

        class _S(NamedTuple):
            leaf_id: jnp.ndarray        # (n,) i32
            hist: jnp.ndarray           # (L+1, S, 3) f32
            total: jnp.ndarray          # (L+1, 3) f32
            value: jnp.ndarray          # (L+1,) f32
            depth: jnp.ndarray          # (L+1,) i32
            best: jnp.ndarray           # (L+1, 13) f32, gain NEG_INF if none
            bestc: jnp.ndarray          # (L+1, 256) bool cat membership
            nl: jnp.ndarray             # i32 leaves so far
            waves: jnp.ndarray          # i32 wave count (profiling)
            done: jnp.ndarray           # bool
            rec_i: jnp.ndarray          # (L, 5) i32   (last row = junk)
            rec_f: jnp.ndarray          # (L, 9) f32   (last row = junk)
            rec_c: jnp.ndarray          # (L, 8) i32   cat bin bitsets
            p_parent: jnp.ndarray       # (W,) i32  parent slot (-1 empty)
            p_small: jnp.ndarray        # (W,) i32  leaf whose hist is fresh
            p_large: jnp.ndarray        # (W,) i32  sibling (subtraction)

        # every per-leaf array carries one junk slot (index L; records:
        # index L-1) absorbing vector-scatter writes from empty lanes, so
        # scatters never collide with live leaves
        neg = jnp.full((L + 1, 13), NEG_INF, jnp.float32)
        W0 = min(4, W) if (4 < W and 8 < L) else W   # first stage width
        init = _S(
            leaf_id=leaf_id0,
            hist=jnp.zeros((L + 1, S, 3), jnp.float32),
            total=jnp.zeros((L + 1, 3), jnp.float32),
            value=jnp.zeros((L + 1,), jnp.float32),
            depth=jnp.zeros((L + 1,), jnp.int32),
            best=neg,
            bestc=jnp.zeros((L + 1, 256), bool),
            nl=jnp.asarray(1, jnp.int32),
            waves=jnp.asarray(0, jnp.int32),
            done=jnp.asarray(False),
            rec_i=jnp.full((L, REC_I_FIELDS), -1, jnp.int32),
            rec_f=jnp.zeros((L, REC_F_FIELDS), jnp.float32),
            rec_c=jnp.zeros((L, 8), jnp.int32),
            p_parent=jnp.full((W0,), -1, jnp.int32),
            p_small=jnp.concatenate([jnp.zeros(1, jnp.int32),
                                     jnp.full((W0 - 1,), -1, jnp.int32)])
            if W0 > 1 else jnp.zeros((1,), jnp.int32),
            p_large=jnp.full((W0,), -1, jnp.int32),
        )

        has_cat = bool(np.asarray(
            self.dataset.f_is_categorical).any())
        find_one = functools.partial(find_best_split_impl, meta=self.meta,
                                     hp=self.hyper, has_cat=has_cat)

        def evaluate(hists, totals, ids, depths, feature_mask):
            """vmapped find-best over fresh leaves; gated by splittability.
            Returns (packed (B,13), cat_member (B,256) bool)."""
            cons = jnp.asarray([-jnp.inf, jnp.inf], jnp.float32)
            packed, catm = jax.vmap(
                lambda h, t: find_one(h, t, cons, feature_mask))(hists,
                                                                 totals)
            ok = self._splittable(totals, depths) & (ids >= 0)
            gain = jnp.where(ok, packed[:, F_GAIN], NEG_INF)
            return packed.at[:, F_GAIN].set(gain), catm

        def make_wave(Ws: int):
          def wave(st: _S) -> _S:
            # 1. fresh histograms for pending smaller children
            fresh = self._wave_hist(binned, st.leaf_id, gh5,
                                    st.p_small)               # (W,S,3)
            root_wave = st.p_parent[0] < 0
            # root total from group-0 slot sums (every row hits one slot)
            root_total = fresh[0, :self.nb, :].sum(0)
            total = jnp.where(
                root_wave & (st.p_small[0] == 0),
                st.total.at[0].set(root_total), st.total)
            # 2. larger sibling = parent - smaller (parent hist still lives
            # at the parent's slot; smaller may reuse that slot, so read
            # parents BEFORE writing fresh)
            par = jnp.where(st.p_parent >= 0, st.p_parent, L)
            large = st.hist[par] - fresh                          # (W,S,3)
            sm_ok = st.p_small >= 0
            lg_ok = st.p_large >= 0
            sm_idx = jnp.where(sm_ok, st.p_small, L)
            lg_idx = jnp.where(lg_ok, st.p_large, L)
            hist = st.hist.at[sm_idx].set(
                jnp.where(sm_ok[:, None, None], fresh, st.hist[sm_idx]))
            hist = hist.at[lg_idx].set(
                jnp.where(lg_ok[:, None, None], large, hist[lg_idx]))
            # root value (stump case + records)
            value = jnp.where(
                root_wave,
                st.value.at[0].set(self._leaf_output(total[0, 0],
                                                     total[0, 1])),
                st.value)

            # 3. find-best for the new leaves (both siblings); reuse the
            # fresh/large buffers rather than re-gathering from hist
            ids = jnp.concatenate([jnp.where(sm_ok, st.p_small, -1),
                                   jnp.where(lg_ok, st.p_large, -1)])
            hists2 = jnp.concatenate([fresh, large])
            idc = jnp.clip(ids, 0, L - 1)
            packed, catm = evaluate(hists2, total[idc], ids,
                                    st.depth[idc], feature_mask)
            safe = jnp.where(ids >= 0, ids, L)
            best = st.best.at[safe].set(
                jnp.where((ids >= 0)[:, None], packed, st.best[safe]))
            bestc = st.bestc.at[safe].set(
                jnp.where((ids >= 0)[:, None], catm, st.bestc[safe]))

            # 4. select up to Ws best-gain splits within budget
            gains = best[:L, F_GAIN]
            top_vals, top_idx = jax.lax.top_k(gains, Ws)
            budget = (L - st.nl).astype(jnp.int32)
            sel = (top_vals > 0.0) & (jnp.arange(Ws) < budget)
            napply = sel.sum().astype(jnp.int32)
            rank = jnp.cumsum(sel.astype(jnp.int32)) - 1

            # 5. apply all selected splits at once.  Selected leaves are
            # distinct (top_k) and so are the new right ids, so scatters
            # can't collide; invalid lanes are routed to the junk rows.
            lsel = top_idx.astype(jnp.int32)                  # (W,)
            vecs = best[lsel]                                 # (W,13)
            r_ids = st.nl + rank                              # (W,)
            f = vecs[:, F_FEATURE].astype(jnp.int32)
            thr = vecs[:, F_THRESHOLD].astype(jnp.int32)
            dl = vecs[:, F_DEFAULT_LEFT] > 0.5
            grp = self.p_group[f]
            off = self.p_offset[f]
            wid = self.p_width[f]
            db = self.p_default_bin[f]
            nbin = self.p_num_bin[f]
            miss = self.p_missing[f]
            def_left = jnp.where(miss == 1, dl, db <= thr)    # (W,)

            # leaf_id update: ONE fused vectorized pass over the W
            # selected feature rows of the contiguous (G, N) matrix
            # (replaces r3's W-times-unrolled dynamic-slice loop, which
            # re-read leaf_id and re-wrote the update vector per split).
            # Masks are disjoint (a row belongs to at most one selected
            # leaf), so the masked deltas sum without collisions.  All
            # values are group-local bins (< nb <= 256), so the whole
            # (W, N) chain runs in int16 — at W=128 the materialized
            # intermediates drop from ~5.4 GB to ~2.7 GB of HBM traffic.
            i16 = lambda a: a.astype(jnp.int16)
            cols = i16(jnp.take(binned_t, grp, axis=0))           # (W,N)
            off16, wid16 = i16(off)[:, None], i16(wid)[:, None]
            db16, nbin16 = i16(db)[:, None], i16(nbin)[:, None]
            thr16 = i16(thr)[:, None]
            shift = jnp.where(db16 == 0, jnp.int16(1), jnp.int16(0))
            in_range = (cols >= off16) & (cols < off16 + wid16)
            bin_ = jnp.where(in_range, cols - off16 + shift, db16)
            is_default = bin_ == db16
            is_na = (miss[:, None] == 2) & (bin_ == nbin16 - 1)
            goes_left = jnp.where(is_default, def_left[:, None],
                                  jnp.where(is_na, dl[:, None],
                                            bin_ <= thr16))
            if has_cat:
                # categorical routing: left iff the decoded bin is in the
                # winning category set (partition.py:49 semantics); the
                # (W,256) membership is packed into 8 x i32 words and the
                # per-row word picked with an 8-way select chain (a
                # table gather here measured far slower on TPU)
                cm = bestc[jnp.clip(lsel, 0, L)]            # (W, 256)
                cmw = jnp.sum(
                    cm.reshape(Ws, 8, 32).astype(jnp.int32)
                    << jnp.arange(32, dtype=jnp.int32)[None, None, :],
                    axis=-1)                                # (W, 8)
                binc = bin_.astype(jnp.int32)   # 32-bit word arithmetic
                widx = binc >> 5
                bit = binc & 31
                wv = jnp.zeros_like(binc)
                for j in range(8):
                    wv = wv + jnp.where(widx == j, cmw[:, j:j + 1], 0)
                left_cat = ((wv >> bit) & 1) == 1
                is_cat_w = vecs[:, F_IS_CAT] > 0.5
                goes_left = jnp.where(is_cat_w[:, None], left_cat,
                                      goes_left)
            mask = (sel[:, None] & (st.leaf_id[None, :] == lsel[:, None])
                    & ~goes_left)
            upd = jnp.sum(mask * (r_ids - lsel)[:, None], axis=0,
                          dtype=jnp.int32)
            leaf_id = st.leaf_id + upd

            # bookkeeping (vectorized scatters into the L-padded arrays)
            safe_l = jnp.where(sel, lsel, L)
            safe_r = jnp.where(sel, r_ids, L)
            lsum = vecs[:, jnp.asarray([F_LEFT_G, F_LEFT_H, F_LEFT_C])]
            rsum = vecs[:, jnp.asarray([F_RIGHT_G, F_RIGHT_H, F_RIGHT_C])]
            total = total.at[safe_l].set(
                jnp.where(sel[:, None], lsum, total[safe_l]))
            total = total.at[safe_r].set(
                jnp.where(sel[:, None], rsum, total[safe_r]))
            value = value.at[safe_l].set(
                jnp.where(sel, vecs[:, F_LEFT_OUT], value[safe_l]))
            value = value.at[safe_r].set(
                jnp.where(sel, vecs[:, F_RIGHT_OUT], value[safe_r]))
            child_d = st.depth[jnp.clip(lsel, 0, L)] + 1
            depth = st.depth.at[safe_l].set(
                jnp.where(sel, child_d, st.depth[safe_l]))
            depth = depth.at[safe_r].set(
                jnp.where(sel, child_d, depth[safe_r]))
            best = best.at[safe_l].set(
                jnp.where(sel[:, None], neg[0][None, :], best[safe_l]))
            best = best.at[safe_r].set(
                jnp.where(sel[:, None], neg[0][None, :], best[safe_r]))
            # split records (rows are padded by one junk row at index L-1)
            ridx = jnp.where(sel, st.nl - 1 + rank, L - 1)
            new_ri = jnp.stack([lsel, r_ids, f, thr,
                                dl.astype(jnp.int32)], axis=1)
            new_rf = jnp.stack(
                [vecs[:, F_GAIN], vecs[:, F_LEFT_G], vecs[:, F_LEFT_H],
                 vecs[:, F_LEFT_C], vecs[:, F_RIGHT_G], vecs[:, F_RIGHT_H],
                 vecs[:, F_RIGHT_C], vecs[:, F_LEFT_OUT],
                 vecs[:, F_RIGHT_OUT]], axis=1)
            rec_i = st.rec_i.at[ridx].set(
                jnp.where(sel[:, None], new_ri, st.rec_i[ridx]))
            rec_f = st.rec_f.at[ridx].set(
                jnp.where(sel[:, None], new_rf, st.rec_f[ridx]))
            if has_cat:
                rec_c = st.rec_c.at[ridx].set(
                    jnp.where(sel[:, None], cmw, st.rec_c[ridx]))
            else:
                rec_c = st.rec_c
            # pending for the next wave
            small_left = vecs[:, F_LEFT_C] <= vecs[:, F_RIGHT_C]
            pp = jnp.where(sel, lsel, -1)
            ps = jnp.where(sel, jnp.where(small_left, lsel, r_ids), -1)
            pl = jnp.where(sel, jnp.where(small_left, r_ids, lsel), -1)

            return _S(leaf_id=leaf_id, hist=hist, total=total, value=value,
                      depth=depth, best=best, bestc=bestc,
                      nl=st.nl + napply,
                      waves=st.waves + 1, done=napply == 0,
                      rec_i=rec_i, rec_f=rec_f, rec_c=rec_c,
                      p_parent=pp, p_small=ps, p_large=pl)
          return wave

        # staged wave widths: the early frontier has 1 -> 2 -> 4 -> ...
        # pending leaves, so a full-width wave wastes almost its whole
        # column tile on empty lanes (the matmul cost is W x hist_cols
        # columns regardless of how many are live).  Growing the width
        # with the frontier cuts the early waves' cost ~5-10x; each stage
        # is its own while_loop over the same state with the pending
        # arrays padded to the next width.
        def resize(st: _S, w_to: int) -> _S:
            pad = w_to - st.p_parent.shape[0]
            if pad <= 0:
                return st
            ext = jnp.full((pad,), -1, jnp.int32)
            return st._replace(
                p_parent=jnp.concatenate([st.p_parent, ext]),
                p_small=jnp.concatenate([st.p_small, ext]),
                p_large=jnp.concatenate([st.p_large, ext]))

        plan = self.stage_plan
        st = init
        for ws, cap in plan:
            st = resize(st, ws)
            limit = L if cap is None else min(cap, L)
            st = jax.lax.while_loop(
                lambda s, lim=limit: (~s.done) & (s.nl < lim),
                make_wave(ws), st)
        final = st
        leaf_final = final.leaf_id

        # score update: score[row] += lr * value[leaf_id[row]] via one-hot
        # matmul (hi/lo split keeps f32-level precision at bf16 speed).
        # A stump (root never split) applies nothing: the boosting driver
        # treats it as the stop signal, matching GBDT::TrainOneIter.
        scaled = final.value[:L] * lr * (final.nl > 1)
        vhi = scaled.astype(jnp.bfloat16)
        vlo = (scaled - vhi.astype(jnp.float32)).astype(jnp.bfloat16)
        vmat = jnp.stack([vhi, vlo], 1)                       # (L, 2)
        oh = jax.nn.one_hot(leaf_final, L, dtype=jnp.bfloat16)
        upd = jnp.einsum("nl,lk->nk", oh, vmat,
                         preferred_element_type=jnp.float32)
        new_score = score + (upd[:, 0] + upd[:, 1])[:self.num_data]

        return (new_score, final.rec_i[:max(L - 1, 1)],
                final.rec_f[:max(L - 1, 1)],
                final.rec_c[:max(L - 1, 1)], final.nl, final.value[0],
                final.waves)

    # ------------------------------------------------------------------
    def grow_one_iter(self, score, grad, hess, feature_mask, lr=None,
                      row_mask=None):
        """Dispatch one boosting iteration; returns device handles
        (new_score, rec_i, rec_f, rec_c, num_leaves, root_value,
        num_waves) without blocking.  ``row_mask`` is an optional (N,)
        f32 0/1 in-bag indicator (bagging / GOSS)."""
        if lr is None:
            lr = self.lr
        obs.inc("grow.dispatches")
        if row_mask is None:
            return self._grow(self.binned, self.binned_t, score, grad,
                              hess, feature_mask,
                              jnp.asarray(lr, jnp.float32),
                              jnp.zeros((0,), jnp.float32))
        return self._grow_masked(self.binned, self.binned_t, score, grad,
                                 hess, feature_mask,
                                 jnp.asarray(lr, jnp.float32), row_mask)


    # ------------------------------------------------------------------
    def fused_train(self, length: int):
        """Jitted program running ``length`` whole boosting iterations in
        ONE device dispatch: gradients -> tree growth -> score update
        inside a ``lax.scan`` over iterations.

        Motivation: the per-iteration path needs ~5 host-side steps per
        tree (gradient dispatch, grow dispatch, score set, record
        copies), and on a loaded host that Python loop starves the
        device — the driver-recorded HIGGS run measured 771 ms/tree vs
        468 ms/tree idle-host for identical device work.  Fusing K
        iterations amortizes every host touch 1/K and makes wall-clock
        track device throughput.

        Sampling lives INSIDE the scan: the per-tree feature_fraction
        mask is ``fold_in(key, tree_idx)`` and the bagging row mask is
        re-drawn every ``bagging_freq`` trees with the per-iteration
        path's exact ``(bagging_seed + it)`` seeding, so the fork
        harness's ``feature_fraction=0.8, bagging_freq=5`` config fuses
        and still emits bit-identical trees (tests/test_fused.py).

        Signature of the returned program::

            run(binned, binned_t, score, lr, gargs, it0, grad_fn=fn)
            -> (final_score,
                (rec_i (K,L-1,5), rec_f (K,L-1,9), rec_c (K,L-1,8),
                 nl (K,), root_value (K,), waves (K,)))

        ``it0`` is the global iteration index of the chunk's first tree
        (traced, so resuming mid-run reuses the compiled program).
        ``grad_fn(score, gargs) -> (grad, hess)`` comes from
        ``ObjectiveFunction.device_grad`` (pure jnp; all arrays via
        ``gargs``).  Compiled once per (length, grad_fn) pair — callers
        must reuse one grad_fn instance to hit the jit cache.
        """
        if length not in self._fused:
            use_bag = self._bag_fraction < 1.0 and self._bag_freq > 0
            bag_freq, bag_seed = self._bag_freq, self._bag_seed
            bag_frac, bag_npad = self._bag_fraction, self._bag_npad

            def run(binned, binned_t, score, lr, gargs, it0, grad_fn):
                no_mask = jnp.zeros((0,), jnp.float32)
                its = jnp.arange(length, dtype=jnp.int32) + it0

                def draw_bag(it):
                    from .bagging import bagging_row_mask
                    return bagging_row_mask(
                        (bag_seed + it) & 0x7FFFFFFF, bag_npad,
                        self.num_data, bag_frac)

                def body(carry, it):
                    sc, bmask = (carry if use_bag else (carry, None))
                    g, h = grad_fn(sc, gargs)
                    fmask = self.feature_mask_for(it)
                    if use_bag:
                        # cond, not where: only redraw steps pay the
                        # (bag_npad,) uniform generation
                        bmask = jax.lax.cond(it % bag_freq == 0,
                                             lambda: draw_bag(it),
                                             lambda: bmask)
                    (new_score, rec_i, rec_f, rec_c, nl, root, waves) = \
                        self._grow_impl(binned, binned_t, sc, g, h,
                                        fmask, lr,
                                        bmask if use_bag else no_mask,
                                        with_mask=use_bag)
                    out = (rec_i, rec_f, rec_c, nl, root, waves)
                    return ((new_score, bmask) if use_bag
                            else new_score), out

                if use_bag:
                    # carry init: the mask active at it0 — drawn at the
                    # last redraw boundary; when it0 itself is a boundary
                    # the first step re-draws the same seed (no-op)
                    init = (score, draw_bag(it0 - it0 % bag_freq))
                    (final_score, _), recs = jax.lax.scan(
                        body, init, its)
                    return final_score, recs
                return jax.lax.scan(body, score, its)

            self._fused[length] = obs.track_jit(
                "fused_train", jax.jit(run, static_argnames=("grad_fn",)),
                static_info=(f"len={length}",))
        return self._fused[length]

    # ------------------------------------------------------------------
    def profile_phases(self, grad, hess, reps: int = 20) -> dict:
        """Honest per-phase attribution for one wave (bench --profile).

        The production grower runs the whole tree inside one
        ``lax.while_loop`` — individual phases are invisible from the
        host.  This method times separately-jitted programs equivalent
        to the wave's phases on the real binned matrices and a
        representative leaf state (rows spread over W leaves, all
        pending), syncing after each, and returns {phase: ms}.
        """
        import time as _time

        w, n = self.wave_width, self.n_pad
        rng = np.random.default_rng(0)
        leaf_id = jnp.asarray(
            rng.integers(0, w, n).astype(np.int32))
        pending = jnp.arange(w, dtype=jnp.int32)
        grad = jnp.pad(grad, (0, n - self.num_data))
        hess = jnp.pad(hess, (0, n - self.num_data))

        k = self.hist_cols

        @jax.jit
        def p_hist(binned, leaf, g, h, pend):
            one = jnp.ones((n,), jnp.bfloat16)
            ghi = g.astype(jnp.bfloat16)
            hhi = h.astype(jnp.bfloat16)
            if k in (5, 6):
                glo = (g - ghi.astype(jnp.float32)).astype(jnp.bfloat16)
                hlo = (h - hhi.astype(jnp.float32)).astype(jnp.bfloat16)
                cols = [ghi, glo, hhi, hlo]
            else:
                cols = [ghi, hhi]
            if k in (4, 6):
                stripe = (jnp.arange(n) < (n // 2)).astype(jnp.bfloat16)
                cols += [stripe, 1.0 - stripe]
            else:
                cols += [one]
            ghk = jnp.stack(cols, 1)
            return self._wave_hist(binned, leaf, ghk, pend)

        @jax.jit
        def p_find(hists, feature_mask):
            find_one = functools.partial(find_best_split_impl,
                                         meta=self.meta, hp=self.hyper,
                                         has_cat=False)
            cons = jnp.asarray([-jnp.inf, jnp.inf], jnp.float32)
            totals = hists[:, :self.nb, :].sum(1)
            packed, _ = jax.vmap(
                lambda hh, t: find_one(hh, t, cons, feature_mask))(hists,
                                                                   totals)
            return packed

        @jax.jit
        def p_apply(binned_t, leaf, grp, thr, rdel):
            cols = jnp.take(binned_t, grp, axis=0).astype(jnp.int32)
            mask = (leaf[None, :] == jnp.arange(w)[:, None]) \
                & (cols > thr[:, None])
            return leaf + jnp.sum(mask * rdel[:, None], axis=0,
                                  dtype=jnp.int32)

        @jax.jit
        def p_score(score, leaf, vals):
            L = self.num_leaves
            oh = jax.nn.one_hot(leaf, L, dtype=jnp.bfloat16)
            vhi = vals.astype(jnp.bfloat16)
            vlo = (vals - vhi.astype(jnp.float32)).astype(jnp.bfloat16)
            upd = jnp.einsum("nl,lk->nk", oh, jnp.stack([vhi, vlo], 1),
                             preferred_element_type=jnp.float32)
            return score + upd[:, 0] + upd[:, 1]

        mask = jnp.ones((len(np.asarray(self.p_group)),), bool)
        grp = jnp.asarray(rng.integers(0, self.num_groups, w, np.int32))
        thr = jnp.asarray(rng.integers(0, self.nb, w, np.int32))
        rdel = jnp.asarray(rng.integers(1, w + 1, w, np.int32))
        vals = jnp.asarray(rng.standard_normal(self.num_leaves)
                           .astype(np.float32))
        score = jnp.zeros((n,), jnp.float32)

        # dispatch-latency floor: an empty jitted program measured the
        # same way; subtracted from every phase so tunnel round-trip
        # latency doesn't masquerade as device time
        @jax.jit
        def p_null(x):
            return x + 1.0

        out = {}
        cases = {
            "null_dispatch": lambda: p_null(score[:8]),
            "wave_hist": lambda: p_hist(self.binned, leaf_id, grad, hess,
                                        pending),
            "find_best": None,   # filled after hist exists
            "split_apply": lambda: p_apply(self.binned_t, leaf_id, grp,
                                           thr, rdel),
            "score_update": lambda: p_score(score, leaf_id, vals),
        }
        hists = jax.block_until_ready(cases["wave_hist"]())
        cases["find_best"] = lambda: p_find(hists, mask)
        for name, fn in cases.items():
            jax.block_until_ready(fn())          # compile + warm
            t0 = _time.perf_counter()
            for _ in range(reps):
                r = fn()
            jax.block_until_ready(r)
            out[name] = round((_time.perf_counter() - t0) / reps * 1e3, 2)
        floor = out.pop("null_dispatch")
        out = {k: round(max(v - floor, 0.0), 2) for k, v in out.items()}
        out["dispatch_floor"] = floor
        for name, ms in out.items():
            obs.set_gauge(f"profile.{name}_ms", ms)
        return out


def device_growth_eligible(config, dataset, objective, num_model) -> bool:
    """Whether the dense device grower covers this training configuration.
    Anything it can't do falls back to the host-driven learner.
    Multiclass runs one grow dispatch per class; bagging/GOSS route a
    0/1 row mask into the wave histogram's count column."""
    if dataset.num_groups == 0 or dataset.num_features == 0:
        return False
    if np.asarray(dataset.monotone_constraints).any():
        return False
    if objective is None or objective.is_renew_tree_output:
        return False
    if getattr(config, "forcedsplits_filename", ""):
        return False
    # single f32 count columns are exact below COUNT_SPLIT_ROWS (2^24);
    # the striped two-column layout extends that to twice the threshold
    if dataset.num_data >= 2 * COUNT_SPLIT_ROWS:
        return False
    return True
