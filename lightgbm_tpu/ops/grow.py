"""Fully on-device wave-synchronized leaf-wise tree growth.

Why this exists: the host-driven learner (``tree/learner.py``) needs one
host<->device round trip per split.  On real TPU hardware behind a network
tunnel that round trip measures ~120 ms and async dispatch ~1 ms, so a
255-leaf tree costs ~30 s in latency alone — three orders of magnitude over
the compute.  Measurement also shows every irregular memory op on TPU
(gather ~10-50 ns/elem, scatter/sort ~30 ns/elem) runs far below HBM
bandwidth, which rules out the reference's index-permutation design
(``DataPartition``, ``dense_bin.hpp:106-175``) entirely: maintaining sorted
leaf windows costs more than the histograms they would save.

The TPU-native formulation is **dense**:

* a per-row ``leaf_id`` vector replaces the row permutation; a split
  updates it with one elementwise pass over a contiguous feature column
  (the ``(G, N)`` transposed copy of the binned matrix);
* histograms for a whole *wave* of fresh leaves are built in ONE pass over
  all rows: per feature-group, ``one_hot(bins) . (leaf_mask x [g,h,1])`` —
  the leaf-mask columns widen the matmul's N dimension to fill the MXU's
  128-lane tiles (a single leaf's 3 stat columns would waste 97% of them);
* the gradient operand is split hi/lo into two bfloat16 columns whose
  float32-accumulated sum reconstructs float32-accurate histograms at
  bfloat16 matmul speed (counts are exact: 0/1 products, f32 accumulation);
  with ``grad_quant_bits=8`` the g/h columns are instead stochastically
  rounded to int8 against a per-tree global scale and the contraction runs
  on the MXU's native int8->int32 path — below ``INT32_SCAN_ROWS`` the
  histograms then stay INTEGER end-to-end through the find-best prefix
  sums and the per-leaf hist/total state (dequantized only at gain/leaf-
  value math; counts, default-bin reconstruction and the parent-minus-
  sibling subtraction are exact), larger datasets dequantize once in f32
  before the scan, and leaf values are REFIT from the full-precision
  gradients after growth either way;
* growth is best-first like the reference (``serial_tree_learner.cpp:
  157-221``) but *wave-synchronized*: each wave evaluates the newest leaves
  (smaller sibling by direct histogram, larger by parent subtraction,
  ``serial_tree_learner.cpp:508-513``) and then applies up to ``wave_width``
  best-gain splits.  With an unlimited wave budget this is exactly
  leaf-wise order except near the num_leaves budget boundary, where the
  reference might prefer a just-created child over an older leaf; waves
  only batch *independent* splits, never reorder by gain.
* the whole tree grows inside one ``lax.while_loop`` — a boosting
  iteration is ONE device dispatch with nothing fetched; split records are
  copied to host asynchronously and replayed into ``Tree`` objects lazily.
* staged wave widths come from ``ops/stage_plan.py``: the byte-stable
  doubling default, or a profile-guided plan derived from per-stage
  timings (``wave_plan=profiled`` / ``DeviceGrower.profile_stage_plan``).

The jitted programs live on a :class:`GrowerPrograms` object that holds
NO device data — the binned matrices, feature metadata and traced
hyper-parameters are all arguments, so programs are shared process-wide
through a cache keyed on (shape signature, config hash, plan digest).
In the retrain-every-window pattern a warm second window therefore
performs ZERO new traces (obs counters ``grow.cache_hits``/``misses``).

Supports: numerical features, missing-value routing (None/Zero/NaN),
categorical optimal splits (the winning category set travels as an
8-word bin bitset), feature_fraction masks, bagging/GOSS via a 0/1
row-mask column, multiclass (one dispatch per class),
L1/L2/max_delta_step, DART/RF (driven from boosting/).  Still host-only:
monotone constraints, forced splits, renew-tree-output objectives.
"""

from __future__ import annotations

import functools
import hashlib
import threading
from collections import OrderedDict
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from . import stage_plan as stage_plan_mod
from .histogram import (QUANT_MAX, bucket_size, quant_scales, quantize_gh,
                        stochastic_round_with)
from .shard import (ShardSpec, local_valid_rows, shard_map_compat,
                    slice_global_draw)
from .split import (F_DEFAULT_LEFT, F_FEATURE, F_GAIN, F_IS_CAT, F_LEFT_C,
                    F_LEFT_G, F_LEFT_H, F_LEFT_OUT, F_RIGHT_C, F_RIGHT_G,
                    F_RIGHT_H, F_RIGHT_OUT, F_THRESHOLD, FeatureMeta,
                    NEG_INF, SplitHyper, find_best_split_impl,
                    find_best_split_quant, find_best_split_stack)

# rows per histogram chunk: large chunks amortize MXU ramp-up; the
# per-chunk one-hot (CH, G, NB) bf16 stays fusable into the dot operand
import os as _os
_CHUNK = int(_os.environ.get("LGBM_TPU_CHUNK", 32768))

# record field layout (host replay reads these)
REC_I_FIELDS = 5    # leaf, right, feature, threshold, default_left
REC_F_FIELDS = 9    # gain, lg, lh, lc, rg, rh, rc, left_out, right_out
# rec_f column indices of the two leaf outputs (quant refit writes them)
REC_F_LEFT_OUT = 7
REC_F_RIGHT_OUT = 8

# above this many rows a single f32 count cell can exceed 2^24 and lose
# integer exactness; the wave matmul then carries TWO striped count
# columns (each stripe < 2^24 rows, summed after accumulation — final
# count error <= 1 ulp instead of unbounded drift).  Module-level so
# tests can force the striped path on small data.  The int8 quantized
# path stripes its g/h columns at the same threshold: 127 * 2^24 stays
# below the int32 accumulator limit per stripe.
COUNT_SPLIT_ROWS = 1 << 24

# int32 find-best scan eligibility (grad_quant_bits=8): every histogram
# cell / prefix sum / subtraction intermediate is bounded by
# |sum q| <= 127 * rows (|q| <= QUANT_MAX = 127 per row), so int32 is
# EXACT up to floor((2^31 - 1) / 127) = 16,909,320 rows.  Above it the
# quantized path dequantizes to f32 before the scan as in PR 4 (striped
# stripe SUMS would wrap; see ROUND8_NOTES.md for the full analysis).
# Module-level so tests can force the f32 fallback on small data.
INT32_SCAN_ROWS = ((1 << 31) - 1) // 127


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


class FTables(NamedTuple):
    """Per-feature group/slot tables as traced device arrays (arguments,
    not closure constants: baking them into the program would both bloat
    the compile request and key the program cache on bin boundary
    content instead of shape).  Only the fields ``FeatureMeta`` does NOT
    already carry — num_bin/default_bin/missing are read from ``meta``
    so there is one source of truth per array."""
    group: jnp.ndarray         # (F,) int32
    offset: jnp.ndarray        # (F,) int32
    width: jnp.ndarray         # (F,) int32  num_bin - (default_bin == 0)

    @classmethod
    def from_dataset(cls, dataset) -> "FTables":
        i32 = lambda a: jnp.asarray(np.asarray(a, np.int32))
        nbins = np.asarray(dataset.f_num_bin, np.int64)
        dbins = np.asarray(dataset.f_default_bin, np.int64)
        return cls(i32(dataset.f_group), i32(dataset.f_offset),
                   i32(nbins - (dbins == 0)))


def feature_fraction_mask(seed: int, tree_idx, nf: int, k: int):
    """(nf,) bool mask selecting ``k`` features without replacement:
    ``fold_in(PRNGKey(seed), tree_idx)`` then the k smallest of nf
    uniforms.  Shared by the per-iteration device path and the fused
    scan (``tree_idx`` may be traced) so both draw bit-identical masks
    for the same global tree index — the property the fused-parity
    tests pin."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), tree_idx)
    u = jax.random.uniform(key, (nf,))
    thr = jnp.sort(u)[k - 1]
    return u <= thr


def _combine_hist_cols(h, k: int):
    """Collapse the K accumulated stat columns (last axis) to
    [g, h, cnt].  K=3: passthrough.  K=4: striped counts summed.
    K=5: hi/lo g,h.  K=6: pairwise sums (hi/lo g,h + striped counts).
    dtype-generic: the bf16 path passes f32 accumulators, the int32
    quantized scan passes int32 (its K is 3 or 6; stripe sums stay
    exact below INT32_SCAN_ROWS).  The quantized f32 FALLBACK combines
    its own stripes in ``_wave_hist`` instead (f32 casts before the
    sum — past the bound an int32 stripe SUM can wrap)."""
    import jax.numpy as _jnp
    if k == 5:
        return _jnp.stack([h[..., 0] + h[..., 1], h[..., 2] + h[..., 3],
                           h[..., 4]], axis=-1)
    if k == 4:
        # each stripe accumulated < 2^24 rows exactly; the sum is exact
        # to <= 1 ulp at up to 2 * COUNT_SPLIT_ROWS rows
        return _jnp.stack([h[..., 0], h[..., 1], h[..., 2] + h[..., 3]],
                          axis=-1)
    if k == 6:
        return _jnp.stack([h[..., 0] + h[..., 1], h[..., 2] + h[..., 3],
                           h[..., 4] + h[..., 5]], axis=-1)
    return h


def _hi_lo_cols(grad, hess, one):
    """[g_hi, g_lo, h_hi, h_lo] bf16 stat columns masked by ``one``: each
    lo column carries the bf16 rounding residual, so an f32-accumulated
    contraction of the pair reconstructs the f32-exact sum.  Shared by
    the gpu_use_dp histogram path and the quantized-path leaf refit."""
    ghi = grad.astype(jnp.bfloat16)
    hhi = hess.astype(jnp.bfloat16)
    glo = (grad - ghi.astype(jnp.float32)).astype(jnp.bfloat16)
    hlo = (hess - hhi.astype(jnp.float32)).astype(jnp.bfloat16)
    return [ghi * one, glo * one, hhi * one, hlo * one]


def _hist_layout(num_data: int, config):
    """(quant_bits, striped, hist_cols) for this row count + config."""
    dp = bool(getattr(config, "gpu_use_dp", False))
    quant_bits = int(getattr(config, "grad_quant_bits", 0) or 0)
    striped = int(num_data) >= COUNT_SPLIT_ROWS
    if quant_bits:
        # striped mode stripes g/h too: 127 * 2^24 per stripe stays
        # inside the int32 accumulator
        hist_cols = 6 if striped else 3
    elif dp:
        # 6 = hi/lo g,h + striped counts: dp must not reintroduce
        # the single-column count overflow it exists to avoid
        hist_cols = 6 if striped else 5
    else:
        hist_cols = 4 if striped else 3
    return quant_bits, striped, hist_cols


def _wave_width(num_leaves: int, hist_cols: int) -> int:
    scale = 3.0 / hist_cols
    wmax = max(int(128 * scale), 4)
    return min(wmax, max(int(num_leaves) - 1, 1))


def default_stage_plan(num_data: int, config) -> list:
    """The legacy doubling plan :func:`get_grower_programs` resolves
    when no explicit plan is given — the single resolution point, so
    the digest in the program-cache key always matches the plan the
    cached programs were traced with (and a profiled plan that equals
    the default hits the same cache entry, not a re-trace)."""
    _, _, hist_cols = _hist_layout(num_data, config)
    num_leaves = int(config.num_leaves)
    return stage_plan_mod.legacy_stage_plan(
        num_leaves, _wave_width(num_leaves, hist_cols), hist_cols)


class GrowerPrograms:
    """The jitted growth programs plus every static fact their traces
    depend on.  Holds NO device data: the binned matrices, feature
    metadata (:class:`~.split.FeatureMeta`), traced hyper-parameters and
    partition tables (:class:`FTables`) are call arguments, so one
    instance serves every :class:`DeviceGrower` whose shape/config
    signature matches (see :func:`get_grower_programs`)."""

    def __init__(self, *, num_data: int, num_groups: int, nb: int,
                 num_features: int, has_cat: bool, config,
                 plan: list, plan_source: str = "default",
                 fusion: Optional[str] = None,
                 shard: Optional[ShardSpec] = None, mesh=None):
        self.config = config.clone()
        config = self.config
        # sharded layout (ops/shard.py): ``num_data`` is then the
        # PER-SHARD padded row count, ``shard`` carries the global facts
        # (real rows, canonical draw shapes) and ``mesh`` the topology.
        # mesh is metadata, not device data — programs stay data-free.
        self.shard = shard
        self.mesh = mesh
        self.num_data = int(num_data)
        self.num_groups = int(num_groups)
        self.nb = int(nb)
        self.num_features = int(num_features)
        self.has_cat = bool(has_cat)
        self.num_leaves = int(config.num_leaves)
        self.num_slots = self.num_groups * self.nb
        self.n_pad = _ceil_to(max(self.num_data, _CHUNK), _CHUNK)

        # stat columns per leaf in the wave matmul.  Default 3 — bf16
        # g/h + exact count: per-term bf16 rounding (rel ~2^-8) is
        # uncorrelated across a bin's rows, so bin sums stay accurate to
        # ~1e-5 relative (measured; cf. the reference GPU learner's f32
        # histograms, docs/GPU-Performance.rst:128-161).  gpu_use_dp
        # restores the hi/lo split (g,h each as two bf16 columns whose
        # f32-accumulated sum reconstructs f32-exact values).
        # grad_quant_bits=8 replaces the bf16 columns with int8
        # stochastic-rounded g/h so the contraction runs int8->int32.
        self.quant_bits, self.striped, self.hist_cols = _hist_layout(
            self.num_data, config)
        # int32 end-to-end: below INT32_SCAN_ROWS the quantized
        # histograms stay integer through the find-best prefix sums
        # (split.find_best_split_quant) and the per-leaf hist/total
        # state, dequantizing only at gain/leaf-value math; counts and
        # the parent-minus-sibling subtraction become exact.  The bound
        # is on n_pad: the stage-profiling probes weight every padded
        # row, and pad rows are zero-masked in production anyway.
        # Sharded, the bound applies to the GLOBAL padded row space —
        # the psum accumulates |sum q| <= 127 * total rows across the
        # whole mesh into the same int32 cells.
        int_rows = self.n_pad if shard is None \
            else shard.n_shards * self.n_pad
        self.int_scan = bool(self.quant_bits) \
            and int_rows <= INT32_SCAN_ROWS
        # Wave cost measured on the chip (scripts/ubench_hist.py,
        # 10.5M rows): ~15.9 ms fixed (the one-hot operand generation
        # over all N, width-independent) + ~0.203 ms per stat column —
        # LINEAR in columns, not column-tile-quantized, and 72% of MXU
        # peak at 2 tiles (hist3_w84: 67.1 ms, 141.7 TF).  Since a wave
        # can split at most the current frontier, the cheapest plan
        # width-matches each stage to the frontier (doubling) and ends
        # with one very wide multi-tile wave for the tail.  gpu_use_dp
        # (k=5) scales each width down by 3/k to hold the column budget.
        self.wave_width = _wave_width(self.num_leaves, self.hist_cols)
        # plan is required and resolved by get_grower_programs (its
        # digest is part of the program-cache key — resolving it here
        # too could silently diverge from the keyed digest)
        self.stage_plan = [(int(w), None if c is None else int(c))
                           for w, c in plan]
        self.plan_source = plan_source
        # hist_kernel: "auto"/"einsum" use the XLA einsum formulation —
        # the best measured for bf16 (both Pallas kernels lost to it,
        # see ops/hist_pallas.py); "pallas" opts into the VMEM kernel
        # on hardware, "interpret" runs it in interpreter mode (CPU
        # tests).  Both the bf16 and the int8 quantized stat columns
        # route through the same gate; the kernel accumulates
        # int8->int32 on the MXU for grad_quant_bits=8 and is
        # byte-identical to the int8 einsum (integer accumulation).
        mode = str(getattr(config, "hist_kernel", "auto")
                   or "auto").lower()
        self.pallas_interpret = mode == "interpret"
        self.use_pallas = mode in ("pallas", "interpret")
        # routing attribution for BENCH digests: which kernel serves
        # the full-width stage (narrow stages always stay on the
        # einsum; multi-tile waves fall back to it too)
        from .hist_pallas import fits_single_tile
        kern = "pallas" if (self.use_pallas
                            and fits_single_tile(self.wave_width,
                                                 self.hist_cols)) \
            else "einsum"
        self.hist_kernel_tag = \
            f"{kern}_{'int8' if self.quant_bits else 'bf16'}"
        # find-best placement inside the wave: "fused" keeps the gain
        # scan in the SAME traced region as the histogram contraction —
        # the fresh product and the parent-minus-sibling residual are
        # scanned in place and no concatenated (2W, S, 3) tensor
        # round-trips through HBM between them — while "two_pass" keeps
        # the legacy concat layout.  The caller (get_grower_programs)
        # resolves auto against a wave_plan=profiled verdict persisted
        # for this signature; a direct construction without one adopts
        # the default resolution here so the trace never depends on an
        # unset attribute.
        self.find_fusion = fusion if fusion in ("fused", "two_pass") \
            else resolve_find_fusion(config)
        self.fused_find = self.find_fusion == "fused"
        # recompile tracking: these TrackedJit wrappers are shared by
        # every grower that adopts this programs object, so in the
        # retrain-every-window pattern a warm window re-dispatches into
        # already-compiled programs and obs records ZERO new compiles.
        # Sharded, the same _grow_impl runs per shard under shard_map
        # (jit outside, shard_map inside) with the psum/pmax hooks
        # active — one jitted program family either way.
        if shard is None:
            self._grow = obs.track_jit(
                "grow", jax.jit(functools.partial(self._grow_impl,
                                                  with_mask=False)))
            self._grow_masked = obs.track_jit(
                "grow_masked",
                jax.jit(functools.partial(self._grow_impl,
                                          with_mask=True)))
        else:
            self._grow = obs.track_jit(
                "grow_sharded",
                jax.jit(self._shard_wrap(with_mask=False)))
            self._grow_masked = obs.track_jit(
                "grow_sharded_masked",
                jax.jit(self._shard_wrap(with_mask=True)))
        self._fused = {}   # scan length -> jitted multi-iteration program
        # one programs object is served process-wide from _PROGRAM_CACHE,
        # so lazy per-length entries need their own lock
        self._fused_lock = threading.Lock()
        # sampling state for device-side draws (feature_fraction masks,
        # fused bagging, quantization rounding): seeds mirror the host
        # learner's derivation (learner.py _rng / GBDT.bagging) so fused
        # and per-iteration paths stay bit-identical
        self._ff_frac = float(config.feature_fraction)
        nf = self.num_features
        self._ff_nf = nf
        self._ff_k = max(1, int(np.ceil(nf * self._ff_frac)))
        self._ff_seed = int(config.feature_fraction_seed
                            if config.feature_fraction_seed
                            else config.seed + 2) & 0x7FFFFFFF
        self._bag_fraction = float(config.bagging_fraction)
        self._bag_freq = int(config.bagging_freq)
        self._bag_seed = int(config.bagging_seed) & 0x7FFFFFFF
        # sharded: the bagging uniform draw keeps the CANONICAL GLOBAL
        # shape (the draw shape is part of the stream), each shard
        # slices its block — bags are shard-invariant bit-for-bit
        self._bag_npad = shard.bag_npad if shard is not None \
            else bucket_size(max(self.num_data, 1))
        self._quant_seed = (int(config.seed) + 5) & 0x7FFFFFFF

    # ------------------------------------------------------------------
    def feature_mask_for(self, tree_idx):
        """Deterministic per-tree feature_fraction mask (device array).
        ``tree_idx`` is the global tree index (iter * num_model + k);
        accepts traced values inside the fused scan."""
        if self._ff_frac >= 1.0 or self._ff_nf <= 1:
            return jnp.ones(self._ff_nf, dtype=bool)
        return feature_fraction_mask(self._ff_seed, tree_idx,
                                     self._ff_nf, self._ff_k)

    # ------------------------------------------------------------------
    # single-controller sharding hooks (ops/shard.py).  All no-ops when
    # self.shard is None, so the unsharded programs trace identically
    # to the pre-sharding code.
    # ------------------------------------------------------------------
    def _shard_wrap(self, *, with_mask: bool):
        """shard_map-wrapped per-iteration program: row buffers split
        over the mesh axis, scalars/metadata replicated, the traced
        GLOBAL ``num_valid`` converted to the shard-local cutoff.  The
        tree outputs are replicated by construction (they derive from
        the psum-reduced histograms), so out_specs take each shard's
        identical copy."""
        from jax.sharding import PartitionSpec as P
        sp = self.shard
        row = P(sp.axis)
        rep = P()
        in_specs = (P(sp.axis, None), P(None, sp.axis), row, row, row,
                    rep, rep, row, rep, rep, rep, rep, rep)
        out_specs = (row,) + (rep,) * 7

        def body(binned, binned_t, score, grad, hess, feature_mask, lr,
                 row_mask, tree_idx, num_valid, meta, hyper, tables):
            nv_loc = local_valid_rows(sp, self.n_pad, num_valid)
            return self._grow_impl(binned, binned_t, score, grad, hess,
                                   feature_mask, lr, row_mask, tree_idx,
                                   nv_loc, meta, hyper, tables,
                                   with_mask=with_mask)

        return shard_map_compat(body, self.mesh, in_specs, out_specs)

    def _psum_hist(self, hist):
        """The growth loop's ONE cross-device sync point: sum the wave
        histograms over the mesh axis.  int32 histograms (the quantized
        int-scan regime) psum exactly; f32 regimes psum g/h in f32 (the
        reduction order is the compiled program's — deterministic
        run-to-run) and counts as int32, keeping row counts exact past
        2^24 global rows (per-shard counts are integer-exact by the
        striping layout, so the cast is exact)."""
        sp = self.shard
        if sp is None:
            return hist
        if hist.dtype == jnp.int32:
            return jax.lax.psum(hist, sp.axis)
        gh = jax.lax.psum(hist[..., :2], sp.axis)
        cnt = jax.lax.psum(jnp.round(hist[..., 2]).astype(jnp.int32),
                           sp.axis).astype(jnp.float32)
        return jnp.concatenate([gh, cnt[..., None]], axis=-1)

    def _quantize_sharded(self, grad, hess, qkey):
        """Sharded :func:`~.histogram.quantize_gh`: the per-tree global
        scale is the pmax of shard-local maxes (max is associative-exact,
        so it equals the single-device scale bitwise), and the rounding
        noise is drawn at the canonical global shape ``draw_npad`` —
        the single-device grower's chunk pad — then sliced to this
        shard's rows, so every real row sees the exact noise value the
        unsharded path would give it."""
        sp = self.shard
        sg, sh = quant_scales(grad, hess)
        sg = jax.lax.pmax(sg, sp.axis)
        sh = jax.lax.pmax(sh, sp.axis)
        kg, kh = jax.random.split(qkey)

        def noise(k):
            return slice_global_draw(
                sp, jax.random.uniform(k, (sp.draw_npad,)), self.n_pad)

        return (sg, sh, stochastic_round_with(grad, sg, noise(kg)),
                stochastic_round_with(hess, sh, noise(kh)))

    # ------------------------------------------------------------------
    # wave histogram: one dense pass for up to W pending leaves
    # ------------------------------------------------------------------
    def _wave_hist(self, binned, leaf_id, ghk, pending, scales=None):
        """(n_pad,) leaf ids, (n_pad, K) stat columns (bf16 — K=3:
        [g,h,1]; K=5: [g_hi,g_lo,h_hi,h_lo,1] — or int8 under
        grad_quant_bits), (W,) pending leaf ids (-1 = empty slot)
        -> (W, S, 3) f32, or int32 in quantized units when
        ``self.int_scan`` (the find-best scan then stays integer).
        ``scales`` is the (2,) [scale_g, scale_h] dequantization vector
        (quantized f32-fallback mode only).

        The one-hot must stay a bare iota-compare so XLA fuses its
        generation into the dot operand (a multi-hot built as
        ``one_hot(..).sum()`` materializes in HBM measured 3.5x slower;
        fusing the leaf-id split application into this scan also measured
        2x slower - the extra data dependency breaks matmul pipelining)."""
        g, nb = self.num_groups, self.nb
        w = pending.shape[0]
        k = self.hist_cols
        quant = bool(self.quant_bits)
        from .hist_pallas import fits_single_tile
        if self.use_pallas and w == self.wave_width \
                and fits_single_tile(w, k):
            # the VMEM kernel packs all stat columns into one 128-lane
            # tile; wider (multi-tile) waves stay on the einsum
            # full-width stage: MXU cost is tile-bound regardless of W,
            # so the VMEM-resident kernel wins; narrow early stages stay
            # on the einsum (XLA lowers small-N contractions cheaper).
            # int8 stat columns take the kernel's int8->int32 variant —
            # integer accumulation, so byte-identical to the einsum.
            from .hist_pallas import wave_hist_pallas
            out = wave_hist_pallas(binned, leaf_id, ghk, pending,
                                   g=g, nb=nb, k=k, w=w,
                                   interpret=self.pallas_interpret)
            acc = out.reshape(g, nb, k, w).transpose(0, 1, 3, 2)
        else:
            ch = _CHUNK
            n_chunks = self.n_pad // ch
            binned_c = binned.reshape(n_chunks, ch, g)
            leaf_c = leaf_id.reshape(n_chunks, ch)
            ghk_c = ghk.reshape(n_chunks, ch, k)
            mdtype = jnp.int8 if quant else jnp.bfloat16
            adtype = jnp.int32 if quant else jnp.float32

            def body(acc, xs):
                b, l, gk = xs
                lm = (l[:, None] == pending[None, :]).astype(mdtype)
                bmat = (lm[:, :, None] * gk[:, None, :]).reshape(ch,
                                                                 w * k)
                # bin tiling: a one-hot wider than 64 breaks XLA's
                # operand fusion (max_bin=255 measured 10x the
                # max_bin=63 wave, not the expected 4x) — strips of 64
                # keep each einsum in the known-fused regime; out-of-
                # strip bins make all-zero one-hot rows, so the concat
                # reassembles exactly
                bi = b.astype(jnp.int32)
                outs = []
                for off in range(0, nb, 64):
                    oh = jax.nn.one_hot(bi - off, min(nb, 64),
                                        dtype=mdtype)           # (CH,G,64)
                    outs.append(jnp.einsum("cgn,cb->gnb", oh, bmat,
                                           preferred_element_type=adtype))
                out = outs[0] if len(outs) == 1 \
                    else jnp.concatenate(outs, axis=1)
                return acc + out, None

            acc0 = jnp.zeros((g, nb, w * k), adtype)
            acc, _ = jax.lax.scan(body, acc0, (binned_c, leaf_c, ghk_c))
            acc = acc.reshape(g, nb, w, k)
        if quant and self.int_scan:
            # int32 end-to-end: the histogram stays in quantized units
            # for the find-best scan (split.find_best_split_quant
            # dequantizes at gain math).  _combine_hist_cols is dtype-
            # generic — striped stripes (k=6) sum in int32, exact below
            # INT32_SCAN_ROWS, which gates int_scan; k=3 passes through.
            hist = _combine_hist_cols(acc, k)
        elif quant:
            # f32 fallback past INT32_SCAN_ROWS: dequantize ONCE per
            # histogram before any gain math.  Striped g/h stripes are
            # cast to f32 BEFORE summing — each stripe is int32-exact
            # (< 127 * 2^24), but their int32 SUM can wrap for a bin
            # holding > 2^31/127 rows (hess == 1.0 quantizes to 127
            # everywhere); the f32 cast costs <= 2^-24 relative, far
            # below the rounding noise.  Count stripes sum in int32
            # (2 * 2^24 * 1 cannot overflow), so counts stay exact up to
            # f32's integer range like the bf16 striped layout.
            f32 = lambda a: a.astype(jnp.float32)
            if k == 6:
                gsum = f32(acc[..., 0]) + f32(acc[..., 1])
                hsum = f32(acc[..., 2]) + f32(acc[..., 3])
                cnt = f32(acc[..., 4] + acc[..., 5])
            else:
                gsum, hsum, cnt = (f32(acc[..., 0]), f32(acc[..., 1]),
                                   f32(acc[..., 2]))
            hist = jnp.stack([gsum * scales[0], hsum * scales[1], cnt],
                             axis=-1)
        else:
            hist = _combine_hist_cols(acc, k)                    # (G,NB,W,3)
        # sharded: psum the combined per-shard histograms — the growth
        # loop's sole cross-device sync (docs/Sharding.md); everything
        # downstream (find-best, totals, root stats) then runs on
        # replicated global values
        return self._psum_hist(
            hist.transpose(2, 0, 1, 3).reshape(w, self.num_slots, 3))

    # ------------------------------------------------------------------
    def _stat_columns(self, grad, hess, one_f, tree_idx):
        """(n_pad, K) wave stat columns + (2,) dequantization scales
        (zeros when quantization is off).  ``one_f`` is the f32 0/1 row
        indicator (valid-row mask x bagging mask).  The ONE assembly
        shared by the production grow program and the profiling probes,
        so probes time exactly the operand pipeline training runs."""
        n = one_f.shape[0]
        k = self.hist_cols
        if self.quant_bits:
            qkey = jax.random.fold_in(
                jax.random.PRNGKey(self._quant_seed), tree_idx)
            if self.shard is not None:
                sg, sh, gq, hq = self._quantize_sharded(grad, hess, qkey)
            else:
                sg, sh, gq, hq = quantize_gh(grad, hess, qkey)
            m8 = one_f.astype(jnp.int8)
            if k == 6:
                # striped g/h/count columns: each stripe's int32
                # accumulation stays exact below 127 * 2^24
                s8 = (jnp.arange(n) < (n // 2)).astype(jnp.int8)
                t8 = (1 - s8).astype(jnp.int8)
                gcols = [gq * m8 * s8, gq * m8 * t8, hq * m8 * s8,
                         hq * m8 * t8, m8 * s8, m8 * t8]
            else:
                gcols = [gq * m8, hq * m8, m8]
            return jnp.stack(gcols, 1), jnp.stack([sg, sh])
        one = one_f.astype(jnp.bfloat16)
        if k in (5, 6):
            gcols = _hi_lo_cols(grad, hess, one)
        else:
            gcols = [grad.astype(jnp.bfloat16) * one,
                     hess.astype(jnp.bfloat16) * one]
        if k in (4, 6):
            # two striped count columns (< 2^24 rows each) keep counts
            # integer-exact beyond the single-column f32 limit
            stripe = (jnp.arange(n) < (n // 2)).astype(jnp.bfloat16)
            gcols += [one * stripe, one * (1.0 - stripe)]
        else:
            gcols += [one]
        return jnp.stack(gcols, 1), jnp.zeros((2,), jnp.float32)

    # ------------------------------------------------------------------
    def _leaf_output(self, g, h, hp):
        s = jnp.sign(g) * jnp.maximum(jnp.abs(g) - hp.lambda_l1, 0.0)
        out = -s / (h + hp.lambda_l2 + 1e-35)
        clipped = jnp.clip(out, -hp.max_delta_step, hp.max_delta_step)
        return jnp.where(hp.max_delta_step <= 0.0, out, clipped)

    def _splittable(self, total, depth, hess_scale=None):
        """``hess_scale`` dequantizes the hessian column when ``total``
        carries int32 quantized units (the int32 scan); counts compare
        directly in either representation."""
        cfg = self.config
        hess = total[..., 1]
        if hess_scale is not None:
            hess = hess.astype(jnp.float32) * hess_scale
        ok = (total[..., 2] > 2 * cfg.min_data_in_leaf) \
            & (hess > 2 * cfg.min_sum_hessian_in_leaf)
        if cfg.max_depth > 0:
            ok = ok & (depth < cfg.max_depth)
        return ok

    # ------------------------------------------------------------------
    def _grow_impl(self, binned, binned_t, score, grad, hess, feature_mask,
                   lr, row_mask, tree_idx, num_valid, meta, hyper, tables,
                   *, with_mask):
        """One boosting iteration on device.  Returns (new_score, rec_i
        (L-1,5) i32, rec_f (L-1,9) f32, rec_c (L-1,8) i32, num_leaves
        i32, root_value f32, num_waves i32, quant_scales (2,) f32).
        ``lr`` is traced so callbacks may reset the learning rate without
        recompiling; ``tree_idx`` is the global tree index keying the
        quantization rounding noise (unused when grad_quant_bits=0).
        ``num_valid`` is the REAL row count as a traced i32 scalar:
        under train_row_bucketing ``self.num_data`` is the pow2 row
        bucket, and the rows in [num_valid, num_data) are bucket padding
        that must carry zero gradient/hessian/count — keeping the cutoff
        traced is what lets ONE compiled program serve every window size
        in the bucket.  The binned matrices — like ``meta``/``hyper``/
        ``tables`` — are arguments, not closures: a closed-over array
        becomes an XLA constant and ships inside the compile request
        (fatal at 10M-row scale on a remote-compile backend), and
        argument-passing is what lets the program cache serve every
        same-shaped dataset."""
        L, W, S = self.num_leaves, self.wave_width, self.num_slots
        n = self.n_pad
        npad_rows = n - self.num_data

        grad = jnp.pad(grad, (0, npad_rows))
        hess = jnp.pad(hess, (0, npad_rows))
        valid_f = jnp.where(jnp.arange(n) < num_valid, 1.0, 0.0)
        # bucket-pad rows may carry garbage gradients (the fused path's
        # grad_fn computes them from padded scores/labels): zero them
        # BEFORE quantization scales / stat columns see them.  For real
        # rows this is an exact f32 no-op (x * 1.0 == x bitwise), which
        # keeps the bucketed and unbucketed paths byte-identical.
        grad = grad * valid_f
        hess = hess * valid_f
        one_f = valid_f
        if with_mask:
            # bagging/GOSS: 0/1 in-bag indicator. Out-of-bag rows drop out
            # of histograms and counts (their grad/hess are already zeroed
            # by the caller) but still get leaf-routed, so the score
            # update reaches them - the reference's OOB traversal update
            # (gbdt.cpp:451-471) falls out for free.
            one_f = one_f * jnp.pad(row_mask, (0, npad_rows))
        gh5, qscales = self._stat_columns(grad, hess, one_f, tree_idx)
        wave_scales = qscales if self.quant_bits else None
        # int32 scan (grad_quant_bits=8 below INT32_SCAN_ROWS): the
        # per-leaf hist/total state stays in quantized integer units —
        # the parent-minus-sibling subtraction, default-bin
        # reconstruction and every prefix sum are then EXACT — and the
        # packed f32 records keep real units (pack_best dequantizes)
        int_scan = self.int_scan
        hdtype = jnp.int32 if int_scan else jnp.float32

        leaf_id0 = jnp.where(jnp.arange(n, dtype=jnp.int32) < num_valid,
                             0, -1)

        class _S(NamedTuple):
            leaf_id: jnp.ndarray        # (n,) i32
            hist: jnp.ndarray           # (L+1, S, 3) f32 (i32: int scan)
            total: jnp.ndarray          # (L+1, 3) f32 (i32: int scan)
            value: jnp.ndarray          # (L+1,) f32
            depth: jnp.ndarray          # (L+1,) i32
            best: jnp.ndarray           # (L+1, 13) f32, gain NEG_INF if none
            bestc: jnp.ndarray          # (L+1, 256) bool cat membership
            bestl: jnp.ndarray          # (L+1, 3) i32 exact left totals
            #                             of the best split (int scan;
            #                             (1, 3) dummy otherwise)
            nl: jnp.ndarray             # i32 leaves so far
            waves: jnp.ndarray          # i32 wave count (profiling)
            done: jnp.ndarray           # bool
            rec_i: jnp.ndarray          # (L, 5) i32   (last row = junk)
            rec_f: jnp.ndarray          # (L, 9) f32   (last row = junk)
            rec_c: jnp.ndarray          # (L, 8) i32   cat bin bitsets
            p_parent: jnp.ndarray       # (W,) i32  parent slot (-1 empty)
            p_small: jnp.ndarray        # (W,) i32  leaf whose hist is fresh
            p_large: jnp.ndarray        # (W,) i32  sibling (subtraction)

        # every per-leaf array carries one junk slot (index L; records:
        # index L-1) absorbing vector-scatter writes from empty lanes, so
        # scatters never collide with live leaves
        neg = jnp.full((L + 1, 13), NEG_INF, jnp.float32)
        W0 = min(4, W) if (4 < W and 8 < L) else W   # first stage width
        init = _S(
            leaf_id=leaf_id0,
            hist=jnp.zeros((L + 1, S, 3), hdtype),
            total=jnp.zeros((L + 1, 3), hdtype),
            value=jnp.zeros((L + 1,), jnp.float32),
            depth=jnp.zeros((L + 1,), jnp.int32),
            best=neg,
            bestc=jnp.zeros((L + 1, 256), bool),
            bestl=jnp.zeros((L + 1, 3) if int_scan else (1, 3),
                            jnp.int32),
            nl=jnp.asarray(1, jnp.int32),
            waves=jnp.asarray(0, jnp.int32),
            done=jnp.asarray(False),
            rec_i=jnp.full((L, REC_I_FIELDS), -1, jnp.int32),
            rec_f=jnp.zeros((L, REC_F_FIELDS), jnp.float32),
            rec_c=jnp.zeros((L, 8), jnp.int32),
            p_parent=jnp.full((W0,), -1, jnp.int32),
            p_small=jnp.concatenate([jnp.zeros(1, jnp.int32),
                                     jnp.full((W0 - 1,), -1, jnp.int32)])
            if W0 > 1 else jnp.zeros((1,), jnp.int32),
            p_large=jnp.full((W0,), -1, jnp.int32),
        )

        has_cat = self.has_cat
        # find-best placement for THIS trace: an explicit param wins,
        # auto adopts the construction-time verdict (possibly the
        # wave_plan=profiled winner).  Read from config inside the
        # traced region on purpose — the mode shapes the trace, so it
        # must stay in the program-cache signature (jaxlint JL101 pins
        # that coupling; dropping it via _NON_TRACE_PARAMS would let a
        # mode switch silently reuse the other mode's cached program).
        fmode = str(self.config.find_best_fusion or "auto").lower()
        fused_find = self.fused_find if fmode == "auto" \
            else fmode == "fused"

        def evaluate(hists, totals, ids, depths, feature_mask):
            """find-best over ONE histogram stack (split.py
            find_best_split_stack), gated by splittability.  Returns
            (packed (B,13), cat_member (B,256) bool, left_int (B,3) i32
            exact quantized-unit left totals — None unless the int32
            scan is active)."""
            cons = jnp.asarray([-jnp.inf, jnp.inf], jnp.float32)
            packed, catm, lint = find_best_split_stack(
                hists, totals, cons, feature_mask, meta, hyper, has_cat,
                scales=qscales if int_scan else None)
            if int_scan:
                ok = self._splittable(totals, depths,
                                      hess_scale=qscales[1]) & (ids >= 0)
            else:
                ok = self._splittable(totals, depths) & (ids >= 0)
            gain = jnp.where(ok, packed[:, F_GAIN], NEG_INF)
            return packed.at[:, F_GAIN].set(gain), catm, lint

        def make_wave(Ws: int):
          def wave(st: _S) -> _S:
            # 1. fresh histograms for pending smaller children
            fresh = self._wave_hist(binned, st.leaf_id, gh5,
                                    st.p_small, wave_scales)  # (W,S,3)
            root_wave = st.p_parent[0] < 0
            # root total from group-0 slot sums (every row hits one slot)
            root_total = fresh[0, :self.nb, :].sum(0)
            total = jnp.where(
                root_wave & (st.p_small[0] == 0),
                st.total.at[0].set(root_total), st.total)
            # 2. larger sibling = parent - smaller (parent hist still lives
            # at the parent's slot; smaller may reuse that slot, so read
            # parents BEFORE writing fresh)
            par = jnp.where(st.p_parent >= 0, st.p_parent, L)
            large = st.hist[par] - fresh                          # (W,S,3)
            sm_ok = st.p_small >= 0
            lg_ok = st.p_large >= 0
            sm_idx = jnp.where(sm_ok, st.p_small, L)
            lg_idx = jnp.where(lg_ok, st.p_large, L)
            hist = st.hist.at[sm_idx].set(
                jnp.where(sm_ok[:, None, None], fresh, st.hist[sm_idx]))
            hist = hist.at[lg_idx].set(
                jnp.where(lg_ok[:, None, None], large, hist[lg_idx]))
            # root value (stump case + records); int scan: the root
            # totals are quantized units, dequantize for the output
            if int_scan:
                rt_g = total[0, 0].astype(jnp.float32) * qscales[0]
                rt_h = total[0, 1].astype(jnp.float32) * qscales[1]
            else:
                rt_g, rt_h = total[0, 0], total[0, 1]
            value = jnp.where(
                root_wave,
                st.value.at[0].set(self._leaf_output(rt_g, rt_h, hyper)),
                st.value)

            # 3. find-best for the new leaves (both siblings); reuse the
            # fresh/large buffers rather than re-gathering from hist
            ids_s = jnp.where(sm_ok, st.p_small, -1)
            ids_l = jnp.where(lg_ok, st.p_large, -1)
            ids = jnp.concatenate([ids_s, ids_l])
            idc = jnp.clip(ids, 0, L - 1)
            if fused_find:
                # fused find-best-in-wave: the gain scan consumes the
                # fresh histogram product and the parent-minus-sibling
                # residual IN PLACE — no (2*Ws, S, 3) concatenated
                # tensor materializes between the contraction and the
                # scan, so XLA fuses the hist+find of a wave into one
                # program region and only the packed winner records
                # (and the residual scattered into the leaf state)
                # survive it.  vmap is per-lane, so each half is
                # bitwise the rows the concatenated scan would produce
                # (tests/test_fused_find.py pins this per regime).
                ics, icl = idc[:Ws], idc[Ws:]
                pk_s, cm_s, li_s = evaluate(fresh, total[ics], ids_s,
                                            st.depth[ics], feature_mask)
                pk_l, cm_l, li_l = evaluate(large, total[icl], ids_l,
                                            st.depth[icl], feature_mask)
                packed = jnp.concatenate([pk_s, pk_l])
                catm = jnp.concatenate([cm_s, cm_l])
                lint = jnp.concatenate([li_s, li_l]) if int_scan \
                    else None
            else:
                # two-pass layout: one concatenated (2*Ws, S, 3) stack
                # scanned by a single second pass
                hists2 = jnp.concatenate([fresh, large])
                packed, catm, lint = evaluate(hists2, total[idc], ids,
                                              st.depth[idc],
                                              feature_mask)
            safe = jnp.where(ids >= 0, ids, L)
            best = st.best.at[safe].set(
                jnp.where((ids >= 0)[:, None], packed, st.best[safe]))
            bestc = st.bestc.at[safe].set(
                jnp.where((ids >= 0)[:, None], catm, st.bestc[safe]))
            if int_scan:
                bestl = st.bestl.at[safe].set(
                    jnp.where((ids >= 0)[:, None], lint, st.bestl[safe]))
            else:
                bestl = st.bestl

            # 4. select up to Ws best-gain splits within budget
            gains = best[:L, F_GAIN]
            top_vals, top_idx = jax.lax.top_k(gains, Ws)
            budget = (L - st.nl).astype(jnp.int32)
            sel = (top_vals > 0.0) & (jnp.arange(Ws) < budget)
            napply = sel.sum().astype(jnp.int32)
            rank = jnp.cumsum(sel.astype(jnp.int32)) - 1

            # 5. apply all selected splits at once.  Selected leaves are
            # distinct (top_k) and so are the new right ids, so scatters
            # can't collide; invalid lanes are routed to the junk rows.
            lsel = top_idx.astype(jnp.int32)                  # (W,)
            vecs = best[lsel]                                 # (W,13)
            r_ids = st.nl + rank                              # (W,)
            f = vecs[:, F_FEATURE].astype(jnp.int32)
            thr = vecs[:, F_THRESHOLD].astype(jnp.int32)
            dl = vecs[:, F_DEFAULT_LEFT] > 0.5
            grp = tables.group[f]
            off = tables.offset[f]
            wid = tables.width[f]
            db = meta.default_bin[f]
            nbin = meta.num_bin[f]
            miss = meta.missing[f]
            def_left = jnp.where(miss == 1, dl, db <= thr)    # (W,)

            # leaf_id update: ONE fused vectorized pass over the W
            # selected feature rows of the contiguous (G, N) matrix
            # (replaces r3's W-times-unrolled dynamic-slice loop, which
            # re-read leaf_id and re-wrote the update vector per split).
            # Masks are disjoint (a row belongs to at most one selected
            # leaf), so the masked deltas sum without collisions.  All
            # values are group-local bins (< nb <= 256), so the whole
            # (W, N) chain runs in int16 — at W=128 the materialized
            # intermediates drop from ~5.4 GB to ~2.7 GB of HBM traffic.
            i16 = lambda a: a.astype(jnp.int16)
            cols = i16(jnp.take(binned_t, grp, axis=0))           # (W,N)
            off16, wid16 = i16(off)[:, None], i16(wid)[:, None]
            db16, nbin16 = i16(db)[:, None], i16(nbin)[:, None]
            thr16 = i16(thr)[:, None]
            shift = jnp.where(db16 == 0, jnp.int16(1), jnp.int16(0))
            in_range = (cols >= off16) & (cols < off16 + wid16)
            bin_ = jnp.where(in_range, cols - off16 + shift, db16)
            is_default = bin_ == db16
            is_na = (miss[:, None] == 2) & (bin_ == nbin16 - 1)
            goes_left = jnp.where(is_default, def_left[:, None],
                                  jnp.where(is_na, dl[:, None],
                                            bin_ <= thr16))
            if has_cat:
                # categorical routing: left iff the decoded bin is in the
                # winning category set (partition.py:49 semantics); the
                # (W,256) membership is packed into 8 x i32 words and the
                # per-row word picked with an 8-way select chain (a
                # table gather here measured far slower on TPU)
                cm = bestc[jnp.clip(lsel, 0, L)]            # (W, 256)
                cmw = jnp.sum(
                    cm.reshape(Ws, 8, 32).astype(jnp.int32)
                    << jnp.arange(32, dtype=jnp.int32)[None, None, :],
                    axis=-1)                                # (W, 8)
                binc = bin_.astype(jnp.int32)   # 32-bit word arithmetic
                widx = binc >> 5
                bit = binc & 31
                wv = jnp.zeros_like(binc)
                for j in range(8):
                    wv = wv + jnp.where(widx == j, cmw[:, j:j + 1], 0)
                left_cat = ((wv >> bit) & 1) == 1
                is_cat_w = vecs[:, F_IS_CAT] > 0.5
                goes_left = jnp.where(is_cat_w[:, None], left_cat,
                                      goes_left)
            mask = (sel[:, None] & (st.leaf_id[None, :] == lsel[:, None])
                    & ~goes_left)
            upd = jnp.sum(mask * (r_ids - lsel)[:, None], axis=0,
                          dtype=jnp.int32)
            leaf_id = st.leaf_id + upd

            # bookkeeping (vectorized scatters into the L-padded arrays)
            safe_l = jnp.where(sel, lsel, L)
            safe_r = jnp.where(sel, r_ids, L)
            if int_scan:
                # exact integer child totals: the winner's left sums
                # come straight from the scan (bestl) and the right
                # child is the parent total minus them — both in
                # quantized units, both exact (read the parent BEFORE
                # the scatter overwrites its slot)
                lsum = bestl[jnp.clip(lsel, 0, L)]
                rsum = total[jnp.clip(lsel, 0, L)] - lsum
            else:
                lsum = vecs[:, jnp.asarray([F_LEFT_G, F_LEFT_H,
                                            F_LEFT_C])]
                rsum = vecs[:, jnp.asarray([F_RIGHT_G, F_RIGHT_H,
                                            F_RIGHT_C])]
            total = total.at[safe_l].set(
                jnp.where(sel[:, None], lsum, total[safe_l]))
            total = total.at[safe_r].set(
                jnp.where(sel[:, None], rsum, total[safe_r]))
            value = value.at[safe_l].set(
                jnp.where(sel, vecs[:, F_LEFT_OUT], value[safe_l]))
            value = value.at[safe_r].set(
                jnp.where(sel, vecs[:, F_RIGHT_OUT], value[safe_r]))
            child_d = st.depth[jnp.clip(lsel, 0, L)] + 1
            depth = st.depth.at[safe_l].set(
                jnp.where(sel, child_d, st.depth[safe_l]))
            depth = depth.at[safe_r].set(
                jnp.where(sel, child_d, depth[safe_r]))
            best = best.at[safe_l].set(
                jnp.where(sel[:, None], neg[0][None, :], best[safe_l]))
            best = best.at[safe_r].set(
                jnp.where(sel[:, None], neg[0][None, :], best[safe_r]))
            # split records (rows are padded by one junk row at index L-1)
            ridx = jnp.where(sel, st.nl - 1 + rank, L - 1)
            new_ri = jnp.stack([lsel, r_ids, f, thr,
                                dl.astype(jnp.int32)], axis=1)
            new_rf = jnp.stack(
                [vecs[:, F_GAIN], vecs[:, F_LEFT_G], vecs[:, F_LEFT_H],
                 vecs[:, F_LEFT_C], vecs[:, F_RIGHT_G], vecs[:, F_RIGHT_H],
                 vecs[:, F_RIGHT_C], vecs[:, F_LEFT_OUT],
                 vecs[:, F_RIGHT_OUT]], axis=1)
            rec_i = st.rec_i.at[ridx].set(
                jnp.where(sel[:, None], new_ri, st.rec_i[ridx]))
            rec_f = st.rec_f.at[ridx].set(
                jnp.where(sel[:, None], new_rf, st.rec_f[ridx]))
            if has_cat:
                rec_c = st.rec_c.at[ridx].set(
                    jnp.where(sel[:, None], cmw, st.rec_c[ridx]))
            else:
                rec_c = st.rec_c
            # pending for the next wave (int scan: exact integer counts
            # decide the smaller sibling — f32 counts round past 2^24)
            if int_scan:
                small_left = lsum[:, 2] <= rsum[:, 2]
            else:
                small_left = vecs[:, F_LEFT_C] <= vecs[:, F_RIGHT_C]
            pp = jnp.where(sel, lsel, -1)
            ps = jnp.where(sel, jnp.where(small_left, lsel, r_ids), -1)
            pl = jnp.where(sel, jnp.where(small_left, r_ids, lsel), -1)

            return _S(leaf_id=leaf_id, hist=hist, total=total, value=value,
                      depth=depth, best=best, bestc=bestc, bestl=bestl,
                      nl=st.nl + napply,
                      waves=st.waves + 1, done=napply == 0,
                      rec_i=rec_i, rec_f=rec_f, rec_c=rec_c,
                      p_parent=pp, p_small=ps, p_large=pl)
          return wave

        # staged wave widths: the early frontier has 1 -> 2 -> 4 -> ...
        # pending leaves, so a full-width wave wastes almost its whole
        # column tile on empty lanes (the matmul cost is W x hist_cols
        # columns regardless of how many are live).  Growing the width
        # with the frontier cuts the early waves' cost ~5-10x; each stage
        # is its own while_loop over the same state with the pending
        # arrays padded to the next width.  The plan comes from
        # ops/stage_plan.py (byte-stable default or profile-derived).
        def resize(st: _S, w_to: int) -> _S:
            pad = w_to - st.p_parent.shape[0]
            if pad <= 0:
                return st
            ext = jnp.full((pad,), -1, jnp.int32)
            return st._replace(
                p_parent=jnp.concatenate([st.p_parent, ext]),
                p_small=jnp.concatenate([st.p_small, ext]),
                p_large=jnp.concatenate([st.p_large, ext]))

        plan = self.stage_plan
        st = init
        for ws, cap in plan:
            st = resize(st, ws)
            limit = L if cap is None else min(cap, L)
            st = jax.lax.while_loop(
                lambda s, lim=limit: (~s.done) & (s.nl < lim),
                make_wave(ws), st)
        final = st
        leaf_final = final.leaf_id
        rec_f_out = final.rec_f

        if self.quant_bits:
            # full-precision leaf-value REFIT (Shi et al. §4.3): tree
            # STRUCTURE came from quantized histograms, but each final
            # leaf's value is recomputed from the full-precision
            # gradients, then written back into the split records so
            # host-materialized trees match the device score update.
            if int_scan:
                # exact integer refit: each masked gradient is split
                # into THREE base-128 int8 digits against the (global)
                # quantization scale — deterministic round-to-nearest,
                # no noise — and the per-leaf digit sums accumulate
                # int8->int32 on the MXU.  Per-row representation error
                # is <= scale/2^15 ~ max|g| * 2^-22 (f32-class), the
                # SUMS are bit-exact in any order — which is what keeps
                # sharded leaf values byte-identical to single-device
                # (an f32 contraction's accumulation order would not
                # survive the psum split).  |digit sums| <= 127 * rows
                # stays in int32 under the same INT32_SCAN_ROWS gate as
                # the histograms.
                def _digits(x, s):
                    cols = []
                    r, sd = x, s
                    for _ in range(3):
                        d = jnp.clip(jnp.round(r / sd), -QUANT_MAX,
                                     QUANT_MAX)
                        r = r - d * sd
                        cols.append(d.astype(jnp.int8))
                        sd = sd / 128.0
                    return cols
                dcols = jnp.stack(_digits(grad * one_f, qscales[0])
                                  + _digits(hess * one_f, qscales[1]), 1)
                oh8 = jax.nn.one_hot(leaf_final, L, dtype=jnp.int8)
                sums6 = jnp.einsum("nl,nk->lk", oh8, dcols,
                                   preferred_element_type=jnp.int32)
                if self.shard is not None:
                    sums6 = jax.lax.psum(sums6, self.shard.axis)
                f32 = lambda a: a.astype(jnp.float32)
                gsum = (f32(sums6[:, 0]) + f32(sums6[:, 1]) * (1 / 128.0)
                        + f32(sums6[:, 2]) * (1 / 16384.0)) * qscales[0]
                hsum = (f32(sums6[:, 3]) + f32(sums6[:, 4]) * (1 / 128.0)
                        + f32(sums6[:, 5]) * (1 / 16384.0)) * qscales[1]
                refit = self._leaf_output(gsum, hsum, hyper)
            else:
                # f32 fallback regime: hi/lo-bf16 one-hot contraction
                # (same cost class as the score update); sharded, the
                # per-shard partial sums psum in f32 — deterministic,
                # though not bitwise equal to single-device order (no
                # byte-identity contract past the int32 bound)
                one_b = one_f.astype(jnp.bfloat16)
                cols4 = jnp.stack(_hi_lo_cols(grad, hess, one_b), 1)
                ohl = jax.nn.one_hot(leaf_final, L, dtype=jnp.bfloat16)
                sums = jnp.einsum("nl,nk->lk", ohl, cols4,
                                  preferred_element_type=jnp.float32)
                if self.shard is not None:
                    sums = jax.lax.psum(sums, self.shard.axis)
                refit = self._leaf_output(sums[:, 0] + sums[:, 1],
                                          sums[:, 2] + sums[:, 3], hyper)
            exists = jnp.arange(L, dtype=jnp.int32) < final.nl
            # each final leaf's value lives in its CREATING record (the
            # last record mentioning the leaf id: left children keep the
            # parent's id, right ids are fresh); segment-max over the
            # record index finds it without a host loop
            recs_r = jnp.arange(L, dtype=jnp.int32)
            lid, rid = final.rec_i[:, 0], final.rec_i[:, 1]
            base = jnp.full((L + 1,), -1, jnp.int32)
            last_l = base.at[jnp.where(lid >= 0, lid, L)].max(recs_r)
            last_r = base.at[jnp.where(rid >= 0, rid, L)].max(recs_r)
            crec = jnp.maximum(last_l[:L], last_r[:L])
            is_left = last_l[:L] >= last_r[:L]
            do = exists & (crec >= 0)
            if self.has_cat:
                # leaves created by a categorical split keep their
                # growth value: sorted-mode cat splits regularize with
                # lambda_l2 + cat_l2 (split.py pack_best use_l2), which
                # the plain-lambda_l2 refit formula would drop —
                # under-regularizing exactly those leaves
                cfeat = final.rec_i[jnp.where(do, crec, 0), 2]
                from_cat = do & (meta.is_cat[jnp.clip(cfeat, 0, None)]
                                 == 1)
                refit = jnp.where(from_cat, final.value[:L], refit)
            leaf_vals = jnp.where(exists, refit, 0.0)
            rows = jnp.where(do, crec, L - 1)        # junk record row
            cols_i = jnp.where(is_left, REC_F_LEFT_OUT, REC_F_RIGHT_OUT)
            rec_f_out = rec_f_out.at[rows, cols_i].set(
                jnp.where(do, leaf_vals, rec_f_out[rows, cols_i]))
        else:
            leaf_vals = final.value[:L]

        # score update: score[row] += lr * value[leaf_id[row]] via one-hot
        # matmul (hi/lo split keeps f32-level precision at bf16 speed).
        # A stump (root never split) applies nothing: the boosting driver
        # treats it as the stop signal, matching GBDT::TrainOneIter.
        scaled = leaf_vals * lr * (final.nl > 1)
        vhi = scaled.astype(jnp.bfloat16)
        vlo = (scaled - vhi.astype(jnp.float32)).astype(jnp.bfloat16)
        vmat = jnp.stack([vhi, vlo], 1)                       # (L, 2)
        oh = jax.nn.one_hot(leaf_final, L, dtype=jnp.bfloat16)
        upd = jnp.einsum("nl,lk->nk", oh, vmat,
                         preferred_element_type=jnp.float32)
        new_score = score + (upd[:, 0] + upd[:, 1])[:self.num_data]

        return (new_score, final.rec_i[:max(L - 1, 1)],
                rec_f_out[:max(L - 1, 1)],
                final.rec_c[:max(L - 1, 1)], final.nl, final.value[0],
                final.waves, qscales)

    # ------------------------------------------------------------------
    def fused_train(self, length: int):
        """Jitted program running ``length`` whole boosting iterations in
        ONE device dispatch: gradients -> tree growth -> score update
        inside a ``lax.scan`` over iterations.

        Motivation: the per-iteration path needs ~5 host-side steps per
        tree (gradient dispatch, grow dispatch, score set, record
        copies), and on a loaded host that Python loop starves the
        device — the driver-recorded HIGGS run measured 771 ms/tree vs
        468 ms/tree idle-host for identical device work.  Fusing K
        iterations amortizes every host touch 1/K and makes wall-clock
        track device throughput.

        Sampling lives INSIDE the scan: the per-tree feature_fraction
        mask is ``fold_in(key, tree_idx)``, the bagging row mask is
        re-drawn every ``bagging_freq`` trees with the per-iteration
        path's exact ``(bagging_seed + it)`` seeding, and the int8
        quantization noise is keyed by the same global tree index — so
        fused and per-iteration emit bit-identical trees even with
        quantization on (tests/test_fused.py, tests/test_quant.py).

        Signature of the returned (raw) program::

            run(binned, binned_t, score, lr, gargs, it0, num_valid,
                meta, hyper, tables, grad_fn=fn)
            -> (final_score,
                (rec_i (K,L-1,5), rec_f (K,L-1,9), rec_c (K,L-1,8),
                 nl (K,), root_value (K,), waves (K,), qscales (K,2)))

        ``it0`` is the global iteration index of the chunk's first tree
        (traced, so resuming mid-run reuses the compiled program);
        ``num_valid`` is the real row count (traced i32 — score/gargs
        rows past it are train_row_bucketing pad).
        ``grad_fn(score, gargs) -> (grad, hess)`` comes from
        ``ObjectiveFunction.device_grad`` (pure jnp; all arrays via
        ``gargs``).  Compiled once per (length, grad_fn) pair — callers
        must reuse one grad_fn instance to hit the jit cache.
        ``DeviceGrower.fused_train`` wraps this with the grower's own
        meta/hyper/tables so boosting-layer call sites stay unchanged.
        """
        with self._fused_lock:
            return self._fused_program(length)

    def _fused_program(self, length: int):
        if length not in self._fused:
            use_bag = self._bag_fraction < 1.0 and self._bag_freq > 0
            bag_freq, bag_seed = self._bag_freq, self._bag_seed
            bag_frac, bag_npad = self._bag_fraction, self._bag_npad
            sp = self.shard

            def draw_bag(it):
                seed = (bag_seed + it) & 0x7FFFFFFF
                if sp is None:
                    from .bagging import bagging_row_mask
                    return bagging_row_mask(seed, bag_npad,
                                            self.num_data, bag_frac)
                # sharded: draw the CANONICAL GLOBAL mask (same shape,
                # same stream as the single-device path) and take this
                # shard's block — bags are shard-invariant bit-for-bit
                from .bagging import bagging_row_mask_global
                full = bagging_row_mask_global(seed, bag_npad,
                                               sp.global_rows, bag_frac)
                return slice_global_draw(sp, full, self.n_pad)

            def scan_core(binned, binned_t, score, lr, gargs, it0,
                          num_valid, meta, hyper, tables, grad_fn):
                """The K-iteration scan; ``num_valid`` is already the
                shard-local cutoff when sharded."""
                no_mask = jnp.zeros((0,), jnp.float32)
                its = jnp.arange(length, dtype=jnp.int32) + it0

                def body(carry, it):
                    sc, bmask = (carry if use_bag else (carry, None))
                    g, h = grad_fn(sc, gargs)
                    fmask = self.feature_mask_for(it)
                    if use_bag:
                        # cond, not where: only redraw steps pay the
                        # (bag_npad,) uniform generation
                        bmask = jax.lax.cond(it % bag_freq == 0,
                                             lambda: draw_bag(it),
                                             lambda: bmask)
                    (new_score, rec_i, rec_f, rec_c, nl, root, waves,
                     qs) = self._grow_impl(
                        binned, binned_t, sc, g, h, fmask, lr,
                        bmask if use_bag else no_mask, it, num_valid,
                        meta, hyper, tables, with_mask=use_bag)
                    out = (rec_i, rec_f, rec_c, nl, root, waves, qs)
                    return ((new_score, bmask) if use_bag
                            else new_score), out

                if use_bag:
                    # carry init: the mask active at it0 — drawn at the
                    # last redraw boundary; when it0 itself is a boundary
                    # the first step re-draws the same seed (no-op)
                    init = (score, draw_bag(it0 - it0 % bag_freq))
                    (final_score, _), recs = jax.lax.scan(
                        body, init, its)
                    return final_score, recs
                return jax.lax.scan(body, score, its)

            if sp is None:
                run = scan_core
            else:
                def run(binned, binned_t, score, lr, gargs, it0,
                        num_valid, meta, hyper, tables, grad_fn):
                    # whole-scan shard_map: K trees per dispatch on every
                    # chip, one histogram psum per wave inside.  Specs
                    # are built at trace time (gargs structure is part
                    # of the jit key anyway): per-row gargs leaves ride
                    # the mesh axis, everything else is replicated.
                    from jax.sharding import PartitionSpec as P
                    row, rep = P(sp.axis), P()
                    total = sp.n_shards * self.n_pad
                    gspec = jax.tree_util.tree_map(
                        lambda a: P(sp.axis, *([None] * (a.ndim - 1)))
                        if (getattr(a, "ndim", 0) >= 1
                            and a.shape[0] == total) else rep, gargs)
                    in_specs = (P(sp.axis, None), P(None, sp.axis), row,
                                rep, gspec, rep, rep, rep, rep, rep)
                    out_specs = (row, rep)

                    def body(b, bt, sc, lr_, ga, i0, nv, me, hy, ta):
                        nv_loc = local_valid_rows(sp, self.n_pad, nv)
                        return scan_core(b, bt, sc, lr_, ga, i0, nv_loc,
                                         me, hy, ta, grad_fn)

                    return shard_map_compat(
                        body, self.mesh, in_specs, out_specs)(
                        binned, binned_t, score, lr, gargs, it0,
                        num_valid, meta, hyper, tables)

            self._fused[length] = obs.track_jit(
                "fused_train_sharded" if sp is not None else "fused_train",
                jax.jit(run, static_argnames=("grad_fn",)),
                static_info=(f"len={length}",))
        return self._fused[length]


# ---------------------------------------------------------------------------
# process-level program cache: the expensive artifact of a DeviceGrower is
# its jitted (traced + compiled) programs, and nothing in them depends on
# the DATA — only on shapes, bin-structure flags and config.  Sharing them
# across grower instances removes the per-window re-trace cost of the
# retrain-every-window harness (ROUND6_NOTES "still open" item).
# ---------------------------------------------------------------------------
_PROGRAM_CACHE: "OrderedDict[tuple, GrowerPrograms]" = OrderedDict()
_PROGRAM_CACHE_LOCK = threading.Lock()
_PROGRAM_CACHE_MAX = 8


# params that never shape a trace, so they must stay out of the
# signature: wave_plan/grower_cache only steer host-side plan resolution
# and caching (keying on them would stop a wave_plan=auto run from
# picking up a profiled run's cached plan — the plan itself is keyed
# separately via its digest), and learning_rate is a traced argument
# (so callbacks may decay it without forcing a program-cache miss)
_NON_TRACE_PARAMS = ("wave_plan", "grower_cache", "learning_rate")


def _config_digest(config) -> str:
    items = sorted((k, repr(v)) for k, v in config.to_dict().items()
                   if k not in _NON_TRACE_PARAMS)
    return hashlib.sha1(repr(items).encode()).hexdigest()


def resolve_find_fusion(config, signature: Optional[tuple] = None) -> str:
    """Resolve ``find_best_fusion`` to the concrete wave layout
    ("fused" / "two_pass"): explicit values pass through; ``auto``
    adopts a ``wave_plan=profiled`` fused-vs-two-pass verdict cached in
    process or persisted beside the compile cache for this signature
    (ops/stage_plan.py), else defaults to fused.  The resolved mode
    joins the program-cache key in :func:`get_grower_programs` — two
    processes whose ``auto`` resolves differently must re-trace, never
    reuse the other layout's compiled program."""
    mode = str(getattr(config, "find_best_fusion", "auto")
               or "auto").lower()
    if mode in ("fused", "two_pass"):
        return mode
    if signature is not None:
        cached = stage_plan_mod.cached_fusion(signature)
        if cached is None:
            cached = stage_plan_mod.load_fusion(signature)
            if cached is not None:
                stage_plan_mod.cache_fusion(signature, cached,
                                            persist=False)
                obs.inc("grow.fusion_persisted_loads")
        if cached in ("fused", "two_pass"):
            return cached
    return "fused"


def programs_signature(num_data: int, num_groups: int, nb: int,
                       num_features: int, has_cat: bool, config,
                       shard: Optional[ShardSpec] = None) -> tuple:
    """Everything a GrowerPrograms trace depends on besides the stage
    plan: array shapes, bin-structure flags, module tunables and the
    full config (hashed — over-keying only costs cache hits, never
    correctness).  Sharded programs append the mesh size plus the
    canonical global draw shapes (``num_data`` is then the per-shard
    row bucket); unsharded signatures keep the historical layout so
    persisted stage plans stay valid."""
    base = (num_data, num_groups, nb, num_features, bool(has_cat),
            _CHUNK, COUNT_SPLIT_ROWS, INT32_SCAN_ROWS,
            _config_digest(config))
    if shard is not None:
        base = base + (("shard", shard.n_shards, shard.global_rows,
                        shard.draw_npad, shard.bag_npad),)
    return base


def get_grower_programs(num_data: int, num_groups: int, nb: int,
                        num_features: int, has_cat: bool, config,
                        plan: Optional[list] = None,
                        plan_source: str = "default",
                        shard: Optional[ShardSpec] = None,
                        mesh=None) -> GrowerPrograms:
    """Fetch (or build) the shared programs for this signature.  When no
    explicit plan is given, a profile-derived plan cached for the same
    signature (``DeviceGrower.profile_stage_plan``) is picked up under
    ``wave_plan=auto``/``profiled``."""
    base = programs_signature(num_data, num_groups, nb, num_features,
                              has_cat, config, shard=shard)
    if shard is not None and mesh is not None:
        # same shard layout over a different device set must not share
        # compiled programs (the mesh is baked into the shard_map)
        base = base + (tuple(int(d.id) for d in mesh.devices.flat),)
    if plan is None and str(getattr(config, "wave_plan", "auto")).lower() \
            in ("auto", "profiled"):
        cached = stage_plan_mod.cached_plan(base)
        if cached is not None:
            plan, plan_source = cached, "profiled"
        else:
            # cross-process: a plan profiled by an earlier process is
            # persisted beside the compile cache — adopt it instead of
            # re-measuring (ROADMAP 1c; corrupt/mismatched files fall
            # back to the legacy plan below)
            persisted = stage_plan_mod.load_plan(base)
            if persisted is not None:
                plan, plan_source = persisted, "persisted"
                stage_plan_mod.cache_plan(base, persisted, persist=False)
                obs.inc("grow.plan_persisted_loads")
    if plan is None:
        plan = default_stage_plan(num_data, config)
    pd = stage_plan_mod.plan_digest(plan)
    # resolved find-best layout: like the plan digest, auto's verdict
    # is resolved HERE (once) and keyed — a cached entry built under
    # the other layout must never serve this resolution
    fusion = resolve_find_fusion(config, base)
    build = functools.partial(
        GrowerPrograms, num_data=num_data, num_groups=num_groups, nb=nb,
        num_features=num_features, has_cat=has_cat, config=config,
        plan=plan, plan_source=plan_source, fusion=fusion, shard=shard,
        mesh=mesh)
    if not bool(getattr(config, "grower_cache", True)):
        return build()
    key = base + (pd, fusion)
    with _PROGRAM_CACHE_LOCK:
        progs = _PROGRAM_CACHE.get(key)
        if progs is not None:
            _PROGRAM_CACHE.move_to_end(key)
            if plan_source in ("profiled", "persisted"):
                # the profiled plan can coincide with the plan a cached
                # entry was built under (same digest => same key); the
                # plan is now measurement-confirmed either way
                progs.plan_source = plan_source
            obs.inc("grow.cache_hits")
            return progs
        obs.inc("grow.cache_misses")
        progs = build()
        _PROGRAM_CACHE[key] = progs
        while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
            _PROGRAM_CACHE.popitem(last=False)
        return progs


class DeviceGrower:
    """Grows whole trees on device; one dispatch per boosting iteration.

    Parameters mirror the serial learner's (dataset, config) pair.  The
    instance owns the device copies of the binned matrix in both layouts
    plus the per-dataset metadata arrays; the jitted programs come from
    the shared process-level cache (:func:`get_grower_programs`) and are
    reached through attribute forwarding, so ``grower.hist_cols`` etc.
    keep working."""

    def __init__(self, dataset, config, row_bucketing=None, mesh=None):
        self.config = config
        self.dataset = dataset
        self.num_data = int(dataset.num_data)
        # single-controller data-parallel mesh (ops/shard.py): rows are
        # split over the mesh axis, wave histograms psum-reduce, every
        # device grows the identical tree.  A 1-device mesh degrades to
        # the plain unsharded grower (identical programs, no shard_map).
        self.mesh = mesh if (mesh is not None
                             and int(mesh.devices.size) > 1) else None

        # per-group slot pitch: smallest power of two covering every group
        nb = 64
        for g in dataset.groups:
            while g.num_total_bin > nb:
                nb *= 2

        # training-shape bucketing: key the program cache (in-process
        # AND the persistent XLA cache, docs/ColdStart.md) on a pow2 row
        # bucket instead of the exact row count, so one compiled program
        # family covers a whole traffic range of retrain-window sizes.
        # The ladder is histogram.bucket_size — the SAME pad the bagging
        # buffer uses, so the fused scan's in-scan bagging draw stays
        # bit-identical to the unbucketed path (the uniform stream's
        # shape is part of the draw).  The real row count travels as the
        # traced `num_valid` scalar; bucket-pad rows carry zero
        # grad/hess/count exactly like the chunk pad, so trees are
        # byte-identical.  Exceptions: grad_quant_bits keys its
        # stochastic-rounding stream on the padded shape (the caller
        # disables bucketing there to keep the quant contract), and a
        # bucket crossing the striped-count eligibility bound falls back
        # to exact rows.
        if row_bucketing is None:
            row_bucketing = bool(getattr(config, "train_row_bucketing",
                                         True))
        quant_on = bool(int(getattr(config, "grad_quant_bits", 0) or 0))
        if self.mesh is not None:
            # sharded layout: the GLOBAL row count pads to
            # n_devices x (per-shard pow2 bucket), so per-shard shapes
            # stay on the bucket ladder and one compiled program family
            # covers a whole traffic range of window sizes per mesh
            # size.  Quantized runs key their rounding stream on the
            # canonical global shape instead of the bucket (same
            # reasoning as the unsharded quant/bucketing exclusion), so
            # they shard exact per-shard rows.
            from .shard import (SHARD_AXIS, mesh_is_multihost,
                                shard_local_rows)
            d = int(self.mesh.devices.size)
            n_loc = shard_local_rows(self.num_data, d, config,
                                     row_bucketing=row_bucketing)
            # pod slice: same mesh-invariant programs, but the score
            # output comes back row-sharded across PROCESSES and must
            # be resharded to fully-replicated before any host read
            self._multihost = mesh_is_multihost(self.mesh)
            self._shard_spec = ShardSpec(
                n_shards=d, axis=SHARD_AXIS, global_rows=self.num_data,
                draw_npad=_ceil_to(max(self.num_data, _CHUNK), _CHUNK),
                bag_npad=bucket_size(max(self.num_data, 1)))
            self.row_bucket = int(n_loc)
            has_cat = bool(np.asarray(dataset.f_is_categorical).any())
            self.programs = get_grower_programs(
                self.row_bucket, int(dataset.num_groups), nb,
                int(dataset.num_features), has_cat, config,
                shard=self._shard_spec, mesh=self.mesh)
            self._base_signature = programs_signature(
                self.row_bucket, int(dataset.num_groups), nb,
                int(dataset.num_features), has_cat, config,
                shard=self._shard_spec)
            self._num_valid = jnp.asarray(self.num_data, jnp.int32)
            total_rows = d * self.programs.n_pad
            self._row_pad = total_rows - self.num_data
            obs.set_gauge("shard.devices", d)
            obs.set_gauge("shard.local_rows", int(self.programs.n_pad))
            if self._multihost:
                import jax as _jax
                obs.set_gauge("shard.hosts",
                              int(_jax.process_count()))
            self._upload_binned(dataset, total_rows - self.num_data)
            self.meta = FeatureMeta.from_dataset(dataset, slot_stride=nb)
            self.hyper = SplitHyper.from_config(config)
            self.tables = FTables.from_dataset(dataset)
            self.lr = float(config.learning_rate)
            return
        self._shard_spec = None
        self._multihost = False
        bucket = self.num_data
        if row_bucketing and not quant_on:
            bucket = bucket_size(max(self.num_data, 1))
            if bucket >= 2 * COUNT_SPLIT_ROWS:
                # the pow2 bucket would cross the striped-count
                # eligibility bound the exact row count still satisfies
                # (device_growth_eligible checks the REAL rows) — fall
                # back to exact rows rather than to the host learner.
                # Say so: an operator counting on one program family
                # per bucket should see why >16.7M-row windows each
                # compile their own
                from ..utils.log import log_info
                log_info(
                    f"train_row_bucketing: row bucket {bucket} would "
                    f"reach the striped-count bound "
                    f"({2 * COUNT_SPLIT_ROWS}); using exact rows "
                    f"({self.num_data}) — programs are per-row-count "
                    f"at this scale")
                bucket = self.num_data
        self.row_bucket = int(bucket)

        has_cat = bool(np.asarray(dataset.f_is_categorical).any())
        self.programs = get_grower_programs(
            self.row_bucket, int(dataset.num_groups), nb,
            int(dataset.num_features), has_cat, config)
        self._base_signature = programs_signature(
            self.row_bucket, int(dataset.num_groups), nb,
            int(dataset.num_features), has_cat, config)
        self._num_valid = jnp.asarray(self.num_data, jnp.int32)
        self._row_pad = self.row_bucket - self.num_data

        self._upload_binned(dataset, self.programs.n_pad - self.num_data)

        self.meta = FeatureMeta.from_dataset(dataset, slot_stride=nb)
        self.hyper = SplitHyper.from_config(config)
        self.tables = FTables.from_dataset(dataset)
        self.lr = float(config.learning_rate)

    def _upload_binned(self, dataset, pad: int):
        """Upload the (N, G) binned matrix padded by ``pad`` rows, plus
        its (G, N) device-side transpose (uploading the transpose
        separately doubled the host->device transfer and the host
        ascontiguousarray pass — ~seconds at 10M rows).  Sharded, both
        layouts are placed row-split over the mesh axis so each device
        holds ONLY its shard's rows."""
        if self._multihost:
            self._upload_binned_multihost(dataset)
            return
        if getattr(dataset, "device_binned", False):
            # matrix already lives in HBM (construct_from_device_matrix)
            binned_d = dataset.binned
            if pad:
                binned_d = jnp.pad(binned_d, ((0, pad), (0, 0)))
            self.binned = binned_d
        else:
            binned = np.asarray(dataset.binned)  # (N, G) uint8
            if pad:
                binned = np.pad(binned, ((0, pad), (0, 0)))
            self.binned = jnp.asarray(binned)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            axis = self._shard_spec.axis
            self.binned = jax.device_put(
                self.binned, NamedSharding(self.mesh, P(axis, None)))
            # transpose stays device-side; the explicit placement pins
            # the (G, N) copy column-split so each device again holds
            # only its rows
            self.binned_t = jax.device_put(
                jnp.transpose(self.binned),
                NamedSharding(self.mesh, P(None, axis)))
        else:
            self.binned_t = jnp.transpose(self.binned)

    def _upload_binned_multihost(self, dataset):
        """Pod-slice upload: each process contributes ONLY its own
        contiguous padded row block via
        ``make_array_from_process_local_data`` — no host ever
        materializes (or ships) the global matrix.  Two sources:

        * a host-sharded dataset from the streaming multihost loader
          (``dataset.host_shard``): ``dataset.binned`` IS the local
          padded block, validated against the mesh's row span;
        * a replicated dataset (every process constructed the full
          matrix, e.g. the test path): slice this process's block out.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..utils.log import LightGBMError
        from .shard import process_row_span, transpose_col_sharded
        spec = self._shard_spec
        n_pad = int(self.programs.n_pad)
        lo, hi = process_row_span(self.mesh, n_pad)
        if getattr(dataset, "host_shard", False):
            local = np.ascontiguousarray(dataset.binned)
            span = getattr(dataset, "host_row_span", None)
            if span is not None and tuple(span) != (lo, hi):
                raise LightGBMError(
                    f"host-sharded dataset covers padded rows {span} "
                    f"but this process's mesh block is ({lo}, {hi}) — "
                    f"the loader and the grower disagree on the pod "
                    f"layout (num_hosts/devices or bucket drift)")
            if local.shape[0] != hi - lo:
                raise LightGBMError(
                    f"host-sharded binned block has {local.shape[0]} "
                    f"rows, mesh block needs {hi - lo}")
        else:
            full = np.asarray(dataset.binned)
            total = spec.n_shards * n_pad
            if full.shape[0] < total:
                full = np.pad(full, ((0, total - full.shape[0]),
                                     (0, 0)))
            local = np.ascontiguousarray(full[lo:hi])
        self.binned = jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P(spec.axis, None)), local)
        self.binned_t = transpose_col_sharded(
            self.mesh, spec.axis)(self.binned)

    # programs hold every static/trace-level attribute (hist_cols,
    # wave_width, stage_plan, nb, n_pad, quant_bits, feature_mask_for,
    # _wave_hist, ...); forward reads so call sites and tests are
    # agnostic to where an attribute lives
    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "programs"), name)

    def __setattr__(self, name, value):
        # a write to a programs-owned attribute would create a shadowing
        # instance attribute: reads would show the new value while the
        # programs (which the jitted code consults) keep the old one —
        # the silent no-op failure mode of the pre-refactor pattern
        # `grower.use_pallas = True`.  Fail loudly instead; mutate
        # `grower.programs.<attr>` explicitly (with grower_cache=false
        # for a private, non-process-shared instance).
        progs = self.__dict__.get("programs")
        if (progs is not None and name not in self.__dict__
                and hasattr(progs, name)):
            raise AttributeError(
                f"'{name}' lives on the shared GrowerPrograms object; "
                f"set grower.programs.{name} explicitly (and pass "
                f"grower_cache=false for a private instance)")
        object.__setattr__(self, name, value)

    # ------------------------------------------------------------------
    def grow_one_iter(self, score, grad, hess, feature_mask, lr=None,
                      row_mask=None, tree_idx=0):
        """Dispatch one boosting iteration; returns device handles
        (new_score, rec_i, rec_f, rec_c, num_leaves, root_value,
        num_waves, quant_scales) without blocking.  ``row_mask`` is an
        optional (N,) f32 0/1 in-bag indicator (bagging / GOSS);
        ``tree_idx`` is the global tree index keying the per-tree
        quantization rounding noise."""
        if lr is None:
            lr = self.lr
        obs.inc("grow.dispatches")
        # routing attribution: which kernel serves this dispatch's
        # full-width histogram stage (BENCH digests read these)
        obs.inc(f"grow.hist.{self.programs.hist_kernel_tag}")
        # fused-find twin counters (same tag family as grow.hist.*):
        # under find_best_fusion=fused each wave's hist+find is ONE
        # dispatch equivalent, two_pass prices two — rollups multiply
        # wave counts by the factor gauge instead of assuming 2/wave
        # (the PR-16 counts-as-waves bug class)
        if self.programs.fused_find:
            obs.inc(f"grow.fused_find.{self.programs.hist_kernel_tag}")
        obs.set_gauge("grow.wave_dispatch_factor",
                      1 if self.programs.fused_find else 2)
        if self.programs.shard is not None:
            obs.inc("grow.sharded_dispatches")
        ti = jnp.asarray(tree_idx, jnp.int32)
        if self._row_pad:
            # bucket pad: the program's row dim is the pow2 bucket; the
            # traced num_valid cuts the padding back out of every stat
            score = jnp.pad(score, (0, self._row_pad))
            grad = jnp.pad(grad, (0, self._row_pad))
            hess = jnp.pad(hess, (0, self._row_pad))
            if row_mask is not None:
                row_mask = jnp.pad(row_mask, (0, self._row_pad))
        if row_mask is None:
            out = self.programs._grow(
                self.binned, self.binned_t, score, grad, hess,
                feature_mask, jnp.asarray(lr, jnp.float32),
                jnp.zeros((0,), jnp.float32), ti, self._num_valid,
                self.meta, self.hyper, self.tables)
        else:
            out = self.programs._grow_masked(
                self.binned, self.binned_t, score, grad, hess,
                feature_mask, jnp.asarray(lr, jnp.float32), row_mask, ti,
                self._num_valid, self.meta, self.hyper, self.tables)
        if self._multihost:
            # the fused program's score comes back row-sharded across
            # processes; reshard to fully-replicated so the host-side
            # slice/flush below (and the caller's np.asarray) work
            from .shard import replicate_to_all
            out = (replicate_to_all(self.mesh)(out[0]),) + tuple(
                out[1:])
        if self._row_pad:
            out = (out[0][:self.num_data],) + tuple(out[1:])
        return out

    # ------------------------------------------------------------------
    def fused_train(self, length: int):
        """Multi-iteration fused program with this grower's metadata
        bound; same call contract the boosting layer always used::

            run(binned, binned_t, score, lr, gargs, it0, grad_fn=fn)
        """
        raw = self.programs.fused_train(length)
        meta, hyper, tables = self.meta, self.hyper, self.tables
        num_valid, row_pad, real_n = (self._num_valid, self._row_pad,
                                      self.num_data)

        def _pad_rows(a):
            # gargs leaves with a leading per-row axis (labels, weights)
            # stretch to the bucket; padded rows produce garbage
            # gradients that _grow_impl's valid mask zeroes.  Only sound
            # for row-local gradient formulas — the boosting layer gates
            # bucketing on objective.device_grad_rowwise.
            if (getattr(a, "ndim", 0) >= 1
                    and a.shape[0] == real_n):
                return jnp.pad(a, [(0, row_pad)] + [(0, 0)] * (a.ndim - 1))
            return a

        kernel_tag = self.programs.hist_kernel_tag
        sharded = self.programs.shard is not None
        fused_find = self.programs.fused_find
        if self._multihost:
            from .shard import replicate_to_all
            replicate = replicate_to_all(self.mesh)
        else:
            replicate = None

        def run(binned, binned_t, score, lr, gargs, it0, grad_fn):
            obs.inc(f"grow.hist.{kernel_tag}")
            # fused-find twin + dispatch factor: mirror of the
            # per-iteration site so fused-chunk rollups price waves
            # with the same 1-vs-2 dispatch accounting
            if fused_find:
                obs.inc(f"grow.fused_find.{kernel_tag}")
            obs.set_gauge("grow.wave_dispatch_factor",
                          1 if fused_find else 2)
            if sharded:
                obs.inc("grow.sharded_dispatches")
            if row_pad:
                score = jnp.pad(score, (0, row_pad))
                gargs = jax.tree_util.tree_map(_pad_rows, gargs)
            final_score, recs = raw(binned, binned_t, score, lr, gargs,
                                    it0, num_valid, meta, hyper, tables,
                                    grad_fn=grad_fn)
            if replicate is not None:
                # pod slice: score returns row-sharded across hosts;
                # every host needs the full vector for the next
                # dispatch's pad, checkpoints and metrics
                final_score = replicate(final_score)
            if row_pad:
                final_score = final_score[:real_n]
            return final_score, recs
        return run

    # ------------------------------------------------------------------
    def profile_stage_plan(self, reps: int = 3, install: bool = True,
                           require_beat_legacy: bool = False):
        """Time the wave histogram at every candidate stage width on the
        REAL binned matrix, record the per-stage timings through the obs
        layer (``grow.stage.w<W>`` spans + gauges), fit the
        fixed-vs-per-column cost model and derive the cheapest stage
        plan (ops/stage_plan.py).  ``install=True`` caches the plan
        under this grower's (shape, config) signature — in process AND
        persisted beside the compile cache, so later growers (and fresh
        processes) pick it up without re-measuring — and swaps this
        grower onto programs built for the new plan.

        ``require_beat_legacy`` (the ``wave_plan=auto``
        profile-on-first-use path) keeps the byte-stable legacy ladder
        unless the derived plan's modeled cost beats it by the 2%
        ``stage_plan.MIN_IMPROVEMENT`` bar — the legacy-confirming
        result is still cached/persisted, so the measurement happens
        once per signature either way.

        Returns ``{"stage_ms", "fixed_ms", "col_ms", "plan",
        "plan_digest", "installed"}``."""
        import time as _time

        reps = max(1, int(reps))
        progs = self.programs
        if progs.shard is not None:
            # the stage probes dispatch _wave_hist outside shard_map,
            # where the mesh axis is unbound; sharded growers keep the
            # byte-stable default ladder (a profiled plan would also
            # have to match across mesh sizes to preserve the
            # byte-identity contract, docs/Sharding.md)
            return {"stage_ms": {}, "stage_cost": {}, "fixed_ms": None,
                    "col_ms": None,
                    "plan": list(progs.stage_plan),
                    "plan_digest":
                        stage_plan_mod.plan_digest(progs.stage_plan),
                    "installed": False}
        if install and progs.plan_source in ("profiled", "persisted"):
            # already measured for this signature in this process, or
            # adopted from the on-disk store: zero re-profiles
            return {"stage_ms": {}, "stage_cost": {}, "fixed_ms": None,
                    "col_ms": None,
                    "plan": list(progs.stage_plan),
                    "plan_digest":
                        stage_plan_mod.plan_digest(progs.stage_plan),
                    "installed": False}
        obs.inc("grow.plan_profiles")
        k = progs.hist_cols
        n = progs.n_pad
        rng = np.random.default_rng(0)
        grad = jnp.asarray(rng.standard_normal(n, dtype=np.float32))
        hess = jnp.abs(grad) + 0.1
        widths = sorted({w for w, _ in progs.stage_plan}
                        | set(stage_plan_mod._ladder(progs.wave_width))
                        | {progs.wave_width})
        stage_ms = {}
        # the REAL operand pipeline (incl. quantization when on), so the
        # probes time exactly what training dispatches
        ghk, scales = progs._stat_columns(grad, hess,
                                          jnp.ones((n,), jnp.float32), 0)
        wave_scales = scales if progs.quant_bits else None

        def probe_for(w):
            leaf = jnp.asarray(rng.integers(0, w, n).astype(np.int32))
            pend = jnp.arange(w, dtype=jnp.int32)
            fn = obs.track_jit(
                f"stage_probe_w{w}",
                jax.jit(lambda b, l, g2, p:
                        progs._wave_hist(b, l, g2, p, wave_scales)))
            return fn, leaf, ghk, pend

        stage_cost = {}
        hist_out = {}
        for w in widths:
            fn, leaf, ghk, pend = probe_for(w)
            jax.block_until_ready(fn(self.binned, leaf, ghk, pend))
            with obs.span("grow.stage_probe", cat="grow", width=w,
                          hist_cols=k):
                t0 = _time.perf_counter()
                for _ in range(reps):
                    r = fn(self.binned, leaf, ghk, pend)
                jax.block_until_ready(r)
                ms = (_time.perf_counter() - t0) / reps * 1e3
            hist_out[w] = r
            stage_ms[w] = round(ms, 3)
            if obs.profile.enabled():
                # static XLA estimate for the already-compiled probe (a
                # compile-cache hit): measured ms + estimated FLOPs =
                # achieved compute per stage width
                cost = obs.profile.cost_of(fn, self.binned, leaf, ghk,
                                           pend)
                if cost is not None:
                    stage_cost[w] = cost
                    if cost.get("flops"):
                        obs.set_gauge(f"grow.stage.w{w}_gflops",
                                      round(cost["flops"] / 1e9, 3))
            obs.observe(f"grow.stage.w{w}", ms / 1e3)
            obs.set_gauge(f"grow.stage.w{w}_ms", round(ms, 3))
            if w == progs.wave_width:
                # per-kernel attribution: the full-width probe times the
                # exact kernel (pallas_int8/einsum_bf16/...) production
                # dispatches at this stage
                tag = progs.hist_kernel_tag
                obs.observe(f"grow.hist.{tag}", ms / 1e3)
                obs.set_gauge(f"grow.hist.{tag}_ms", round(ms, 3))
        fixed, col = stage_plan_mod.fit_wave_costs(
            widths, [stage_ms[w] for w in widths], k,
            num_data=progs.num_data)

        # fused-vs-two-pass verdict (find_best_fusion=auto): time the
        # per-width gain scan both ways — as its own second program
        # over a materialized (2W, S, 3) stack (the two-pass wave's
        # extra dispatch) and riding the histogram program end-to-end
        # (the fused wave) — then price a full tree under each layout
        # and persist the winner beside the stage plan.  An explicit
        # find_best_fusion skips the measurement: the layout is forced.
        find_ms, fused_ms = {}, {}
        fusion_cfg = str(getattr(self.config, "find_best_fusion",
                                 "auto") or "auto").lower()
        fusion = fusion_cfg if fusion_cfg in ("fused", "two_pass") \
            else "fused"
        fusion_detail = None
        if fusion_cfg == "auto":
            mask_all = jnp.ones((progs.num_features,), bool)
            stack_scales = scales if progs.int_scan else None

            def scan_stack(hists, m):
                cons = jnp.asarray([-jnp.inf, jnp.inf], jnp.float32)
                totals = hists[:, :progs.nb, :].sum(1)
                packed, _, _ = find_best_split_stack(
                    hists, totals, cons, m, self.meta, self.hyper,
                    progs.has_cat, scales=stack_scales)
                return packed

            def timed(fn, *args):
                jax.block_until_ready(fn(*args))
                t0 = _time.perf_counter()
                for _ in range(reps):
                    r = fn(*args)
                jax.block_until_ready(r)
                return (_time.perf_counter() - t0) / reps * 1e3

            for w in widths:
                leaf = jnp.asarray(
                    rng.integers(0, w, n).astype(np.int32))
                pend = jnp.arange(w, dtype=jnp.int32)
                # the negated fresh product stands in for the
                # parent-minus-sibling residual: shape/dtype-faithful,
                # and the scan cost is data-independent
                h2 = jnp.concatenate([hist_out[w], -hist_out[w]])
                two_fn = obs.track_jit(f"fusion_probe_find_w{w}",
                                       jax.jit(scan_stack))
                find_ms[w] = round(timed(two_fn, h2, mask_all), 3)

                def fused_body(b, l, g2, p, m):
                    fr = progs._wave_hist(b, l, g2, p, wave_scales)
                    return jnp.concatenate([scan_stack(fr, m),
                                            scan_stack(-fr, m)])

                fused_fn = obs.track_jit(f"fusion_probe_fused_w{w}",
                                         jax.jit(fused_body))
                fused_ms[w] = round(
                    timed(fused_fn, self.binned, leaf, ghk, pend,
                          mask_all), 3)
                obs.set_gauge(f"grow.find.w{w}_ms", find_ms[w])
                obs.set_gauge(f"grow.fused.w{w}_ms", fused_ms[w])

            plan_tp = stage_plan_mod.derive_stage_plan(
                progs.num_leaves, progs.wave_width, k, fixed, col,
                measured_ms=stage_ms, find_ms=find_ms,
                fusion="two_pass")
            plan_f = stage_plan_mod.derive_stage_plan(
                progs.num_leaves, progs.wave_width, k, fixed, col,
                measured_ms=fused_ms)
            cost_tp, _ = stage_plan_mod.plan_cost_fn(
                plan_tp, progs.num_leaves,
                stage_plan_mod.wave_cost_fn(
                    k, fixed, col, stage_ms, find_ms=find_ms,
                    fusion="two_pass"))
            cost_f, _ = stage_plan_mod.plan_cost_fn(
                plan_f, progs.num_leaves,
                stage_plan_mod.wave_cost_fn(k, fixed, col, fused_ms))
            if cost_tp < cost_f * (1.0 - stage_plan_mod.MIN_IMPROVEMENT):
                fusion, plan = "two_pass", plan_tp
            else:
                fusion, plan = "fused", plan_f
            fusion_detail = {"fused_ms_per_tree": round(cost_f, 3),
                             "two_pass_ms_per_tree": round(cost_tp, 3)}
            obs.inc(f"grow.fusion_profiled.{fusion}")
        else:
            plan = stage_plan_mod.derive_stage_plan(
                progs.num_leaves, progs.wave_width, k, fixed, col,
                measured_ms=stage_ms, find_ms=find_ms or None,
                fusion=fusion)
        if require_beat_legacy:
            legacy = stage_plan_mod.legacy_stage_plan(
                progs.num_leaves, progs.wave_width, k)
            meas = fused_ms if (fusion == "fused" and fused_ms) \
                else stage_ms
            if not stage_plan_mod.plan_beats(
                    plan, legacy, progs.num_leaves, k, fixed, col,
                    measured_ms=meas,
                    find_ms=find_ms if fusion == "two_pass" else None,
                    fusion=fusion):
                plan = legacy
        obs.set_gauge("grow.stage.fixed_ms", round(fixed, 3))
        obs.set_gauge("grow.stage.col_ms", round(col, 5))
        installed = False
        if install:
            stage_plan_mod.cache_plan(self._base_signature, plan)
            if fusion_cfg == "auto":
                # the verdict persists beside the plan, so
                # find_best_fusion=auto in THIS process (the rebuild
                # below) and every fresh process resolves to it
                stage_plan_mod.cache_fusion(self._base_signature,
                                            fusion,
                                            detail=fusion_detail)
            if plan != progs.stage_plan or fusion != progs.find_fusion:
                self.programs = get_grower_programs(
                    progs.num_data, progs.num_groups, progs.nb,
                    progs.num_features, progs.has_cat, self.config,
                    plan=plan, plan_source="profiled")
                installed = True
            else:
                # derived plan == current plan: nothing to rebuild, but
                # the plan is now measurement-confirmed (keeps the
                # early-exit above from re-probing this signature)
                progs.plan_source = "profiled"
        return {"stage_ms": stage_ms, "stage_cost": stage_cost,
                "fixed_ms": round(fixed, 3),
                "col_ms": round(col, 5), "plan": plan,
                "plan_digest": stage_plan_mod.plan_digest(plan),
                "find_ms": find_ms, "fused_ms": fused_ms,
                "fusion": fusion, "fusion_detail": fusion_detail,
                "installed": installed}

    # ------------------------------------------------------------------
    def profile_psum(self, reps: int = 10) -> Optional[dict]:
        """Time ONE wave-histogram-shaped psum on the mesh — the growth
        loop's sole sync point — via a separately-jitted shard_map whose
        body is just the collective, so the measured ms is communication
        (plus dispatch floor), not histogram compute.  Records the
        ``shard.psum`` timing and ``shard.psum_ms`` gauge that
        ``obs.summary()``'s shard digest and ``bench.py --suite shard``
        read; returns ``{"psum_ms": ...}``, or None unsharded."""
        import time as _time

        progs = self.programs
        sp = progs.shard
        if sp is None:
            return None
        from jax.sharding import PartitionSpec as P
        w, s = progs.wave_width, progs.num_slots
        dtype = jnp.int32 if progs.int_scan else jnp.float32
        fn = obs.track_jit(
            "shard.psum_probe",
            jax.jit(shard_map_compat(
                lambda h: jax.lax.psum(h, sp.axis), self.mesh,
                (P(sp.axis),), P())))
        buf = jnp.zeros((sp.n_shards, w, s, 3), dtype)
        jax.block_until_ready(fn(buf))
        t0 = _time.perf_counter()
        for _ in range(max(1, int(reps))):
            r = fn(buf)
        jax.block_until_ready(r)
        ms = (_time.perf_counter() - t0) / max(1, int(reps)) * 1e3
        obs.observe("shard.psum", ms / 1e3)
        obs.set_gauge("shard.psum_ms", round(ms, 3))
        out = {"psum_ms": round(ms, 3)}
        if obs.profile.enabled():
            cost = obs.profile.cost_of(fn, buf)
            if cost is not None:
                out["cost"] = cost
                if cost.get("bytes_accessed"):
                    obs.set_gauge("shard.psum_gbytes",
                                  round(cost["bytes_accessed"] / 1e9, 4))
        return out

    # ------------------------------------------------------------------
    def profile_phases(self, grad, hess, reps: int = 20) -> dict:
        """Honest per-phase attribution for one wave (bench --profile).

        The production grower runs the whole tree inside one
        ``lax.while_loop`` — individual phases are invisible from the
        host.  This method times separately-jitted programs equivalent
        to the wave's phases on the real binned matrices and a
        representative leaf state (rows spread over W leaves, all
        pending), syncing after each, and returns {phase: ms}.
        """
        import time as _time

        if self.programs.shard is not None:
            from ..utils.log import log_warning
            log_warning("profile_phases is unavailable under "
                        "data_sharding (phase probes run outside the "
                        "mesh); use profile_psum for collective time")
            return {}
        w, n = self.wave_width, self.n_pad
        rng = np.random.default_rng(0)
        leaf_id = jnp.asarray(
            rng.integers(0, w, n).astype(np.int32))
        pending = jnp.arange(w, dtype=jnp.int32)
        grad = jnp.pad(grad, (0, n - self.num_data))
        hess = jnp.pad(hess, (0, n - self.num_data))

        quant = bool(self.quant_bits)

        @jax.jit
        def p_hist(binned, leaf, g, h, pend):
            # the real operand pipeline (shared _stat_columns), so the
            # profiled wave_hist matches production bit-for-bit
            ghk, scales = self.programs._stat_columns(
                g, h, jnp.ones((n,), jnp.float32), 0)
            return self.programs._wave_hist(binned, leaf, ghk, pend,
                                            scales if quant else None)

        p_hist = obs.track_jit("grow.probe.hist", p_hist)
        int_scan = bool(self.int_scan)

        @jax.jit
        def p_find(hists, feature_mask):
            cons = jnp.asarray([-jnp.inf, jnp.inf], jnp.float32)
            totals = hists[:, :self.nb, :].sum(1)
            if int_scan:
                # the int32 scan variant (what production runs when
                # quantized); unit scales keep the probe self-contained
                find_q = functools.partial(find_best_split_quant,
                                           meta=self.meta, hp=self.hyper,
                                           has_cat=False)
                ones2 = jnp.ones((2,), jnp.float32)
                packed, _, _ = jax.vmap(
                    lambda hh, t: find_q(hh, t, ones2, cons,
                                         feature_mask))(hists, totals)
                return packed
            find_one = functools.partial(find_best_split_impl,
                                         meta=self.meta, hp=self.hyper,
                                         has_cat=False)
            packed, _ = jax.vmap(
                lambda hh, t: find_one(hh, t, cons, feature_mask))(hists,
                                                                   totals)
            return packed

        p_find = obs.track_jit("grow.probe.find", p_find)

        @jax.jit
        def p_apply(binned_t, leaf, grp, thr, rdel):
            cols = jnp.take(binned_t, grp, axis=0).astype(jnp.int32)
            mask = (leaf[None, :] == jnp.arange(w)[:, None]) \
                & (cols > thr[:, None])
            return leaf + jnp.sum(mask * rdel[:, None], axis=0,
                                  dtype=jnp.int32)

        p_apply = obs.track_jit("grow.probe.apply", p_apply)

        @jax.jit
        def p_score(score, leaf, vals):
            L = self.num_leaves
            oh = jax.nn.one_hot(leaf, L, dtype=jnp.bfloat16)
            vhi = vals.astype(jnp.bfloat16)
            vlo = (vals - vhi.astype(jnp.float32)).astype(jnp.bfloat16)
            upd = jnp.einsum("nl,lk->nk", oh, jnp.stack([vhi, vlo], 1),
                             preferred_element_type=jnp.float32)
            return score + upd[:, 0] + upd[:, 1]

        p_score = obs.track_jit("grow.probe.score", p_score)

        mask = jnp.ones((self.num_features,), bool)
        grp = jnp.asarray(rng.integers(0, self.num_groups, w, np.int32))
        thr = jnp.asarray(rng.integers(0, self.nb, w, np.int32))
        rdel = jnp.asarray(rng.integers(1, w + 1, w, np.int32))
        vals = jnp.asarray(rng.standard_normal(self.num_leaves)
                           .astype(np.float32))
        score = jnp.zeros((n,), jnp.float32)

        # dispatch-latency floor: an empty jitted program measured the
        # same way; subtracted from every phase so tunnel round-trip
        # latency doesn't masquerade as device time
        @jax.jit
        def p_null(x):
            return x + 1.0

        p_null = obs.track_jit("grow.probe.null", p_null)

        out = {}
        cases = {
            "null_dispatch": lambda: p_null(score[:8]),
            "wave_hist": lambda: p_hist(self.binned, leaf_id, grad, hess,
                                        pending),
            "find_best": None,   # filled after hist exists
            "split_apply": lambda: p_apply(self.binned_t, leaf_id, grp,
                                           thr, rdel),
            "score_update": lambda: p_score(score, leaf_id, vals),
        }
        hists = jax.block_until_ready(cases["wave_hist"]())
        cases["find_best"] = lambda: p_find(hists, mask)
        for name, fn in cases.items():
            jax.block_until_ready(fn())          # compile + warm
            t0 = _time.perf_counter()
            for _ in range(reps):
                r = fn()
            jax.block_until_ready(r)
            out[name] = round((_time.perf_counter() - t0) / reps * 1e3, 2)
        floor = out.pop("null_dispatch")
        out = {k: round(max(v - floor, 0.0), 2) for k, v in out.items()}
        out["dispatch_floor"] = floor
        for name, ms in out.items():
            obs.set_gauge(f"profile.{name}_ms", ms)
        if obs.profile.enabled():
            # static XLA estimates for the (already compiled) phase
            # probes; nested under "costs" so {phase: ms} consumers are
            # unaffected
            probe_args = {
                "wave_hist": (p_hist, (self.binned, leaf_id, grad, hess,
                                       pending)),
                "find_best": (p_find, (hists, mask)),
                "split_apply": (p_apply, (self.binned_t, leaf_id, grp,
                                          thr, rdel)),
                "score_update": (p_score, (score, leaf_id, vals)),
            }
            costs = {}
            for name, (fn, a) in probe_args.items():
                cost = obs.profile.cost_of(fn, *a)
                if cost is not None:
                    costs[name] = cost
                    if cost.get("flops"):
                        obs.set_gauge(f"profile.{name}_gflops",
                                      round(cost["flops"] / 1e9, 3))
            if costs:
                out["costs"] = costs
        return out


def device_growth_eligible(config, dataset, objective, num_model,
                           n_shards: int = 1) -> bool:
    """Whether the dense device grower covers this training configuration.
    Anything it can't do falls back to the host-driven learner.
    Multiclass runs one grow dispatch per class; bagging/GOSS route a
    0/1 row mask into the wave histogram's count column."""
    if dataset.num_groups == 0 or dataset.num_features == 0:
        return False
    if np.asarray(dataset.monotone_constraints).any():
        return False
    if objective is None or objective.is_renew_tree_output:
        return False
    if getattr(config, "forcedsplits_filename", ""):
        return False
    # single f32 count columns are exact below COUNT_SPLIT_ROWS (2^24);
    # the striped two-column layout extends that to twice the threshold
    # (the int8 path's striped int32 g/h accumulators share the bound).
    # The bound is per-ACCUMULATOR, i.e. per shard: a single-controller
    # mesh grows the eligible global row count by its device count
    # (cross-shard counts psum in int32, exact to 2^31).
    if dataset.num_data >= max(int(n_shards), 1) * 2 * COUNT_SPLIT_ROWS:
        return False
    return True
