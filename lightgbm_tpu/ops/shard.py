"""Single-controller data-parallel sharding for the device grower.

The reference's Network layer (PAPER.md §1) powers its data-parallel
tree learner with Allreduce/ReduceScatter collectives where the
histogram reduction is the ONLY synchronization point per split.  The
multiprocess worker mesh (``lightgbm_tpu/parallel/``) reproduces that
faithfully but dispatches per-worker Python every step, which keeps it
out of ``DeviceGrower.fused_train``'s K-trees-per-dispatch ``lax.scan``
— and therefore out of every fused-path win (program cache, int8 MXU
histograms, persisted stage plans).

This module is the jax-native equivalent: ONE Python process shards the
binned matrix (and every per-row buffer) row-wise across a device mesh
with ``shard_map``, the existing fused scan runs unchanged on every
chip, and a ``lax.psum`` of the wave histograms over the mesh axis is
the sole cross-device communication of the growth loop (plus one (2,)
``pmax`` per tree for the global quantization scale when
``grad_quant_bits=8``).  Partition, traversal and leaf bookkeeping stay
shard-local; find-best runs replicated on the globally-reduced
histograms, so every device grows the identical tree — no split
broadcast, exactly like the reference's data-parallel learner with
``GLOBAL_data_count``.

Row layout (the :class:`ShardSpec` contract)
--------------------------------------------

Global padded row space = ``n_shards * local_rows``; shard ``d`` owns
the contiguous block ``[d * local_rows, (d + 1) * local_rows)`` and a
real dataset row ``r`` lives at global padded index ``r`` — so shard
``r // local_rows`` holds it.  Trailing shards may be mostly (or all)
bucket padding; that costs nothing, because the grower's dense
formulation processes every padded row regardless.  The traced global
``num_valid`` scalar cuts validity per shard
(``clip(num_valid - d * local_rows, 0, local_rows)``).

Determinism / byte-identity contract (docs/Sharding.md)
-------------------------------------------------------

* ``grad_quant_bits=8`` under the int32 find-best scan: integer psum is
  associative-exact, the quantization scale is a global ``pmax`` (max is
  exact), the stochastic-rounding noise and the in-scan bagging mask
  are drawn at CANONICAL GLOBAL shapes (``draw_npad`` / ``bag_npad`` —
  jax's threefry draw is NOT prefix-stable across shapes, so the shape
  itself is part of the stream) and sliced per shard, and the leaf
  refit runs on exact int32 digit sums — so the sharded trainer emits
  models BYTE-IDENTICAL to the single-device fused path.
* f32 / bf16 histograms: the psum's reduction order is fixed by the
  compiled program, so results are bit-reproducible run-to-run but not
  bitwise equal to the single-device accumulation order.  Counts psum
  as int32 either way, so row counts stay exact past 2^24 global rows.
* fused find-best-in-wave (``find_best_fusion``, ops/grow.py) composes
  with all of the above: the psum happens INSIDE the fused program,
  directly between the shard-local wave histograms and the replicated
  gain scan that consumes them, so fusing removes the two-pass layout's
  second dispatch without adding any collective — the reduced stack is
  scanned where it lands instead of round-tripping through HBM first.
  The 1-vs-N byte-identity contract is pinned per layout by
  tests/_shard_worker.py's ``fused_find`` scenario.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import numpy as np

from ..utils.log import LightGBMError, log_info

#: the one mesh axis the sharded grower reduces over
SHARD_AXIS = "shards"


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map`` with ``check_vma``; 0.4.x keeps
    it under ``jax.experimental.shard_map`` with ``check_rep``.  Either
    way replication checking is off: the grower's growth loop carries a
    ``lax.while_loop`` whose replication rule old jax cannot derive, and
    the replicated-output contract is enforced by the byte-identity
    tests instead (tests/test_shard.py, scripts/check_shard.py).
    """
    smap = getattr(jax, "shard_map", None)
    if smap is not None:
        try:
            return smap(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=False)
        except TypeError:
            # jax versions where jax.shard_map exists but still takes
            # check_rep
            return smap(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as smap_exp
    return smap_exp(fn, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, check_rep=False)


class ShardSpec(NamedTuple):
    """Static facts of one sharded-training layout (trace-level; joins
    the grower program-cache signature via ``shard_signature``)."""

    n_shards: int     #: mesh size D (always > 1; D == 1 runs unsharded)
    axis: str         #: mesh axis name (SHARD_AXIS)
    global_rows: int  #: REAL global row count (num_valid upper bound)
    #: canonical global shape of the quantization-noise draw — the
    #: single-device grower's chunk pad for ``global_rows``, so the
    #: per-row rounding noise matches the unsharded path bit-for-bit
    draw_npad: int
    #: canonical global shape of the bagging uniform draw
    #: (= ``histogram.bucket_size(global_rows)``, the same pad the
    #: serial learner's bagging buffer uses)
    bag_npad: int


def local_valid_rows(spec: ShardSpec, local_rows: int, num_valid):
    """Traced per-shard valid-row count: global rows are laid out in
    contiguous ``local_rows`` blocks, so shard ``d`` is valid up to
    ``num_valid - d * local_rows`` (clipped)."""
    import jax.numpy as jnp
    d = jax.lax.axis_index(spec.axis)
    return jnp.clip(num_valid - d * local_rows, 0,
                    local_rows).astype(jnp.int32)


def slice_global_draw(spec: ShardSpec, full, local_rows: int):
    """Take this shard's block of a canonically-shaped global draw.

    ``full`` is a 1-D array drawn at a canonical global shape
    (``draw_npad`` / ``bag_npad``); rows beyond it (only ever bucket
    padding, zeroed by the valid mask) read as 0.
    """
    import jax.numpy as jnp
    total = spec.n_shards * local_rows
    if full.shape[0] >= total:
        full = full[:total]
    else:
        full = jnp.pad(full, (0, total - full.shape[0]))
    off = jax.lax.axis_index(spec.axis) * local_rows
    return jax.lax.dynamic_slice(full, (off,), (local_rows,))


def make_shard_mesh(num_devices: int = 0):
    """One-axis ``SHARD_AXIS`` mesh over local devices (0 = all).

    Raises :class:`LightGBMError` when fewer than 2 devices are
    available — single-controller sharding with one device is exactly
    the unsharded fused path, so callers fall back instead.
    """
    from jax.sharding import Mesh
    devices = jax.devices()
    d = int(num_devices) or len(devices)
    if d < 2:
        raise LightGBMError(
            f"data_sharding=single_controller needs >= 2 devices, have "
            f"{len(devices)} (request {d}); on CPU force a virtual mesh "
            f"with XLA_FLAGS=--xla_force_host_platform_device_count=N")
    if d > len(devices):
        raise LightGBMError(
            f"shard_devices={d} exceeds available devices "
            f"({len(devices)})")
    return Mesh(np.asarray(devices[:d]), (SHARD_AXIS,))


def sharding_mode(config) -> str:
    """Resolved ``data_sharding`` mode string ("off" when unset)."""
    return str(getattr(config, "data_sharding", "off") or "off").lower()


def resolve_shard_mesh(config) -> Optional[object]:
    """Mesh for ``data_sharding=single_controller``, or None (off /
    not enough devices — logged, training proceeds unsharded)."""
    if sharding_mode(config) != "single_controller":
        return None
    try:
        mesh = make_shard_mesh(int(getattr(config, "shard_devices", 0)
                                   or 0))
    except LightGBMError as e:
        log_info(f"data_sharding=single_controller unavailable "
                 f"({e}); training unsharded")
        return None
    log_info(f"data_sharding=single_controller: row-sharding over "
             f"{mesh.devices.size} device(s), psum wave histograms")
    return mesh
