"""Single-controller data-parallel sharding for the device grower.

The reference's Network layer (PAPER.md §1) powers its data-parallel
tree learner with Allreduce/ReduceScatter collectives where the
histogram reduction is the ONLY synchronization point per split.  The
multiprocess worker mesh (``lightgbm_tpu/parallel/``) reproduces that
faithfully but dispatches per-worker Python every step, which keeps it
out of ``DeviceGrower.fused_train``'s K-trees-per-dispatch ``lax.scan``
— and therefore out of every fused-path win (program cache, int8 MXU
histograms, persisted stage plans).

This module is the jax-native equivalent: ONE Python process shards the
binned matrix (and every per-row buffer) row-wise across a device mesh
with ``shard_map``, the existing fused scan runs unchanged on every
chip, and a ``lax.psum`` of the wave histograms over the mesh axis is
the sole cross-device communication of the growth loop (plus one (2,)
``pmax`` per tree for the global quantization scale when
``grad_quant_bits=8``).  Partition, traversal and leaf bookkeeping stay
shard-local; find-best runs replicated on the globally-reduced
histograms, so every device grows the identical tree — no split
broadcast, exactly like the reference's data-parallel learner with
``GLOBAL_data_count``.

Row layout (the :class:`ShardSpec` contract)
--------------------------------------------

Global padded row space = ``n_shards * local_rows``; shard ``d`` owns
the contiguous block ``[d * local_rows, (d + 1) * local_rows)`` and a
real dataset row ``r`` lives at global padded index ``r`` — so shard
``r // local_rows`` holds it.  Trailing shards may be mostly (or all)
bucket padding; that costs nothing, because the grower's dense
formulation processes every padded row regardless.  The traced global
``num_valid`` scalar cuts validity per shard
(``clip(num_valid - d * local_rows, 0, local_rows)``).

Determinism / byte-identity contract (docs/Sharding.md)
-------------------------------------------------------

* ``grad_quant_bits=8`` under the int32 find-best scan: integer psum is
  associative-exact, the quantization scale is a global ``pmax`` (max is
  exact), the stochastic-rounding noise and the in-scan bagging mask
  are drawn at CANONICAL GLOBAL shapes (``draw_npad`` / ``bag_npad`` —
  jax's threefry draw is NOT prefix-stable across shapes, so the shape
  itself is part of the stream) and sliced per shard, and the leaf
  refit runs on exact int32 digit sums — so the sharded trainer emits
  models BYTE-IDENTICAL to the single-device fused path.
* f32 / bf16 histograms: the psum's reduction order is fixed by the
  compiled program, so results are bit-reproducible run-to-run but not
  bitwise equal to the single-device accumulation order.  Counts psum
  as int32 either way, so row counts stay exact past 2^24 global rows.
* fused find-best-in-wave (``find_best_fusion``, ops/grow.py) composes
  with all of the above: the psum happens INSIDE the fused program,
  directly between the shard-local wave histograms and the replicated
  gain scan that consumes them, so fusing removes the two-pass layout's
  second dispatch without adding any collective — the reduced stack is
  scanned where it lands instead of round-tripping through HBM first.
  The 1-vs-N byte-identity contract is pinned per layout by
  tests/_shard_worker.py's ``fused_find`` scenario.
"""

from __future__ import annotations

import os
import threading
from typing import NamedTuple, Optional, Tuple

import jax
import numpy as np

from ..utils.log import LightGBMError, log_info

#: the one mesh axis the sharded grower reduces over
SHARD_AXIS = "shards"

#: env fallbacks for the multi-controller bring-up params (one process
#: per host cannot share a config file edit per rank, so rank/host
#: count usually travel through the launcher's environment)
ENV_COORDINATOR = "LGBM_TPU_COORDINATOR"
ENV_NUM_HOSTS = "LGBM_TPU_NUM_HOSTS"
ENV_HOST_RANK = "LGBM_TPU_HOST_RANK"


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map`` with ``check_vma``; 0.4.x keeps
    it under ``jax.experimental.shard_map`` with ``check_rep``.  Either
    way replication checking is off: the grower's growth loop carries a
    ``lax.while_loop`` whose replication rule old jax cannot derive, and
    the replicated-output contract is enforced by the byte-identity
    tests instead (tests/test_shard.py, scripts/check_shard.py).
    """
    smap = getattr(jax, "shard_map", None)
    if smap is not None:
        try:
            return smap(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=False)
        except TypeError:
            # jax versions where jax.shard_map exists but still takes
            # check_rep
            return smap(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as smap_exp
    return smap_exp(fn, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, check_rep=False)


class ShardSpec(NamedTuple):
    """Static facts of one sharded-training layout (trace-level; joins
    the grower program-cache signature via ``shard_signature``)."""

    n_shards: int     #: mesh size D (always > 1; D == 1 runs unsharded)
    axis: str         #: mesh axis name (SHARD_AXIS)
    global_rows: int  #: REAL global row count (num_valid upper bound)
    #: canonical global shape of the quantization-noise draw — the
    #: single-device grower's chunk pad for ``global_rows``, so the
    #: per-row rounding noise matches the unsharded path bit-for-bit
    draw_npad: int
    #: canonical global shape of the bagging uniform draw
    #: (= ``histogram.bucket_size(global_rows)``, the same pad the
    #: serial learner's bagging buffer uses)
    bag_npad: int


def local_valid_rows(spec: ShardSpec, local_rows: int, num_valid):
    """Traced per-shard valid-row count: global rows are laid out in
    contiguous ``local_rows`` blocks, so shard ``d`` is valid up to
    ``num_valid - d * local_rows`` (clipped)."""
    import jax.numpy as jnp
    d = jax.lax.axis_index(spec.axis)
    return jnp.clip(num_valid - d * local_rows, 0,
                    local_rows).astype(jnp.int32)


def slice_global_draw(spec: ShardSpec, full, local_rows: int):
    """Take this shard's block of a canonically-shaped global draw.

    ``full`` is a 1-D array drawn at a canonical global shape
    (``draw_npad`` / ``bag_npad``); rows beyond it (only ever bucket
    padding, zeroed by the valid mask) read as 0.
    """
    import jax.numpy as jnp
    total = spec.n_shards * local_rows
    if full.shape[0] >= total:
        full = full[:total]
    else:
        full = jnp.pad(full, (0, total - full.shape[0]))
    off = jax.lax.axis_index(spec.axis) * local_rows
    return jax.lax.dynamic_slice(full, (off,), (local_rows,))


def make_shard_mesh(num_devices: int = 0):
    """One-axis ``SHARD_AXIS`` mesh over local devices (0 = all).

    Raises :class:`LightGBMError` when fewer than 2 devices are
    available — single-controller sharding with one device is exactly
    the unsharded fused path, so callers fall back instead.
    """
    from jax.sharding import Mesh
    devices = jax.devices()
    d = int(num_devices) or len(devices)
    if d < 2:
        raise LightGBMError(
            f"data_sharding=single_controller needs >= 2 devices, have "
            f"{len(devices)} (request {d}); on CPU force a virtual mesh "
            f"with XLA_FLAGS=--xla_force_host_platform_device_count=N")
    if d > len(devices):
        raise LightGBMError(
            f"shard_devices={d} exceeds available devices "
            f"({len(devices)})")
    return Mesh(np.asarray(devices[:d]), (SHARD_AXIS,))


def sharding_mode(config) -> str:
    """Resolved ``data_sharding`` mode string ("off" when unset)."""
    return str(getattr(config, "data_sharding", "off") or "off").lower()


# ---------------------------------------------------------------------------
# multi-controller (pod-slice) bring-up
# ---------------------------------------------------------------------------

def multihost_params(config=None) -> Optional[Tuple[str, int, int]]:
    """Resolve ``(coordinator_address, num_hosts, host_rank)`` from the
    config with ``LGBM_TPU_COORDINATOR`` / ``LGBM_TPU_NUM_HOSTS`` /
    ``LGBM_TPU_HOST_RANK`` env fallbacks.

    Returns None when none of the three is set anywhere (multi-
    controller simply not configured); raises :class:`LightGBMError`
    when the triple is only partially specified or malformed — a pod
    host guessing its rank would train a silently-wrong model.
    """
    coord = str(getattr(config, "coordinator_address", "") or ""
                ) or os.environ.get(ENV_COORDINATOR, "")
    hosts_raw = getattr(config, "num_hosts", 0) or 0
    hosts = int(hosts_raw) or int(os.environ.get(ENV_NUM_HOSTS, "0")
                                  or "0")
    rank_raw = getattr(config, "host_rank", -1)
    rank = int(-1 if rank_raw is None else rank_raw)
    if rank < 0:
        rank = int(os.environ.get(ENV_HOST_RANK, "-1") or "-1")
    if not coord and hosts <= 0 and rank < 0:
        return None
    if not coord or hosts <= 0 or rank < 0:
        raise LightGBMError(
            f"data_sharding=multi_controller needs ALL of "
            f"coordinator_address/num_hosts/host_rank (or the "
            f"{ENV_COORDINATOR}/{ENV_NUM_HOSTS}/{ENV_HOST_RANK} env "
            f"vars); resolved coordinator={coord!r} num_hosts={hosts} "
            f"host_rank={rank}")
    if rank >= hosts:
        raise LightGBMError(
            f"host_rank={rank} out of range for num_hosts={hosts}")
    if ":" not in coord:
        raise LightGBMError(
            f"coordinator_address must be host:port, got {coord!r}")
    return coord, hosts, rank


def _distributed_client_active() -> bool:
    """Whether ``jax.distributed.initialize`` already ran in this
    process — checked WITHOUT touching ``jax.devices()`` (which would
    initialize the backend pre-coordinator and wedge the bring-up)."""
    try:
        from jax._src import distributed as _jdist
        return getattr(_jdist.global_state, "client", None) is not None
    except Exception:   # noqa: BLE001 — private-API drift: assume cold
        return False


def multihost_setup(config=None) -> Tuple[int, int]:
    """Fail-fast ``jax.distributed`` bring-up for one pod-slice host.

    Returns ``(host_rank, num_hosts)``.  Idempotent: a process whose
    distributed client is already up just reports its rank.  Rank 0
    hosts the coordinator and initializes directly; ranks > 0 first
    probe the coordinator socket with :func:`~lightgbm_tpu.parallel.
    network.wait_for_peer` (honoring ``network_timeout`` /
    ``network_retries``) so a dead coordinator surfaces as the
    familiar "peer unreachable after N attempts" error instead of a
    multi-minute initialize hang.  On CPU the cross-process collective
    backend is pinned to gloo BEFORE initialize — without it every
    psum dies with "Multiprocess computations aren't implemented on
    the CPU backend".
    """
    from .. import obs
    if _distributed_client_active():
        rank = int(jax.process_index())
        hosts = int(jax.process_count())
        obs.set_gauge("shard.hosts", hosts)
        return rank, hosts
    resolved = multihost_params(config)
    if resolved is None:
        raise LightGBMError(
            "data_sharding=multi_controller: no coordinator configured "
            "— set coordinator_address/num_hosts/host_rank (or the "
            "LGBM_TPU_COORDINATOR/LGBM_TPU_NUM_HOSTS/"
            "LGBM_TPU_HOST_RANK env vars)")
    coord, hosts, rank = resolved
    try:
        # scoped to the CPU backend; a no-op for TPU pods
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:   # noqa: BLE001 — option absent on this jax
        pass
    if rank > 0:
        # fail fast with peer context before the (slow) initialize
        # handshake; the probe retries with the shared backoff policy
        from ..parallel.network import wait_for_peer
        wait_for_peer(coord, config=config)
    from ..parallel.network import network_policy_from_config
    attempts, timeout_s = network_policy_from_config(config)
    try:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=hosts,
            process_id=rank,
            initialization_timeout=max(10, int(attempts * timeout_s)))
    except Exception as e:   # noqa: BLE001 — any bring-up failure
        raise LightGBMError(
            f"jax.distributed bring-up failed for host {rank}/{hosts} "
            f"against coordinator {coord}: {type(e).__name__}: {e}")
    got = int(jax.process_count())
    if got != hosts:
        raise LightGBMError(
            f"pod bring-up inconsistent: num_hosts={hosts} configured "
            f"but jax.process_count()={got}")
    obs.set_gauge("shard.hosts", hosts)
    log_info(f"multi_controller: host {rank}/{hosts} up against "
             f"{coord}, {len(jax.devices())} global device(s)")
    return rank, hosts


def is_multihost() -> bool:
    """True when this process is part of an initialized multi-process
    runtime (safe to call pre-bring-up: never initializes jax)."""
    if not _distributed_client_active():
        return False
    try:
        return int(jax.process_count()) > 1
    except Exception:   # noqa: BLE001
        return False


def mesh_is_multihost(mesh) -> bool:
    """Whether a mesh spans more than one process."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def make_pod_mesh():
    """One-axis ``SHARD_AXIS`` mesh over ALL global devices, sorted by
    ``(process_index, device id)`` so each host's addressable devices
    form one CONTIGUOUS run of mesh positions — the invariant that
    makes a host's row block ``[first_dev * n_loc, (last_dev+1) *
    n_loc)`` contiguous in the global padded row space (and therefore
    loadable as one streamed slab)."""
    from jax.sharding import Mesh
    devices = sorted(jax.devices(),
                     key=lambda d: (int(d.process_index), int(d.id)))
    if len(devices) < 2:
        raise LightGBMError(
            f"data_sharding=multi_controller needs >= 2 global "
            f"devices, have {len(devices)}")
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def process_row_span(mesh, local_rows: int,
                     process_index: Optional[int] = None
                     ) -> Tuple[int, int]:
    """``[lo, hi)`` block of the global PADDED row space owned by one
    process under a pod mesh with ``local_rows`` rows per device."""
    pid = (int(jax.process_index()) if process_index is None
           else int(process_index))
    idx = [i for i, d in enumerate(mesh.devices.flat)
           if int(d.process_index) == pid]
    if not idx:
        raise LightGBMError(
            f"process {pid} owns no devices of the pod mesh")
    if idx != list(range(idx[0], idx[0] + len(idx))):
        raise LightGBMError(
            f"pod mesh devices of process {pid} are not contiguous "
            f"(mesh positions {idx}); build the mesh with "
            f"make_pod_mesh()")
    return idx[0] * int(local_rows), (idx[-1] + 1) * int(local_rows)


def shard_local_rows(num_data: int, n_shards: int, config,
                     row_bucketing: Optional[bool] = None) -> int:
    """Per-device padded row count for a ``num_data``-row dataset over
    ``n_shards`` devices: ``ceil(N/D)`` lifted onto the pow2 bucket
    ladder (unless quantization keys its rounding stream on the exact
    padded shape, or the bucket would cross the striped-count bound),
    then chunk-aligned.  Factored out of the grower so ingest code can
    compute a host's row block BEFORE the grower exists — the padded
    layout is part of the data contract, not a grower detail."""
    from .grow import _CHUNK, _ceil_to, COUNT_SPLIT_ROWS
    from .histogram import bucket_size
    if row_bucketing is None:
        row_bucketing = bool(getattr(config, "train_row_bucketing",
                                     True))
    quant_on = bool(int(getattr(config, "grad_quant_bits", 0) or 0))
    srows = -(-int(num_data) // int(n_shards))
    if row_bucketing and not quant_on:
        b = bucket_size(max(srows, 1))
        if b >= 2 * COUNT_SPLIT_ROWS:
            log_info(
                f"train_row_bucketing: per-shard bucket {b} would "
                f"reach the striped-count bound; using exact "
                f"per-shard rows ({srows})")
        else:
            srows = b
    return _ceil_to(max(srows, _CHUNK), _CHUNK)


# replicate-to-all programs keyed by mesh device ids: ONE compiled
# identity per mesh, reused across growers/windows so warm same-shape
# windows re-dispatch instead of re-tracing (obs.track_jit makes any
# violation visible to the zero-retrace gates)
_REPLICATE_CACHE: dict = {}
_TRANSPOSE_CACHE: dict = {}
_PROGRAM_CACHE_LOCK = threading.Lock()


def replicate_to_all(mesh):
    """Jitted identity resharding any array to fully-replicated over
    ``mesh``.  Multi-controller growers apply it to the row-sharded
    final score so every host holds the full vector (checkpoints,
    metrics and the next dispatch all read it host-side); on a
    single-process mesh the arrays are already fully addressable and
    callers skip this entirely."""
    key = tuple(int(d.id) for d in mesh.devices.flat)
    fn = _REPLICATE_CACHE.get(key)
    if fn is None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .. import obs
        fn = obs.track_jit(
            "shard.replicate",
            jax.jit(lambda x: x,
                    out_shardings=NamedSharding(mesh, P())))
        with _PROGRAM_CACHE_LOCK:
            fn = _REPLICATE_CACHE.setdefault(key, fn)
    return fn


def transpose_col_sharded(mesh, axis: str = SHARD_AXIS):
    """Jitted ``(N, G) -> (G, N)`` transpose whose output is pinned
    column-split over the mesh axis — the multi-controller equivalent
    of the single-process ``device_put`` placement (``device_put``
    cannot reshard an array it cannot fully address; an SPMD program
    with explicit ``out_shardings`` can)."""
    key = (tuple(int(d.id) for d in mesh.devices.flat), axis)
    fn = _TRANSPOSE_CACHE.get(key)
    if fn is None:
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .. import obs
        fn = obs.track_jit(
            "shard.binned_t",
            jax.jit(lambda x: jnp.transpose(x),
                    out_shardings=NamedSharding(mesh, P(None, axis))))
        with _PROGRAM_CACHE_LOCK:
            fn = _TRANSPOSE_CACHE.setdefault(key, fn)
    return fn


def host_replicated(mesh, value):
    """Place host-identical data fully-replicated on every device of a
    (possibly multi-process) mesh.  Every process must call this with
    the SAME value — it is the caller's broadcast contract (mappers
    and labels travel over the net.broadcast blob plane first)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, P())
    arr = np.asarray(value)
    return jax.make_array_from_process_local_data(sh, arr)


def resolve_shard_mesh(config) -> Optional[object]:
    """Mesh for the configured ``data_sharding`` mode, or None.

    ``single_controller`` degrades gracefully (logged, training
    proceeds unsharded) — it is a local optimization.  A
    ``multi_controller`` failure RAISES instead: one pod host silently
    falling back to unsharded training while its peers wait on the
    histogram psum would wedge the whole slice, so bring-up errors
    must kill the process loudly.
    """
    mode = sharding_mode(config)
    if mode == "multi_controller":
        rank, hosts = multihost_setup(config)
        mesh = make_pod_mesh()
        log_info(f"data_sharding=multi_controller: host {rank}/{hosts}"
                 f", row-sharding over {mesh.devices.size} global "
                 f"device(s), psum wave histograms")
        return mesh
    if mode != "single_controller":
        return None
    try:
        mesh = make_shard_mesh(int(getattr(config, "shard_devices", 0)
                                   or 0))
    except LightGBMError as e:
        log_info(f"data_sharding=single_controller unavailable "
                 f"({e}); training unsharded")
        return None
    log_info(f"data_sharding=single_controller: row-sharding over "
             f"{mesh.devices.size} device(s), psum wave histograms")
    return mesh
