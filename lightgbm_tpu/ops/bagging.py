"""Device-side bagging / GOSS row selection.

The reference builds bagging index arrays with per-thread reservoir splits
(``gbdt.cpp:161-243``); here selection is a bernoulli mask + stable key-sort
compaction, producing the same (buffer, count) contract the tree learner
consumes.  GOSS (``goss.hpp:88-133``) keeps the top |g*h| rows and
up-weights a bernoulli sample of the rest by (n - top_k) / other_k.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .. import obs


def bagging_partition(key, n_pad: int, num_data, fraction):
    """Returns (buffer (n_pad,) int32 with selected rows first, count)."""
    return _bagging_impl(key, int(n_pad),
                         jnp.asarray(num_data, jnp.int32),
                         jnp.asarray(fraction, jnp.float32))


def _bag_selection(key, n_pad: int, num_data, fraction):
    """The ONE Bernoulli selection draw both bagging representations
    share: (valid, selected) bool (n_pad,) vectors.  Keeping it single-
    sourced is what guarantees the fused scan's row mask and the
    per-iteration permutation buffer select bit-identical bags."""
    pos = jnp.arange(n_pad, dtype=jnp.int32)
    valid = pos < num_data
    u = jax.random.uniform(key, (n_pad,))
    return valid, valid & (u < fraction)


@functools.partial(jax.jit, static_argnames=("n_pad",))
def _bagging_impl(key, n_pad, num_data, fraction):
    valid, selected = _bag_selection(key, n_pad, num_data, fraction)
    sort_key = jnp.where(selected, 0, jnp.where(valid, 1, 2))
    order = jnp.argsort(sort_key.astype(jnp.int32), stable=True)
    return order.astype(jnp.int32), selected.sum().astype(jnp.int32)


_bagging_impl = obs.track_jit("bagging_partition", _bagging_impl)


def bagging_row_mask(seed, n_pad: int, num_data: int, fraction):
    """(num_data,) f32 0/1 in-bag indicator from the SAME uniform draw
    ``bagging_partition`` makes for ``(PRNGKey(seed), n_pad)``.

    ``n_pad`` must be the learner's bagging-buffer pad (``bucket_size``),
    not the grower's chunk pad: the uniform draw's shape is part of the
    stream, so mask-based (fused scan) and buffer-based (per-iteration)
    bagging only agree bit-for-bit when both draw ``(n_pad,)`` uniforms.
    Traceable — ``seed`` may be a scan-carried iteration index.
    """
    _, sel = _bag_selection(jax.random.PRNGKey(seed), n_pad, num_data,
                            fraction)
    return sel.astype(jnp.float32)[:num_data]


def bagging_row_mask_global(seed, n_pad: int, num_data, fraction):
    """The FULL ``(n_pad,)`` f32 mask of the same draw
    :func:`bagging_row_mask` slices — the sharded fused scan takes each
    shard's block of this global-row-indexed mask, which is what makes
    bags shard-invariant (the same rows are in-bag whatever the mesh
    size, bit-for-bit)."""
    _, sel = _bag_selection(jax.random.PRNGKey(seed), n_pad, num_data,
                            fraction)
    return sel.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("n_pad",))
def goss_partition(key, grad_abs, n_pad, num_data, top_rate, other_rate):
    """GOSS selection on |g*h| scores summed over classes.

    Returns (buffer, count, multiplier_mask) where multiplier_mask is 1.0
    for kept/top rows and (n-top_k)/other_k for sampled rest rows (applied
    to grad AND hess by the caller, goss.hpp:117-126).
    """
    pos = jnp.arange(n_pad, dtype=jnp.int32)
    valid = pos < num_data
    scores = jnp.where(valid, grad_abs, -jnp.inf)
    top_k = jnp.maximum(
        (num_data.astype(jnp.float32) * top_rate).astype(jnp.int32), 1)
    other_k = jnp.maximum(
        (num_data.astype(jnp.float32) * other_rate).astype(jnp.int32), 1)
    sorted_desc = jnp.sort(scores)[::-1]
    threshold = sorted_desc[jnp.clip(top_k - 1, 0, n_pad - 1)]
    is_top = valid & (grad_abs >= threshold)
    rest = valid & ~is_top
    n_rest = jnp.maximum(rest.sum(), 1)
    prob = other_k.astype(jnp.float32) / n_rest.astype(jnp.float32)
    u = jax.random.uniform(key, (n_pad,))
    sampled = rest & (u < prob)
    selected = is_top | sampled
    multiplier = jnp.where(
        sampled,
        (num_data - top_k).astype(jnp.float32)
        / other_k.astype(jnp.float32), 1.0)
    sort_key = jnp.where(selected, 0, jnp.where(valid, 1, 2))
    order = jnp.argsort(sort_key.astype(jnp.int32), stable=True)
    return (order.astype(jnp.int32), selected.sum().astype(jnp.int32),
            multiplier)


goss_partition = obs.track_jit("goss_partition", goss_partition)
