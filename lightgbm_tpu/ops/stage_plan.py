"""Wave-stage planning for the device grower.

The grower splits a tree's growth into *stages*: each stage runs a
``lax.while_loop`` of fixed-width waves, and the stage plan decides the
wave width (histogram columns = width x stat columns) and the leaf-count
cap at which the next, wider stage takes over.  The measured wave cost
is ``fixed + col_ms * width * hist_cols``: the fixed part (the one-hot
operand generation over all N rows) is width-independent, so at small
frontiers it dominates and FEWER, WIDER stages win, while at large
frontiers the column term dominates and width-matching the frontier
wins.  ``ops/grow.py`` historically hardcoded a doubling plan from
constants measured at 10.5M rows (scripts/ubench_hist.py); this module
keeps that plan as the byte-stable default and adds

* a cost model + simulator (``plan_cost``) over the leaf-growth
  trajectory (a wave can split at most ``min(width, frontier, budget)``
  leaves);
* ``derive_stage_plan``: pick the cheapest plan from the doubling-ladder
  family for MEASURED (fixed, col) costs;
* a process-level plan cache keyed on the grower's (shape, config)
  signature, filled by ``DeviceGrower.profile_stage_plan`` (which times
  each candidate width with separately-jitted probes and records the
  timings through the obs layer as ``grow.stage.w<W>``).

The derived plan only replaces the default when profiling ran
(``wave_plan=profiled``) or a cached profiled plan exists for the same
signature (``wave_plan=auto``): wave batching order can move splits near
the ``num_leaves`` budget boundary, so the unprofiled default must stay
byte-identical across releases.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

# constants measured on the chip at 10.5M rows (scripts/ubench_hist.py):
# ~15.9 ms fixed one-hot operand generation + ~0.203 ms per stat column.
# Both terms contract over all N rows, so ``fit_wave_costs`` scales them
# linearly by rows/REF_ROWS when falling back for a different shape.
DEFAULT_FIXED_MS = 15.9
DEFAULT_COL_MS = 0.203
REF_ROWS = 10_500_000

Plan = List[Tuple[int, Optional[int]]]

_PLAN_CACHE: Dict[tuple, Plan] = {}
_PLAN_CACHE_LOCK = threading.Lock()

# wave_plan=auto profiles on first use only at production scale: below
# this many training rows the whole tree costs milliseconds and the
# probe compiles would dominate (small CPU tests/windows keep the
# byte-stable legacy plan with zero measurement overhead)
AUTO_PROFILE_MIN_ROWS = 1 << 19


def legacy_stage_plan(num_leaves: int, wave_width: int,
                      hist_cols: int) -> Plan:
    """The historical doubling plan (moved verbatim from ops/grow.py):
    byte-stable — growth order near the leaf budget depends on it."""
    scale = 3.0 / hist_cols
    return [
        (ws, cap) for ws, cap in
        ((4, 8), (16, 32), (max(int(32 * scale), 4), 64),
         (max(int(64 * scale), 4), 128))
        if ws < wave_width and cap < num_leaves
    ] + [(wave_width, None)]


def plan_digest(plan: Sequence) -> str:
    """Short stable digest of a stage plan (bench JSON attribution)."""
    canon = repr([(int(w), None if c is None else int(c))
                  for w, c in plan])
    return hashlib.sha1(canon.encode()).hexdigest()[:10]


def plan_cost_fn(plan: Sequence, num_leaves: int,
                 wave_ms) -> Tuple[float, int]:
    """(modeled ms per tree, wave count) for a full growth to
    ``num_leaves`` given a per-width wave cost function.  Per wave at
    most ``min(width, frontier, budget)`` splits apply: only existing
    leaves can split, so a wide early wave still pays its full cost
    while splitting few leaves."""
    nl, cost, waves = 1, 0.0, 0
    L = num_leaves
    for ws, cap in plan:
        limit = L if cap is None else min(cap, L)
        while nl < limit:
            s = min(ws, nl, L - nl)
            if s <= 0:
                break
            nl += s
            cost += wave_ms(ws)
            waves += 1
    return cost, waves


def plan_cost(plan: Sequence, num_leaves: int, hist_cols: int,
              fixed_ms: float, col_ms: float) -> Tuple[float, int]:
    """plan_cost_fn under the linear fixed + col * width * k model."""
    return plan_cost_fn(plan, num_leaves,
                        lambda w: fixed_ms + col_ms * w * hist_cols)


def plan_dispatches(plan: Sequence, num_leaves: int,
                    fused: bool = True) -> int:
    """XLA program-dispatch equivalents for one tree under the plan:
    a fused hist+find wave is ONE dispatch (the gain scan rides the
    histogram program), while the two-pass layout pays a second
    find-best program per wave.  The simulator's wave count itself is
    layout-independent — fused waves count as one wave, never two
    (the PR-16 counts-as-waves bug class) — only the dispatch factor
    changes."""
    _, waves = plan_cost_fn(plan, num_leaves, lambda w: 0.0)
    return waves * (1 if fused else 2)


def _ladder(wave_width: int) -> List[int]:
    out, w = [], 4
    while w < wave_width:
        out.append(w)
        w *= 2
    return out


# a candidate plan must beat the incumbent by this margin to justify
# its extra lax.while_loop stages: below it, the modeled saving is
# measurement noise and fewer stages (smaller program, fewer compiled
# loop bodies) win.  This is what turns a flat measured cost curve
# ("per-wave fixed cost dominates at small frontiers") into FEWER,
# WIDER stages instead of the full ladder.
MIN_IMPROVEMENT = 0.02


def wave_cost_fn(hist_cols: int, fixed_ms: float, col_ms: float,
                 measured_ms: Optional[Dict[int, float]] = None,
                 find_ms: Optional[Dict[int, float]] = None,
                 fusion: str = "fused"):
    """Per-width wave cost (ms): the measured probe timing when one
    exists for the width, else the linear fixed + col * width * k model
    — shared by ``derive_stage_plan`` and ``plan_beats`` so the
    derivation and the legacy-bar comparison price plans identically.

    Fused-mode cost term: under ``fusion="fused"`` the find-best scan
    rides the histogram program, so ``measured_ms`` should carry the
    END-TO-END fused wave timings and nothing is added.  Under
    ``fusion="two_pass"`` each wave pays the second find-best dispatch:
    ``find_ms`` (width -> per-wave gain-scan ms, from the fusion
    probes) is added on top of the histogram cost.  With no ``find_ms``
    both modes price identically — the historical behaviour, so every
    pre-fusion call site is unchanged."""
    def wave_ms(w):
        base = float(measured_ms[w]) if measured_ms and w in measured_ms \
            else fixed_ms + col_ms * w * hist_cols
        if fusion == "two_pass" and find_ms:
            base += float(find_ms.get(w, 0.0))
        return base
    return wave_ms


def plan_beats(candidate: Sequence, incumbent: Sequence, num_leaves: int,
               hist_cols: int, fixed_ms: float, col_ms: float,
               measured_ms: Optional[Dict[int, float]] = None,
               find_ms: Optional[Dict[int, float]] = None,
               fusion: str = "fused") -> bool:
    """Whether ``candidate``'s modeled per-tree cost beats
    ``incumbent``'s by the ``MIN_IMPROVEMENT`` bar — the gate
    ``wave_plan=auto`` applies before displacing the byte-stable legacy
    ladder with a freshly measured plan.  ``find_ms``/``fusion`` carry
    the find-best placement pricing so the bar compares plans under
    the SAME wave layout the derivation used."""
    wave_ms = wave_cost_fn(hist_cols, fixed_ms, col_ms, measured_ms,
                           find_ms=find_ms, fusion=fusion)
    c_cand, _ = plan_cost_fn(candidate, num_leaves, wave_ms)
    c_inc, _ = plan_cost_fn(incumbent, num_leaves, wave_ms)
    return c_cand < c_inc * (1.0 - MIN_IMPROVEMENT)


def derive_stage_plan(num_leaves: int, wave_width: int, hist_cols: int,
                      fixed_ms: float, col_ms: float,
                      measured_ms: Optional[Dict[int, float]] = None,
                      find_ms: Optional[Dict[int, float]] = None,
                      fusion: str = "fused",
                      frontier_packing: bool = True) -> Plan:
    """Cheapest plan from the doubling-ladder family: every subset of
    intermediate widths {4, 8, 16, ...} (stage (w, 2w) runs width w
    until the leaf count outgrows it) closed by the full-width stage.
    The ladder has <= 6 rungs, so exhaustive search is trivial.

    ``measured_ms`` (width -> per-wave ms, from the profile probes) is
    used directly when present — the measured curve is typically NOT
    linear at small widths (a minimum MXU tile / dispatch floor), which
    is exactly what makes narrow early stages worthless on some shapes;
    the linear (fixed, col) model only fills unprobed widths.  Candidates
    are scanned fewest-stages-first and a longer plan must be at least
    ``MIN_IMPROVEMENT`` cheaper to displace the incumbent.

    ``frontier_packing`` is the knob that merges adjacent under-full
    waves into one wider dispatch: a skipped ladder rung w hands its
    frontier-w wave to the next stage's 2w-wide (initially half-empty)
    dispatch, trading wasted lanes for one fewer wave.  Disabled, the
    candidate set collapses to the single strictly width-matched full
    ladder, so every wave runs at (at most) its frontier's width.
    ``find_ms``/``fusion`` price the find-best placement per wave
    (:func:`wave_cost_fn`): under two_pass each wave carries the second
    gain-scan dispatch, which makes packed (fewer-wave) plans win
    earlier than under fused pricing."""
    wave_ms = wave_cost_fn(hist_cols, fixed_ms, col_ms, measured_ms,
                           find_ms=find_ms, fusion=fusion)

    rungs = _ladder(wave_width)
    full: Plan = [(w, 2 * w) for w in rungs
                  if 2 * w < num_leaves] + [(wave_width, None)]
    if not frontier_packing:
        return full
    candidates: List[Plan] = [[(wave_width, None)]]
    for mask in range(1, 1 << len(rungs)):
        subset = [rungs[i] for i in range(len(rungs)) if mask >> i & 1]
        candidates.append([(w, 2 * w) for w in subset
                           if 2 * w < num_leaves] + [(wave_width, None)])
    candidates.sort(key=len)
    best_plan = candidates[0]
    best_cost, _ = plan_cost_fn(best_plan, num_leaves, wave_ms)
    for plan in candidates[1:]:
        cost, _ = plan_cost_fn(plan, num_leaves, wave_ms)
        if cost < best_cost * (1.0 - MIN_IMPROVEMENT):
            best_cost, best_plan = cost, plan
    return best_plan


def fit_wave_costs(widths: Sequence[int], ms: Sequence[float],
                   hist_cols: int,
                   num_data: Optional[int] = None) -> Tuple[float, float]:
    """Least-squares (fixed_ms, col_ms) from per-width probe timings.
    Degenerate fits (negative slope/intercept from noisy small-scale
    probes) fall back to the measured chip constants, scaled to
    ``num_data`` rows when given (both cost terms are linear in N)."""
    import numpy as np
    x = np.asarray([w * hist_cols for w in widths], np.float64)
    y = np.asarray(ms, np.float64)
    if len(x) >= 2 and float(x.max() - x.min()) > 0:
        col, fixed = np.polyfit(x, y, 1)
    else:
        col, fixed = -1.0, -1.0
    if col <= 0 or fixed < 0:
        scale = num_data / REF_ROWS if num_data else 1.0
        return DEFAULT_FIXED_MS * scale, DEFAULT_COL_MS * scale
    return float(fixed), float(col)


def cached_plan(signature: tuple) -> Optional[Plan]:
    with _PLAN_CACHE_LOCK:
        plan = _PLAN_CACHE.get(signature)
        return list(plan) if plan is not None else None


def cache_plan(signature: tuple, plan: Sequence,
               persist: bool = True) -> None:
    """Record ``plan`` for ``signature`` in the process cache and —
    unless ``persist=False`` — write it through to the on-disk store
    beside the compile cache, so fresh processes adopt it without
    re-profiling (``persist=False`` is for plans that CAME from disk)."""
    with _PLAN_CACHE_LOCK:
        _PLAN_CACHE[signature] = [(int(w), None if c is None else int(c))
                                  for w, c in plan]
    if persist:
        save_plan(signature, plan)


# ---------------------------------------------------------------------------
# on-disk persistence: profiled plans live beside the persistent XLA
# compile cache (ROADMAP 1c).  A stage plan shapes the traced program,
# so a cross-process warm start needs BOTH the compiled executables and
# the plan they were compiled for — co-locating them makes "warm the
# cache dir" one operation.  Files are keyed on a sha1 of the grower's
# (shape, config) signature repr (PYTHONHASHSEED-independent — the same
# property tests pin for programs_signature itself) and verified on
# load: signature text must match exactly and the stored digest must
# match the stored plan, so a corrupt or hand-edited file degrades to
# the legacy plan instead of training with an unvetted stage order.
# ---------------------------------------------------------------------------

def store_dir() -> Optional[str]:
    """``<compile_cache_dir>/stage_plans``, or None when no persistent
    compile cache is active (plans then live for the process only)."""
    from .. import compile_cache
    return compile_cache.artifact_dir("stage_plans")


def _plan_path(signature: tuple) -> Optional[str]:
    d = store_dir()
    if d is None:
        return None
    key = hashlib.sha1(repr(tuple(signature)).encode()).hexdigest()[:20]
    return os.path.join(d, f"plan_{key}.json")


def save_plan(signature: tuple, plan: Sequence) -> Optional[str]:
    """Atomically persist ``plan``; returns the path, or None when no
    store is active or the write fails (best-effort — a read-only cache
    dir must not take down training over a plan)."""
    path = _plan_path(signature)
    if path is None:
        return None
    canon = [[int(w), None if c is None else int(c)] for w, c in plan]
    payload = {"signature": repr(tuple(signature)),
               "plan": canon,
               "digest": plan_digest(canon)}
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
    except OSError as e:
        from ..utils.log import log_warning
        log_warning(f"cannot persist the profiled stage plan to "
                    f"{path}: {e}; the plan stays process-local")
        try:
            os.unlink(tmp)    # don't leave orphaned .tmp files behind
        except OSError:
            pass
        return None
    return path


def load_plan(signature: tuple) -> Optional[Plan]:
    """Load a persisted plan for ``signature``; None (-> legacy plan)
    when absent, unreadable, signature-mismatched, or digest-corrupt."""
    path = _plan_path(signature)
    if path is None or not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return None
    if payload.get("signature") != repr(tuple(signature)):
        return None
    try:
        plan = [(int(w), None if c is None else int(c))
                for w, c in payload.get("plan")]
    except (TypeError, ValueError):
        return None
    if not plan or plan_digest(plan) != payload.get("digest"):
        return None
    return plan


def forget_plan(signature: tuple) -> None:
    """Drop ``signature``'s plan from the process cache AND the disk
    store (tests and operators invalidating a stale measurement)."""
    with _PLAN_CACHE_LOCK:
        _PLAN_CACHE.pop(signature, None)
    path = _plan_path(signature)
    if path is not None:
        try:
            os.remove(path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# fused-vs-two-pass verdicts: wave_plan=profiled times the find-best
# scan in both wave layouts and the winner is recorded here, keyed and
# persisted EXACTLY like the stage plan it was measured with (same
# signature, same store beside the compile cache), so
# ``find_best_fusion=auto`` resolves to the measured layout in this
# process and every fresh process after it.  Like the plan, the
# resolved mode shapes the traced program — ops/grow.py keys the
# program cache on it — so a corrupt or mismatched file degrades to
# the default (fused) rather than adopting an unvetted layout.
# ---------------------------------------------------------------------------

_FUSION_MODES = ("fused", "two_pass")
_FUSION_CACHE: Dict[tuple, str] = {}


def cached_fusion(signature: tuple) -> Optional[str]:
    with _PLAN_CACHE_LOCK:
        return _FUSION_CACHE.get(signature)


def cache_fusion(signature: tuple, mode: str, persist: bool = True,
                 detail: Optional[dict] = None) -> None:
    """Record the measured find-best layout for ``signature`` in the
    process cache and — unless ``persist=False`` — the on-disk store
    (``persist=False`` is for verdicts that CAME from disk).
    ``detail`` (e.g. the per-tree ms both layouts modeled) rides along
    in the persisted file for bench/ops archaeology."""
    if mode not in _FUSION_MODES:
        raise ValueError(f"find-best fusion verdict must be one of "
                         f"{_FUSION_MODES}, got {mode!r}")
    with _PLAN_CACHE_LOCK:
        _FUSION_CACHE[signature] = mode
    if persist:
        save_fusion(signature, mode, detail)


def _fusion_path(signature: tuple) -> Optional[str]:
    d = store_dir()
    if d is None:
        return None
    key = hashlib.sha1(repr(tuple(signature)).encode()).hexdigest()[:20]
    return os.path.join(d, f"fusion_{key}.json")


def save_fusion(signature: tuple, mode: str,
                detail: Optional[dict] = None) -> Optional[str]:
    """Atomically persist the fusion verdict; best-effort like
    :func:`save_plan` (a read-only cache dir must not take down
    training over a verdict)."""
    path = _fusion_path(signature)
    if path is None:
        return None
    payload = {"signature": repr(tuple(signature)), "mode": str(mode)}
    if detail:
        payload["detail"] = detail
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
    except OSError as e:
        from ..utils.log import log_warning
        log_warning(f"cannot persist the fused-find verdict to "
                    f"{path}: {e}; the verdict stays process-local")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path


def load_fusion(signature: tuple) -> Optional[str]:
    """Load a persisted fusion verdict; None (-> default fused) when
    absent, unreadable, signature-mismatched, or not a known mode."""
    path = _fusion_path(signature)
    if path is None or not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return None
    if payload.get("signature") != repr(tuple(signature)):
        return None
    mode = payload.get("mode")
    return mode if mode in _FUSION_MODES else None


def forget_fusion(signature: tuple) -> None:
    """Drop ``signature``'s fusion verdict from the process cache AND
    the disk store."""
    with _PLAN_CACHE_LOCK:
        _FUSION_CACHE.pop(signature, None)
    path = _fusion_path(signature)
    if path is not None:
        try:
            os.remove(path)
        except OSError:
            pass
