"""Plotting utilities (reference ``python-package/lightgbm/plotting.py``)."""

from __future__ import annotations

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel

__all__ = ["plot_importance", "plot_metric", "plot_tree", "create_tree_digraph"]


def _to_booster(booster):
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel")


def plot_importance(booster, ax=None, height=0.2, xlim=None, ylim=None,
                    title="Feature importance", xlabel="Feature importance",
                    ylabel="Features", importance_type="split",
                    max_num_features=None, ignore_zero=True, figsize=None,
                    grid=True, **kwargs):
    import matplotlib.pyplot as plt
    bst = _to_booster(booster)
    importance = bst.feature_importance(importance_type)
    names = bst.feature_name()
    tuples = sorted(zip(names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [t for t in tuples if t[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("cannot plot trees with zero importance")
    labels, values = zip(*tuples)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, str(int(x) if float(x).is_integer() else x),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric=None, dataset_names=None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="auto", figsize=None, grid=True):
    import matplotlib.pyplot as plt
    if isinstance(booster, LGBMModel):
        eval_results = booster.evals_result_
    elif isinstance(booster, dict):
        eval_results = booster
    else:
        raise TypeError("booster must be dict or LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    names = dataset_names or list(eval_results.keys())
    for name in names:
        metrics = eval_results[name]
        m = metric or next(iter(metrics))
        results = metrics[m]
        ax.plot(range(len(results)), results, label=name)
    ax.legend(loc="best")
    if title:
        ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(metric or "metric" if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index=0, show_info=None, precision=3,
                        name=None, comment=None, **kwargs):
    import graphviz
    bst = _to_booster(booster)
    if tree_index >= len(bst._gbdt.models):
        raise IndexError("tree_index is out of range")
    tree = bst._gbdt.models[tree_index]
    feature_names = bst.feature_name()
    show_info = show_info or []
    graph = graphviz.Digraph(name=name, comment=comment, **kwargs)

    def add(idx, parent=None, decision=None):
        if idx < 0:
            leaf = ~idx
            node_name = f"leaf{leaf}"
            label = f"leaf {leaf}: {tree.leaf_value[leaf]:.{precision}f}"
            if "leaf_count" in show_info:
                label += f"\ncount: {tree.leaf_count[leaf]}"
            graph.node(node_name, label=label)
        else:
            node_name = f"split{idx}"
            f = int(tree.split_feature[idx])
            fname = feature_names[f] if f < len(feature_names) else str(f)
            dt = int(tree.decision_type[idx])
            op = "==" if dt & 1 else "<="
            label = f"{fname} {op} {tree.threshold[idx]:.{precision}g}"
            if "split_gain" in show_info:
                label += f"\ngain: {tree.split_gain[idx]:.{precision}f}"
            if "internal_count" in show_info:
                label += f"\ncount: {tree.internal_count[idx]}"
            graph.node(node_name, label=label)
            add(int(tree.left_child[idx]), node_name, "yes")
            add(int(tree.right_child[idx]), node_name, "no")
        if parent is not None:
            graph.edge(parent, node_name, decision)
        return node_name

    add(0 if tree.num_leaves > 1 else -1)
    return graph


def plot_tree(booster, ax=None, tree_index=0, figsize=None, show_info=None,
              precision=3, **kwargs):
    import matplotlib.pyplot as plt
    import matplotlib.image as mpimg
    import io
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                **kwargs)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    s = io.BytesIO(graph.pipe(format="png"))
    img = mpimg.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
