"""scikit-learn API wrappers (reference
``python-package/lightgbm/sklearn.py:128-833``)."""

from __future__ import annotations

from inspect import signature
from typing import Optional

import numpy as np

from .basic import Booster, Dataset
from .engine import train
from .utils.log import LightGBMError

__all__ = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]


def _objective_function_wrapper(func):
    """Wrap a sklearn-style objective fobj(y_true, y_pred[, group]) into the
    engine's fobj(preds, dataset) (reference sklearn.py:31-86)."""

    def inner(preds, dataset):
        labels = dataset.get_label()
        argc = len(signature(func).parameters)
        if argc == 2:
            grad, hess = func(labels, preds)
        elif argc == 3:
            grad, hess = func(labels, preds, dataset.get_group())
        else:
            raise TypeError(
                "Self-defined objective should have 2 or 3 arguments")
        return grad, hess
    return inner


def _eval_function_wrapper(func):
    """Wrap feval(y_true, y_pred[, weight[, group]]) ->
    (name, value, is_higher_better) (reference sklearn.py:88-127)."""

    def inner(preds, dataset):
        labels = dataset.get_label()
        argc = len(signature(func).parameters)
        if argc == 2:
            return func(labels, preds)
        if argc == 3:
            return func(labels, preds, dataset.get_weight())
        if argc == 4:
            return func(labels, preds, dataset.get_weight(),
                        dataset.get_group())
        raise TypeError(
            "Self-defined eval function should have 2, 3 or 4 arguments")
    return inner


class LGBMModel:
    """Base sklearn estimator (reference sklearn.py:128-622)."""

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=100,
                 subsample_for_bin=200000, objective=None, class_weight=None,
                 min_split_gain=0.0, min_child_weight=1e-3,
                 min_child_samples=20, subsample=1.0, subsample_freq=0,
                 colsample_bytree=1.0, reg_alpha=0.0, reg_lambda=0.0,
                 random_state=None, n_jobs=-1, silent=True,
                 importance_type="split", **kwargs):
        self.boosting_type = boosting_type
        self.objective = objective
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result = None
        self._best_score = None
        self._best_iteration = None
        self._classes = None
        self._n_classes = None
        self._n_features = None
        self._objective = objective
        self.set_params(**kwargs)

    # -- sklearn plumbing ----------------------------------------------
    def get_params(self, deep=True):
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective,
            "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample,
            "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha,
            "reg_lambda": self.reg_lambda,
            "random_state": self.random_state,
            "n_jobs": self.n_jobs,
            "silent": self.silent,
            "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params):
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, f"_{key}"):
                setattr(self, f"_{key}", value)
            self._other_params[key] = value
        for key in list(self._other_params):
            if hasattr(type(self), key) or key in signature(
                    type(self).__init__).parameters:
                self._other_params.pop(key)
        return self

    # ------------------------------------------------------------------
    def _get_lgb_params(self):
        params = self.get_params()
        params.pop("silent", None)
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        params["boosting"] = params.pop("boosting_type", "gbdt")
        params["bagging_fraction"] = params.pop("subsample", 1.0)
        params["bagging_freq"] = params.pop("subsample_freq", 0)
        params["feature_fraction"] = params.pop("colsample_bytree", 1.0)
        params["lambda_l1"] = params.pop("reg_alpha", 0.0)
        params["lambda_l2"] = params.pop("reg_lambda", 0.0)
        params["min_gain_to_split"] = params.pop("min_split_gain", 0.0)
        params["min_sum_hessian_in_leaf"] = params.pop("min_child_weight",
                                                       1e-3)
        params["min_data_in_leaf"] = params.pop("min_child_samples", 20)
        params["bin_construct_sample_cnt"] = params.pop("subsample_for_bin",
                                                        200000)
        rs = params.pop("random_state", None)
        if rs is not None:
            params["seed"] = (rs if isinstance(rs, int)
                              else rs.randint(2 ** 31 - 1))
        params.pop("n_jobs", None)
        if params.get("objective") is None:
            params["objective"] = self._default_objective()
        if callable(params.get("objective")):
            self._fobj = _objective_function_wrapper(params["objective"])
            params["objective"] = "none"
        else:
            self._fobj = None
        return {k: v for k, v in params.items() if v is not None}

    def _default_objective(self):
        return "regression"

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose=True,
            feature_name="auto", categorical_feature="auto",
            callbacks=None):
        params = self._get_lgb_params()
        if self.class_weight is not None:
            sample_weight = _apply_class_weight(
                self.class_weight, np.asarray(y), sample_weight)
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric
        feval = (_eval_function_wrapper(eval_metric)
                 if callable(eval_metric) else None)

        train_ds = Dataset(X, label=y, weight=sample_weight,
                           group=group, init_score=init_score,
                           params={}, feature_name=feature_name,
                           categorical_feature=categorical_feature,
                           free_raw_data=False)
        valid_sets = []
        valid_names = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_ds)
                else:
                    w = (eval_sample_weight or {}).get(i) \
                        if isinstance(eval_sample_weight, dict) \
                        else (eval_sample_weight[i]
                              if eval_sample_weight else None)
                    g = eval_group[i] if eval_group else None
                    isc = eval_init_score[i] if eval_init_score else None
                    valid_sets.append(train_ds.create_valid(
                        vx, label=vy, weight=w, group=g, init_score=isc))
                valid_names.append((eval_names or {}).get(i)
                                   if isinstance(eval_names, dict)
                                   else (eval_names[i] if eval_names
                                         else f"valid_{i}"))
        evals_result = {}
        self._Booster = train(
            params, train_ds,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None,
            valid_names=valid_names or None,
            fobj=self._fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=evals_result, verbose_eval=verbose,
            callbacks=callbacks)
        self._evals_result = evals_result
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        self._n_features = train_ds.num_feature()
        return self

    def predict(self, X, raw_score=False, num_iteration=-1, pred_leaf=False,
                pred_contrib=False, **kwargs):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit first")
        if num_iteration <= 0 and self._best_iteration is not None \
                and self._best_iteration > 0:
            num_iteration = self._best_iteration
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib)

    # -- attributes -----------------------------------------------------
    @property
    def booster_(self):
        if self._Booster is None:
            raise LightGBMError("No booster found, call fit first")
        return self._Booster

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def best_iteration_(self):
        return self._best_iteration

    @property
    def best_score_(self):
        return self._best_score

    @property
    def n_features_(self):
        return self._n_features

    @property
    def feature_importances_(self):
        if self._Booster is None:
            raise LightGBMError("No booster found, call fit first")
        return self._Booster.feature_importance(self.importance_type)

    @property
    def objective_(self):
        return self.objective or self._default_objective()


class LGBMRegressor(LGBMModel):
    def _default_objective(self):
        return "regression"

    def _more_tags(self):
        return {"estimator_type": "regressor"}


class LGBMClassifier(LGBMModel):
    def _default_objective(self):
        return "binary" if (self._n_classes or 2) <= 2 else "multiclass"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self._classes, y_enc = np.unique(y, return_inverse=True)
        self._n_classes = len(self._classes)
        if self._n_classes > 2:
            self._other_params["num_class"] = self._n_classes
            if self.objective is None:
                self.objective = "multiclass"
        # transform eval sets' labels too
        es = kwargs.get("eval_set")
        if es is not None:
            mapping = {c: i for i, c in enumerate(self._classes)}
            new_es = []
            for vx, vy in ([es] if isinstance(es, tuple) else es):
                new_es.append((vx, np.asarray(
                    [mapping[v] for v in np.asarray(vy)])))
            kwargs["eval_set"] = new_es
        return super().fit(X, y_enc, **kwargs)

    def predict(self, X, raw_score=False, num_iteration=-1, pred_leaf=False,
                pred_contrib=False, **kwargs):
        result = self.predict_proba(X, raw_score, num_iteration, pred_leaf,
                                    pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        return self._classes[np.argmax(result, axis=1)]

    def predict_proba(self, X, raw_score=False, num_iteration=-1,
                      pred_leaf=False, pred_contrib=False, **kwargs):
        res = super().predict(X, raw_score, num_iteration, pred_leaf,
                              pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return res
        if res.ndim == 1:
            return np.column_stack([1.0 - res, res])
        return res

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self):
        return self._n_classes

    def _more_tags(self):
        return {"estimator_type": "classifier"}


class LGBMRanker(LGBMModel):
    def _default_objective(self):
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        eval_set = kwargs.get("eval_set")
        if eval_set is not None and kwargs.get("eval_group") is None:
            raise ValueError("Eval_group cannot be None when eval_set "
                             "is not None")
        return super().fit(X, y, group=group, **kwargs)


def _apply_class_weight(class_weight, y, sample_weight):
    if class_weight == "balanced":
        classes, counts = np.unique(y, return_counts=True)
        weights = {c: len(y) / (len(classes) * cnt)
                   for c, cnt in zip(classes, counts)}
    else:
        weights = dict(class_weight)
    w = np.asarray([weights.get(v, 1.0) for v in y], np.float64)
    if sample_weight is not None:
        w = w * np.asarray(sample_weight, np.float64)
    return w
