"""``LGBM_*`` C-API compatibility shim.

The reference's compatibility contract is ``src/c_api.cpp`` /
``include/LightGBM/c_api.h:50-234,799-815``: opaque dataset/booster
handles, int return codes (0 ok, -1 failure + ``LGBM_GetLastError``),
caller-allocated output buffers.  The fork's cache-admission harness
consumes exactly this surface (``src/test.cpp:243-298``:
DatasetCreateFromCSR / DatasetSetField / BoosterCreate /
BoosterUpdateOneIter / BoosterPredictForCSR).

This module reproduces that surface Python-level so C-API-shaped client
code ports mechanically:

* handles are opaque ints managed by an internal registry — ``Free``
  really invalidates them, double-free raises through the error code;
* out-parameters are ``Ref`` cells (the ``ctypes.byref`` analog);
* array arguments are numpy arrays whose dtype must match the declared
  ``C_API_DTYPE_*`` constant, like the C layer's type switch;
* caller-allocated result buffers (``out_result``) are written in place.

Functions intentionally keep the reference's argument order, including
the ``parameters`` string argument, so a port is a transliteration.
"""
# jaxlint: abi-header=../include/lightgbm_tpu/c_api.h
# (JL151 checks every declaration below against these defs' arities)

from __future__ import annotations

import json
import threading
from typing import Dict, Optional

import numpy as np

from .boosting import create_boosting
from .boosting.gbdt import GBDT
from .config import Config
from .data.dataset import BinnedDataset, Metadata
from .utils.log import LightGBMError

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3

C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_PREDICT_CONTRIB = 3

_DTYPE_MAP = {
    C_API_DTYPE_FLOAT32: np.float32,
    C_API_DTYPE_FLOAT64: np.float64,
    C_API_DTYPE_INT32: np.int32,
    C_API_DTYPE_INT64: np.int64,
}


class Ref:
    """Out-parameter cell — the ``ctypes.byref(x)`` analog."""

    __slots__ = ("value",)

    def __init__(self, value=None):
        self.value = value


_last_error = ""
# _last_error is process-global by C-API contract (LGBM_GetLastError);
# the embed path and user threads can fail concurrently, so the write is
# lock-guarded — a reader still sees whichever error landed last, but
# never a torn interpreter state
_ERROR_LOCK = threading.Lock()


def LGBM_GetLastError() -> str:
    return _last_error


def _api(fn):
    """C return-code convention: 0 ok, -1 failure + stored message."""
    def wrapper(*args, **kwargs):
        global _last_error
        try:
            fn(*args, **kwargs)
            return 0
        except Exception as e:   # noqa: BLE001 — the C API catches all
            with _ERROR_LOCK:
                _last_error = str(e)
            return -1
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


# ---------------------------------------------------------------------------
# handle registry
# ---------------------------------------------------------------------------

class _DatasetEntry:
    __slots__ = ("binned", "config", "raw_params", "feature_names")

    def __init__(self, binned, config, raw_params):
        self.binned = binned
        self.config = config
        self.raw_params = raw_params
        self.feature_names = None


class _BoosterEntry:
    __slots__ = ("gbdt", "train", "valids", "custom_objective")

    def __init__(self, gbdt, train):
        self.gbdt = gbdt
        self.train = train
        self.valids = []
        self.custom_objective = False


class _ServeEntry:
    """A hot-swap PredictionServer behind an opaque handle
    (lightgbm_tpu extension — LGBM_Serve* functions)."""

    __slots__ = ("server",)

    def __init__(self, server):
        self.server = server


class _FleetEntry:
    """A multi-tenant FleetServer behind an opaque handle
    (lightgbm_tpu extension — LGBM_Fleet* functions)."""

    __slots__ = ("server",)

    def __init__(self, server):
        self.server = server


_handles: Dict[int, object] = {}
_next_handle = 1
# the serving setup is multi-threaded by design (PredictionServer micro-
# batch worker + harness threads), so handle allocation/free must not race
_HANDLES_LOCK = threading.Lock()


def _register(obj) -> int:
    global _next_handle
    with _HANDLES_LOCK:
        h = _next_handle
        _next_handle += 1
        _handles[h] = obj
    return h


def _unregister(handle) -> None:
    with _HANDLES_LOCK:
        del _handles[handle]


_HANDLE_KINDS = {_DatasetEntry: "Dataset", _BoosterEntry: "Booster",
                 _ServeEntry: "Serve", _FleetEntry: "Fleet"}


def _get(handle, cls):
    obj = _handles.get(handle)
    if not isinstance(obj, cls):
        kind = _HANDLE_KINDS.get(cls, "object")
        raise LightGBMError(f"invalid {kind} handle: {handle!r}")
    return obj


def _tokenize_params(parameters: Optional[str]) -> Dict[str, str]:
    """The C API's parameter format — space-separated key=value — as a
    raw dict.  The ONE tokenizer: `_parse_params` builds the Config
    from it, and explicit-key detection (LGBM_ServeCreate) reads its
    keys, so the two can never disagree."""
    kv: Dict[str, str] = {}
    if parameters:
        for tok in str(parameters).split():
            if "=" in tok:
                k, v = tok.split("=", 1)
                kv[k] = v
    return kv


def _parse_params(parameters: Optional[str]) -> Config:
    return Config(_tokenize_params(parameters))


def _check_array(arr, name, dtype_const, allowed):
    if dtype_const not in allowed:
        raise LightGBMError(f"unsupported dtype constant for {name}: "
                            f"{dtype_const}")
    want = _DTYPE_MAP[dtype_const]
    arr = np.asarray(arr)
    if arr.dtype != want:
        raise LightGBMError(
            f"{name} dtype {arr.dtype} does not match declared "
            f"C_API_DTYPE constant ({np.dtype(want)})")
    return arr


# ---------------------------------------------------------------------------
# Dataset functions (c_api.h:50-335)
# ---------------------------------------------------------------------------

@_api
def LGBM_DatasetCreateFromFile(filename, parameters, reference, out: Ref):
    cfg = _parse_params(parameters)
    ref = _get(reference, _DatasetEntry).binned if reference else None
    from .cli import _load_dataset
    binned = _load_dataset(str(filename), cfg, reference=ref)
    out.value = _register(_DatasetEntry(binned, cfg, parameters))


@_api
def LGBM_DatasetCreateFromMat(data, data_type, nrow, ncol, is_row_major,
                              parameters, reference, out: Ref):
    data = _check_array(data, "data", data_type,
                        (C_API_DTYPE_FLOAT32, C_API_DTYPE_FLOAT64))
    mat = np.asarray(data).reshape(
        (nrow, ncol) if is_row_major else (ncol, nrow))
    if not is_row_major:
        mat = mat.T
    cfg = _parse_params(parameters)
    ref = _get(reference, _DatasetEntry).binned if reference else None
    binned = BinnedDataset.construct_from_matrix(
        np.ascontiguousarray(mat, np.float64), cfg, reference=ref)
    out.value = _register(_DatasetEntry(binned, cfg, parameters))


@_api
def LGBM_DatasetCreateFromCSR(indptr, indptr_type, indices, data, data_type,
                              nindptr, nelem, num_col, parameters,
                              reference, out: Ref):
    indptr = _check_array(indptr, "indptr", indptr_type,
                          (C_API_DTYPE_INT32, C_API_DTYPE_INT64))
    data = _check_array(data, "data", data_type,
                        (C_API_DTYPE_FLOAT32, C_API_DTYPE_FLOAT64))
    indices = np.asarray(indices, np.int32)
    if len(indptr) != nindptr:
        raise LightGBMError("nindptr does not match indptr length")
    cfg = _parse_params(parameters)
    ref = _get(reference, _DatasetEntry).binned if reference else None
    binned = BinnedDataset.construct_from_csr(
        indptr[:nindptr], indices[:nelem],
        np.asarray(data[:nelem], np.float64), int(num_col), cfg,
        reference=ref)
    out.value = _register(_DatasetEntry(binned, cfg, parameters))


@_api
def LGBM_DatasetGetSubset(handle, used_row_indices, num_used_row_indices,
                          parameters, out: Ref):
    entry = _get(handle, _DatasetEntry)
    idx = np.asarray(used_row_indices, np.int32)[:num_used_row_indices]
    sub = entry.binned.copy_subset(idx)
    out.value = _register(_DatasetEntry(sub, entry.config, parameters))


@_api
def LGBM_DatasetSetFeatureNames(handle, feature_names, num_feature_names):
    entry = _get(handle, _DatasetEntry)
    names = [str(feature_names[i]) for i in range(num_feature_names)]
    entry.binned.feature_names = names
    entry.feature_names = names


@_api
def LGBM_DatasetGetFeatureNames(handle, out_strs: Ref, out_len: Ref):
    entry = _get(handle, _DatasetEntry)
    names = list(entry.binned.feature_names)
    out_strs.value = names
    out_len.value = len(names)


@_api
def LGBM_DatasetFree(handle):
    _get(handle, _DatasetEntry)
    _unregister(handle)


@_api
def LGBM_DatasetSaveBinary(handle, filename):
    _get(handle, _DatasetEntry).binned.save_binary(str(filename))


@_api
def LGBM_DatasetSetField(handle, field_name, field_data, num_element,
                         type_):
    entry = _get(handle, _DatasetEntry)
    md = entry.binned.metadata
    if md is None:
        md = entry.binned.metadata = Metadata(entry.binned.num_data)
    name = str(field_name)
    if name in ("label", "weight"):
        data = _check_array(field_data, name, type_,
                            (C_API_DTYPE_FLOAT32,))[:num_element]
        (md.set_label if name == "label" else md.set_weights)(
            np.asarray(data, np.float64))
    elif name in ("group", "query"):
        data = _check_array(field_data, name, type_,
                            (C_API_DTYPE_INT32,))[:num_element]
        md.set_query(np.asarray(data))
    elif name == "init_score":
        data = _check_array(field_data, name, type_,
                            (C_API_DTYPE_FLOAT64,))[:num_element]
        md.set_init_score(np.asarray(data, np.float64))
    else:
        raise LightGBMError(f"unknown field name: {name}")


@_api
def LGBM_DatasetGetField(handle, field_name, out_len: Ref, out_ptr: Ref,
                         out_type: Ref):
    md = _get(handle, _DatasetEntry).binned.metadata
    name = str(field_name)
    if md is None:
        raise LightGBMError("dataset has no metadata")
    if name == "label":
        arr, t = md.label, C_API_DTYPE_FLOAT32
        arr = None if arr is None else np.asarray(arr, np.float32)
    elif name == "weight":
        arr, t = md.weights, C_API_DTYPE_FLOAT32
        arr = None if arr is None else np.asarray(arr, np.float32)
    elif name in ("group", "query"):
        arr, t = md.query_boundaries, C_API_DTYPE_INT32
        arr = None if arr is None else np.asarray(arr, np.int32)
    elif name == "init_score":
        arr, t = md.init_score, C_API_DTYPE_FLOAT64
        arr = None if arr is None else np.asarray(arr, np.float64)
    else:
        raise LightGBMError(f"unknown field name: {name}")
    if arr is None:
        raise LightGBMError(f"field {name} is not set")
    out_ptr.value = arr
    out_len.value = len(arr)
    out_type.value = t


@_api
def LGBM_DatasetGetNumData(handle, out: Ref):
    out.value = int(_get(handle, _DatasetEntry).binned.num_data)


@_api
def LGBM_DatasetGetNumFeature(handle, out: Ref):
    out.value = int(_get(handle, _DatasetEntry).binned.num_total_features)


# ---------------------------------------------------------------------------
# Booster functions (c_api.h:341-797)
# ---------------------------------------------------------------------------

@_api
def LGBM_BoosterCreate(train_data, parameters, out: Ref):
    entry = _get(train_data, _DatasetEntry)
    cfg = _parse_params(parameters)
    gbdt = create_boosting(cfg)
    gbdt.init_train(entry.binned)
    out.value = _register(_BoosterEntry(gbdt, entry))


@_api
def LGBM_BoosterCreateFromModelfile(filename, out_num_iterations: Ref,
                                    out: Ref):
    gbdt = GBDT.load_model_from_file(str(filename))
    out_num_iterations.value = gbdt.num_iterations()
    out.value = _register(_BoosterEntry(gbdt, None))


@_api
def LGBM_BoosterLoadModelFromString(model_str, out_num_iterations: Ref,
                                    out: Ref):
    gbdt = GBDT.load_model_from_string(str(model_str))
    out_num_iterations.value = gbdt.num_iterations()
    out.value = _register(_BoosterEntry(gbdt, None))


@_api
def LGBM_BoosterFree(handle):
    _get(handle, _BoosterEntry)
    _unregister(handle)


@_api
def LGBM_BoosterAddValidData(handle, valid_data):
    b = _get(handle, _BoosterEntry)
    v = _get(valid_data, _DatasetEntry)
    b.gbdt.add_valid(v.binned, f"valid_{len(b.valids)}")
    b.valids.append(v)


@_api
def LGBM_BoosterGetNumClasses(handle, out_len: Ref):
    out_len.value = max(
        int(_get(handle, _BoosterEntry).gbdt.config.num_class), 1)


@_api
def LGBM_BoosterUpdateOneIter(handle, is_finished: Ref):
    b = _get(handle, _BoosterEntry)
    # unified driver: a 1-iteration chunk takes the per-iteration device
    # path but keeps bagging state consistent with fused chunks
    is_finished.value = 1 if b.gbdt.train_chunked(1) else 0


@_api
def LGBM_BoosterUpdateChunked(handle, n_iters, chunk, is_finished: Ref):
    """lightgbm_tpu extension (not in the reference ABI): train
    ``n_iters`` boosting iterations in fused device dispatches of up to
    ``chunk`` whole iterations each (``GBDT.train_chunked``).  The
    windowed retrain harness replaces its UpdateOneIter loop with ONE
    call per window, which is what lets wall-clock track device
    throughput instead of per-iteration host dispatch latency."""
    b = _get(handle, _BoosterEntry)
    is_finished.value = 1 if b.gbdt.train_chunked(int(n_iters),
                                                  chunk=int(chunk)) else 0


@_api
def LGBM_BoosterUpdateOneIterCustom(handle, grad, hess, is_finished: Ref):
    b = _get(handle, _BoosterEntry)
    grad = np.asarray(grad, np.float32)
    hess = np.asarray(hess, np.float32)
    is_finished.value = 1 if b.gbdt.train_one_iter(grad, hess) else 0


@_api
def LGBM_BoosterRollbackOneIter(handle):
    _get(handle, _BoosterEntry).gbdt.rollback_one_iter()


@_api
def LGBM_BoosterGetCurrentIteration(handle, out_iteration: Ref):
    out_iteration.value = _get(handle, _BoosterEntry).gbdt.num_iterations()


@_api
def LGBM_BoosterNumModelPerIteration(handle, out_tree_per_iteration: Ref):
    out_tree_per_iteration.value = _get(handle, _BoosterEntry).gbdt.num_model


@_api
def LGBM_BoosterNumberOfTotalModel(handle, out_models: Ref):
    out_models.value = len(_get(handle, _BoosterEntry).gbdt.models)


@_api
def LGBM_BoosterGetEvalCounts(handle, out_len: Ref):
    b = _get(handle, _BoosterEntry)
    out_len.value = len(b.gbdt.train_metrics)


@_api
def LGBM_BoosterGetEvalNames(handle, out_len: Ref, out_strs: Ref):
    b = _get(handle, _BoosterEntry)
    names = [m.name for m in b.gbdt.train_metrics]
    out_strs.value = names
    out_len.value = len(names)


@_api
def LGBM_BoosterGetEval(handle, data_idx, out_len: Ref, out_results):
    """data_idx 0 = training data, >=1 = validation sets (c_api.cpp)."""
    b = _get(handle, _BoosterEntry)
    if data_idx == 0:
        res = b.gbdt.eval_train()
    else:
        allv = b.gbdt.eval_valid()
        name = f"valid_{data_idx - 1}"
        res = [r for r in allv if r[0] == name]
    vals = [v for (_, _, v, _) in res]
    out_results[:len(vals)] = vals
    out_len.value = len(vals)


def _num_preds(gbdt, nrow, predict_type, num_iteration):
    total_iter = gbdt.num_iterations()
    it = total_iter if num_iteration <= 0 else min(num_iteration,
                                                   total_iter)
    if predict_type == C_API_PREDICT_LEAF_INDEX:
        return nrow * gbdt.num_model * it
    if predict_type == C_API_PREDICT_CONTRIB:
        return nrow * gbdt.num_model * (gbdt.max_feature_idx + 2)
    return nrow * gbdt.num_model


@_api
def LGBM_BoosterCalcNumPredict(handle, num_row, predict_type,
                               num_iteration, out_len: Ref):
    b = _get(handle, _BoosterEntry)
    out_len.value = _num_preds(b.gbdt, num_row, predict_type,
                               num_iteration)


def _predict_dense(gbdt, mat, predict_type, num_iteration, out_len: Ref,
                   out_result):
    if predict_type == C_API_PREDICT_LEAF_INDEX:
        res = gbdt.predict(mat, num_iteration=num_iteration,
                           pred_leaf=True)
    elif predict_type == C_API_PREDICT_CONTRIB:
        res = gbdt.predict(mat, num_iteration=num_iteration,
                           pred_contrib=True)
    elif predict_type == C_API_PREDICT_RAW_SCORE:
        res = gbdt.predict(mat, num_iteration=num_iteration,
                           raw_score=True)
    else:
        res = gbdt.predict(mat, num_iteration=num_iteration)
    flat = np.asarray(res, np.float64).reshape(-1)
    out_result[:len(flat)] = flat
    out_len.value = len(flat)


@_api
def LGBM_BoosterPredictForMat(handle, data, data_type, nrow, ncol,
                              is_row_major, predict_type, num_iteration,
                              parameter, out_len: Ref, out_result):
    b = _get(handle, _BoosterEntry)
    data = _check_array(data, "data", data_type,
                        (C_API_DTYPE_FLOAT32, C_API_DTYPE_FLOAT64))
    mat = np.asarray(data).reshape(
        (nrow, ncol) if is_row_major else (ncol, nrow))
    if not is_row_major:
        mat = mat.T
    _predict_dense(b.gbdt, np.asarray(mat, np.float64), predict_type,
                   num_iteration, out_len, out_result)


def _densify_csr(indptr, indptr_type, indices, data, data_type, nindptr,
                 num_col) -> np.ndarray:
    indptr = _check_array(indptr, "indptr", indptr_type,
                          (C_API_DTYPE_INT32, C_API_DTYPE_INT64))
    data = _check_array(data, "data", data_type,
                        (C_API_DTYPE_FLOAT32, C_API_DTYPE_FLOAT64))
    indices = np.asarray(indices, np.int32)
    nrow = int(nindptr) - 1
    mat = np.zeros((nrow, int(num_col)), np.float64)
    counts = np.diff(np.asarray(indptr[:nrow + 1], np.int64))
    rows = np.repeat(np.arange(nrow, dtype=np.int64), counts)
    nnz = len(rows)
    mat[rows, indices[:nnz]] = np.asarray(data[:nnz], np.float64)
    return mat


@_api
def LGBM_BoosterPredictForCSR(handle, indptr, indptr_type, indices, data,
                              data_type, nindptr, nelem, num_col,
                              predict_type, num_iteration, parameter,
                              out_len: Ref, out_result):
    b = _get(handle, _BoosterEntry)
    mat = _densify_csr(indptr, indptr_type, indices, data, data_type,
                       nindptr, num_col)
    _predict_dense(b.gbdt, mat, predict_type, num_iteration, out_len,
                   out_result)


@_api
def LGBM_BoosterSaveModel(handle, start_iteration, num_iteration,
                          filename):
    _get(handle, _BoosterEntry).gbdt.save_model_to_file(
        str(filename), start_iteration, num_iteration)


@_api
def LGBM_BoosterSaveModelToString(handle, start_iteration, num_iteration,
                                  buffer_len, out_len: Ref, out_str: Ref):
    s = _get(handle, _BoosterEntry).gbdt.model_to_string(
        start_iteration, num_iteration)
    out_str.value = s
    out_len.value = len(s) + 1


@_api
def LGBM_BoosterDumpModel(handle, start_iteration, num_iteration,
                          buffer_len, out_len: Ref, out_str: Ref):
    b = _get(handle, _BoosterEntry)
    b.gbdt._flush_pending()
    dump = {
        "name": "tree",
        "version": "v2",
        "num_class": max(int(b.gbdt.config.num_class), 1),
        "num_tree_per_iteration": b.gbdt.num_model,
        "label_index": 0,
        "max_feature_idx": b.gbdt.max_feature_idx,
        "feature_names": list(b.gbdt.feature_names),
        "tree_info": [t.to_json() for t in b.gbdt.models],
    }
    s = json.dumps(dump)
    out_str.value = s
    out_len.value = len(s) + 1


@_api
def LGBM_BoosterFeatureImportance(handle, num_iteration, importance_type,
                                  out_results):
    b = _get(handle, _BoosterEntry)
    imp = b.gbdt.feature_importance(
        "split" if importance_type == 0 else "gain", num_iteration)
    out_results[:len(imp)] = imp


# ---------------------------------------------------------------------------
# Prediction-server functions (lightgbm_tpu extension, not in the
# reference ABI): a hot-swap packed-ensemble predictor behind an opaque
# handle, so the windowed harness scores every request against the
# CURRENT model and atomically replaces it after each retrain
# (docs/Serving.md).
# ---------------------------------------------------------------------------


@_api
def LGBM_ServeCreate(booster_handle, parameters, out: Ref):
    """Create a PredictionServer seeded from a booster.  Recognized
    parameters: ``num_iteration_predict`` (served tree slice) and the
    pass-through extras ``serve_max_batch`` / ``serve_max_wait_ms``
    (micro-batching queue configuration)."""
    b = _get(booster_handle, _BoosterEntry)
    cfg = _parse_params(parameters)
    from .config import resolve_alias
    from .serve import PredictionServer
    # only an EXPLICIT device_predict_min_rows overrides the server's
    # adopt-from-booster default (the schema default would mask it)
    explicit = {resolve_alias(k) for k in _tokenize_params(parameters)}
    min_rows = (int(cfg.device_predict_min_rows)
                if "device_predict_min_rows" in explicit else None)
    server = PredictionServer(
        b.gbdt,
        num_iteration=int(getattr(cfg, "num_iteration_predict", -1)),
        max_batch=int(cfg.extra.get("serve_max_batch", 8192)),
        max_wait_ms=float(cfg.extra.get("serve_max_wait_ms", 2.0)),
        device_predict_min_rows=min_rows)
    out.value = _register(_ServeEntry(server))


@_api
def LGBM_ServeSwap(serve_handle, booster_handle):
    """Atomically point the server at ``booster_handle``'s current
    model (the retrain-window hand-off)."""
    s = _get(serve_handle, _ServeEntry)
    b = _get(booster_handle, _BoosterEntry)
    s.server.swap(b.gbdt)


@_api
def LGBM_ServeCalcNumPredict(serve_handle, num_row, out_len: Ref):
    s = _get(serve_handle, _ServeEntry)
    out_len.value = int(num_row) * s.server.packed.num_model


@_api
def LGBM_ServePredictForCSR(serve_handle, indptr, indptr_type, indices,
                            data, data_type, nindptr, nelem, num_col,
                            predict_type, out_len: Ref, out_result):
    """Score CSR rows against the server's CURRENT model in one packed
    device dispatch.  Supports NORMAL and RAW_SCORE predict types."""
    s = _get(serve_handle, _ServeEntry)
    if predict_type not in (C_API_PREDICT_NORMAL,
                            C_API_PREDICT_RAW_SCORE):
        raise LightGBMError("LGBM_ServePredictForCSR supports NORMAL "
                            "and RAW_SCORE predict types only")
    mat = _densify_csr(indptr, indptr_type, indices, data, data_type,
                       nindptr, num_col)
    res = s.server.predict(
        mat, raw_score=(predict_type == C_API_PREDICT_RAW_SCORE))
    flat = np.asarray(res, np.float64).reshape(-1)
    out_result[:len(flat)] = flat
    out_len.value = len(flat)


@_api
def LGBM_ServeFree(serve_handle):
    _get(serve_handle, _ServeEntry).server.stop()
    _unregister(serve_handle)


# ---------------------------------------------------------------------------
# Model-fleet functions (lightgbm_tpu extension, not in the reference
# ABI): M tenants stacked into one packed array family behind an opaque
# handle — one jitted program serves any (tenant_ids, rows) batch, a
# tenant retrain hands off via a zero-retrace device index write
# (docs/Serving.md "Model fleets").
# ---------------------------------------------------------------------------


@_api
def LGBM_FleetCreate(booster_handle, num_tenants, parameters, out: Ref):
    """Create a FleetServer with ``num_tenants`` tenants, all seeded
    from ``booster_handle``'s current model (specialize them afterwards
    with LGBM_FleetSwapTenant).  Recognized parameters:
    ``num_iteration_predict`` (served slice), ``serve_replicas``,
    ``fleet_value_dtype`` and the pass-through extras
    ``serve_max_batch`` / ``serve_max_wait_ms``."""
    b = _get(booster_handle, _BoosterEntry)
    cfg = _parse_params(parameters)
    from .serve import FleetServer
    m = int(num_tenants)
    if m < 1:
        raise LightGBMError(f"num_tenants must be >= 1, got {m}")
    server = FleetServer(
        [b.gbdt] * m,
        num_iteration=int(getattr(cfg, "num_iteration_predict", -1)),
        replicas=int(getattr(cfg, "serve_replicas", 1)),
        value_dtype=str(getattr(cfg, "fleet_value_dtype", "f32")),
        max_batch=int(cfg.extra.get("serve_max_batch", 8192)),
        max_wait_ms=float(cfg.extra.get("serve_max_wait_ms", 2.0)))
    out.value = _register(_FleetEntry(server))


@_api
def LGBM_FleetSwapTenant(fleet_handle, tenant_id, booster_handle):
    """Atomically point ONE tenant at ``booster_handle``'s current
    model (the per-tenant retrain-window hand-off); the other tenants
    keep serving throughout."""
    f = _get(fleet_handle, _FleetEntry)
    b = _get(booster_handle, _BoosterEntry)
    f.server.swap_tenant(int(tenant_id), b.gbdt)


@_api
def LGBM_FleetCalcNumPredict(fleet_handle, num_row, out_len: Ref):
    f = _get(fleet_handle, _FleetEntry)
    out_len.value = int(num_row) * f.server.fleet.num_model


@_api
def LGBM_FleetPredictForCSR(fleet_handle, tenant_ids, num_tenant_ids,
                            indptr, indptr_type, indices, data,
                            data_type, nindptr, nelem, num_col,
                            predict_type, out_len: Ref, out_result):
    """Score CSR rows against the fleet in one packed device dispatch.
    ``tenant_ids`` is an int32 array routing each row to its tenant;
    ``num_tenant_ids == 1`` broadcasts one tenant to the whole batch.
    Supports NORMAL and RAW_SCORE predict types."""
    f = _get(fleet_handle, _FleetEntry)
    if predict_type not in (C_API_PREDICT_NORMAL,
                            C_API_PREDICT_RAW_SCORE):
        raise LightGBMError("LGBM_FleetPredictForCSR supports NORMAL "
                            "and RAW_SCORE predict types only")
    tids = np.asarray(tenant_ids, np.int32).reshape(-1)
    n_ids = int(num_tenant_ids)
    tids = tids[:n_ids] if n_ids > 1 else int(tids[0])
    mat = _densify_csr(indptr, indptr_type, indices, data, data_type,
                       nindptr, num_col)
    res = f.server.predict(
        tids, mat, raw_score=(predict_type == C_API_PREDICT_RAW_SCORE))
    flat = np.asarray(res, np.float64).reshape(-1)
    out_result[:len(flat)] = flat
    out_len.value = len(flat)


@_api
def LGBM_FleetFree(fleet_handle):
    _get(fleet_handle, _FleetEntry).server.stop()
    _unregister(fleet_handle)


# ---------------------------------------------------------------------------
# AOT warmup functions (lightgbm_tpu extension, not in the reference
# ABI): precompile a deployment's declared (rows, features, config)
# program families into the persistent XLA compile cache
# (docs/ColdStart.md) so the first real retrain window / first large
# predict batch runs warm.  The harness calls these once at container
# start, before the request loop.
# ---------------------------------------------------------------------------


@_api
def LGBM_WarmupTrain(parameters, num_row, num_feature,
                     out_num_compiled: Ref):
    """Drive the real training path on a synthetic (num_row,
    num_feature) dataset long enough to compile every program a
    production run with ``parameters`` dispatches (one fused chunk +
    any per-iteration remainder).  ``parameters`` should include
    ``compile_cache_dir`` (or export LGBM_TPU_COMPILE_CACHE) plus the
    production training params.  Returns the number of fresh
    persistent-cache entries written (0 = already warm)."""
    from .warmup import warmup_train
    cfg = _parse_params(parameters)
    report = warmup_train(int(num_row), int(num_feature), config=cfg)
    out_num_compiled.value = int(report["cache_misses"])


@_api
def LGBM_WarmupServe(parameters, num_row, num_feature,
                     out_num_compiled: Ref):
    """Precompile the packed-forest traversal family for the declared
    serving deployment (``num_iterations``/``num_leaves``/``num_class``
    from ``parameters``; every realizable depth pad).  ``num_row`` <= 0
    warms the PredictionServer default buckets (128/1024/8192 + the
    ``device_predict_min_rows`` bucket)."""
    from .warmup import warmup_serve
    cfg = _parse_params(parameters)
    rows = [int(num_row)] if int(num_row) > 0 else []
    report = warmup_serve(rows, int(num_feature), config=cfg)
    out_num_compiled.value = int(report["cache_misses"])


# ---------------------------------------------------------------------------
# Network functions (c_api.h:799-815)
# ---------------------------------------------------------------------------

_network_conf = {"num_machines": 1, "rank": 0}
_NETWORK_LOCK = threading.Lock()


@_api
def LGBM_NetworkInit(machines, local_listen_port, listen_time_out,
                     num_machines):
    """Single-controller JAX owns process wiring (SURVEY §2.4: socket/MPI
    linkers are subsumed by ICI/`jax.distributed`); this records the
    topology request so ported clients keep working and multi-host
    configs route through `parallel.network`."""
    with _NETWORK_LOCK:
        _network_conf["num_machines"] = int(num_machines)
        _network_conf["rank"] = 0


@_api
def LGBM_NetworkFree():
    with _NETWORK_LOCK:
        _network_conf["num_machines"] = 1
        _network_conf["rank"] = 0
