"""Feature-parallel tree learner (reference
``src/treelearner/feature_parallel_tree_learner.cpp``).

Every worker holds ALL rows (data replicated); the feature *search* is
sharded: the group axis of the binned matrix is sliced per device, each
device builds histograms and scans thresholds only for its own feature
groups, and the single communication per leaf is an allreduce-max of the
13-float packed split record keyed lexicographically by (gain, -feature)
— the TPU mapping of ``SyncUpGlobalBestSplit``
(``parallel_tree_learner.h:183-207``, call at
``feature_parallel_tree_learner.cpp:63``).  Because data is replicated, the
partition then proceeds identically on every device with no split
broadcast, exactly like the reference (``feature_parallel_tree_learner.cpp:
31-74``).

Shard layout: groups are assigned as contiguous slices of the group axis
(the reference rebalances by bin count per tree,
``feature_parallel_tree_learner.cpp:31-50``; contiguous slices keep XLA
slicing static — group sizes are already balanced to <=256 bins by EFB).
Per-device feature metadata lives in stacked (D, Fmax, ...) arrays sharded
over the mesh axis, with -1 padding for devices owning fewer features.
"""

from __future__ import annotations

import functools
import math
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import obs
from ..ops.histogram import _histogram_scan, num_chunks_for
from ..ops.split import (F_FEATURE, F_GAIN, FeatureMeta,
                         find_best_split_impl)
from ..tree.learner import SerialTreeLearner, _LeafInfo
from .network import Network


class FeatureParallelTreeLearner(SerialTreeLearner):
    """Features sharded over the mesh axis; split records allreduced."""

    def __init__(self, config, dataset, network: Network):
        super().__init__(config, dataset)
        self.net = network
        d = network.num_machines
        g = dataset.num_groups
        self.g_loc = max(int(math.ceil(g / d)), 1)
        g_pad = d * self.g_loc
        cols = np.asarray(dataset.binned)
        if g_pad > g:
            cols = np.pad(cols, ((0, 0), (0, g_pad - g)))
        # replicated: every worker holds all rows of all groups (the hist
        # kernel slices its own columns); self.binned (serial) drives the
        # replicated partition
        self._binned_cols = network.replicate(jnp.asarray(cols))

        f_group = np.asarray(dataset.f_group)
        dev_feats = [np.nonzero((f_group >= w * self.g_loc)
                                & (f_group < (w + 1) * self.g_loc))[0]
                     for w in range(d)]
        f_max = max(max((len(a) for a in dev_feats), default=1), 1)
        metas = []
        for w in range(d):
            subset = np.full(f_max, -1, np.int64)
            subset[:len(dev_feats[w])] = dev_feats[w]
            metas.append(FeatureMeta.from_dataset(
                dataset, subset, slot_base=w * self.g_loc * 256))
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *metas)
        spec = lambda a: P(network.axis, *([None] * (a.ndim - 1)))
        self._meta_sh = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(network.mesh,
                                                      spec(a))), stacked)
        self._rep = P()
        self._hist_fns: Dict = {}
        self._fb_fn = None

    # ------------------------------------------------------------------
    def _hist_fn(self, m: int):
        if m in self._hist_fns:
            return self._hist_fns[m]
        net, g_loc = self.net, self.g_loc
        n_rows = int(self._binned_cols.shape[0])
        num_chunks = num_chunks_for(m)

        def _hist(binned_cols, grad, hess, buffer, begin, start, count):
            w = jax.lax.axis_index(net.axis)
            cols = jax.lax.dynamic_slice(
                binned_cols, (0, w * g_loc), (n_rows, g_loc))
            win = jax.lax.dynamic_slice(buffer, (begin,), (m,))
            pos = jnp.arange(m, dtype=jnp.int32)
            valid = (pos >= start) & (pos < start + count)
            idx = jnp.where(valid, win, 0)
            bins = cols[idx]                               # (M, g_loc)
            vf = valid.astype(jnp.float32)
            gh = jnp.stack([grad[idx] * vf, hess[idx] * vf, vf], axis=1)
            return _histogram_scan(bins, gh, num_chunks)   # (g_loc,256,3)

        _hist = obs.track_jit(f"fp.hist_m{m}", jax.jit(net.run_sharded(
            _hist, (self._rep,) * 7, P(net.axis))))
        self._hist_fns[m] = _hist
        return _hist

    def _leaf_histogram(self, grad, hess, info: _LeafInfo):
        b, m, start = self._window(info.begin, info.count)
        fn = self._hist_fn(m)
        i32 = lambda v: jnp.asarray(v, jnp.int32)
        # out: (D*g_loc, 256, 3) sharded over groups
        return fn(self._binned_cols, grad, hess, self.buffer, i32(b),
                  i32(start), i32(info.count))

    def _leaf_totals(self, hist) -> np.ndarray:
        # group 0 is real on every dataset; its slots live on device 0
        return np.asarray(hist[0].sum(axis=0), np.float64)

    # ------------------------------------------------------------------
    def _find_best(self, info: _LeafInfo, feature_mask):
        if self._fb_fn is None:
            net = self.net
            nf = self.ctx.num_features
            has_cat = self.ctx.has_categorical
            meta_specs = jax.tree_util.tree_map(
                lambda a: P(net.axis, *([None] * (a.ndim - 1))),
                self._meta_sh)

            def _fb(hist_sh, total, constraint, fmask, meta2, hp):
                meta = jax.tree_util.tree_map(lambda a: a[0], meta2)
                flat = hist_sh.reshape(-1, 3)
                gid = meta.global_id
                mask_l = jnp.where(
                    gid >= 0, fmask[jnp.clip(gid, 0, nf - 1)], False)
                packed, cat = find_best_split_impl(
                    flat, total, constraint, mask_l, meta, hp, has_cat)
                # SyncUpGlobalBestSplit: max gain, ties to the smaller
                # global feature id (the serial argmax order)
                gain = packed[F_GAIN]
                fid = packed[F_FEATURE].astype(jnp.int32)
                gmax = net.allreduce_max(gain)
                is_max = gain == gmax
                tid = jnp.where(is_max, fid, jnp.iinfo(jnp.int32).max)
                tmin = net.allreduce_min(tid)
                owner = is_max & (fid == tmin)
                # select via where, NOT multiply: non-owner shards may carry
                # inf outputs (0/0 leaf math on masked features) and
                # inf * 0 = NaN would poison the psum
                packed_g = net.allreduce(
                    jnp.where(owner, packed, 0.0))
                cat_g = net.allreduce(
                    jnp.where(owner, cat.astype(jnp.float32), 0.0))
                return packed_g, cat_g > 0.5

            self._fb_fn = obs.track_jit("fp.find_best", jax.jit(
                net.run_sharded(
                    _fb,
                    (P(net.axis), self._rep, self._rep, self._rep,
                     meta_specs, self._rep),
                    (self._rep, self._rep))))
        return self._fb_fn(info.hist,
                           jnp.asarray(info.total, jnp.float32),
                           jnp.asarray((info.cmin, info.cmax), jnp.float32),
                           feature_mask, self._meta_sh, self.ctx.hyper)
