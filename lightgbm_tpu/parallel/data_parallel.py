"""Data-parallel tree learner (reference
``src/treelearner/data_parallel_tree_learner.cpp``).

On TPU the row dimension shards over a mesh axis; local histograms are
psum-reduced so every device sees global histograms (the analog of the
reference's ReduceScatter of packed histogram buffers,
data_parallel_tree_learner.cpp:147-162).  Single-process multi-device is
exercised on the CPU mesh in tests; real pods use the same code over ICI.
"""

from __future__ import annotations

from ..tree.learner import SerialTreeLearner


def maybe_sharded_learner(config, dataset):
    """Serial learner today; hook point for auto row-sharding over a mesh
    when one is configured (tpu_num_devices / an active global mesh)."""
    return SerialTreeLearner(config, dataset)


class DataParallelTreeLearner(SerialTreeLearner):
    """Placeholder: rows sharded across workers, histogram psum.

    Full multi-host implementation lands with the parallel milestone; the
    single-device semantics are identical (global histograms -> identical
    splits), so this degrades to the serial learner meanwhile.
    """
    pass
