"""Data-parallel tree learner (reference
``src/treelearner/data_parallel_tree_learner.cpp``).

Rows are sharded contiguously over the one-axis device mesh; every split
step each device builds the histogram of its local rows for ALL features
and the shards are ``psum``-reduced so every device sees the GLOBAL
histogram (the analog of the reference's ReduceScatter of packed histogram
buffers + per-rank aggregation, ``data_parallel_tree_learner.cpp:147-162``
— on TPU the allreduce rides ICI, and split finding is cheap enough to
replicate instead of scattering feature ownership).  Split finding then
uses global counts exactly as the serial learner, so data-parallel trees
are bit-identical to serial trees on the same data
(``FindBestSplitsFromHistograms`` with ``GLOBAL_data_count``,
``data_parallel_tree_learner.cpp:165-246``).

Per-device partition state lives in sharded arrays driven through
``shard_map``: an index buffer (the local row permutation) plus per-leaf
``(begin, count)`` tables, because each device's local leaf sizes differ —
only the GLOBAL counts (carried by the SplitInfo record) are known on host.
The histogram subtraction trick operates on the psum-reduced global
histograms, so the comm volume is one (G, 256, 3) allreduce per split — the
same O(total_bins) the reference moves, with the smaller-child optimisation
intact.

Single-process multi-device is exercised on the 8-device CPU mesh in tests;
the same code runs over ICI on a real pod (devices from ``jax.devices()``),
and under multi-controller ``jax.distributed`` for multi-host.
"""

from __future__ import annotations

import functools
import math
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import obs
from ..ops.histogram import (_gather_rows, _histogram_scan, bucket_size,
                             num_chunks_for)
from ..ops.partition import _partition_kernel
from ..tree.learner import SerialTreeLearner, SplitParams, _LeafInfo
from .network import Network


class DataParallelTreeLearner(SerialTreeLearner):
    """Rows sharded over the mesh axis; histograms psum-reduced."""

    def __init__(self, config, dataset, network: Network):
        super().__init__(config, dataset)
        self.net = network
        d = network.num_machines
        n = dataset.num_data
        # per-device row block: power-of-two so leaf windows bucket cleanly
        self.n_loc = bucket_size(max(int(math.ceil(n / d)), 1))
        self.n_shards = d
        n_pad_total = d * self.n_loc
        binned_np = np.asarray(dataset.binned)
        pad_rows = n_pad_total - n
        if pad_rows > 0:
            binned_np = np.pad(binned_np, ((0, pad_rows), (0, 0)))
        # each device owns global rows [w*n_loc, w*n_loc + n_valid[w])
        self.n_valid = np.clip(n - np.arange(d) * self.n_loc, 0,
                               self.n_loc).astype(np.int32)
        self.binned = network.shard_rows(jnp.asarray(binned_np))
        self._row_spec = P(network.axis)
        self._row2d_spec = P(network.axis, None)
        self._rep_spec = P()
        base_buf = np.tile(np.arange(self.n_loc, dtype=np.int32), d)
        self._full_buffer = network.shard_rows(jnp.asarray(base_buf))
        self._n_valid_dev = network.shard_rows(jnp.asarray(self.n_valid))
        self._hist_fns: Dict = {}
        self._part_fns: Dict = {}
        self._bag_fn = None
        self._addend_fn = None
        self._traverse_binned = None
        self._num_leaves = int(config.num_leaves)

    @property
    def traverse_binned(self):
        """Replicated (N, G) matrix for full-traversal score paths (OOB
        updates, rollback); built lazily — the sharded copy is the hot
        path."""
        if self._traverse_binned is None:
            self._traverse_binned = jnp.asarray(self.dataset.binned)
        return self._traverse_binned

    # ------------------------------------------------------------------
    def _pad_rows(self, x):
        """(N,) replicated -> (D*n_loc,) row-sharded."""
        n_pad_total = self.n_shards * self.n_loc
        if x.shape[0] != n_pad_total:
            x = jnp.pad(x, (0, n_pad_total - x.shape[0]))
        return jax.device_put(x, NamedSharding(self.net.mesh,
                                               self._row_spec))

    # ------------------------------------------------------------------
    def bagging_state(self, seed: int, fraction: float):
        """Per-device bernoulli selection (the reference applies bagging to
        rank-local rows, gbdt.cpp:161-243 under num_machines>1)."""
        if self._bag_fn is None:
            net = self.net
            n_loc = self.n_loc

            def _bag(key, n_valid, frac):
                w = jax.lax.axis_index(net.axis)
                k = jax.random.fold_in(key, w)
                pos = jnp.arange(n_loc, dtype=jnp.int32)
                valid = pos < n_valid[0]
                u = jax.random.uniform(k, (n_loc,))
                selected = valid & (u < frac)
                sort_key = jnp.where(selected, 0, jnp.where(valid, 1, 2))
                order = jnp.argsort(sort_key.astype(jnp.int32), stable=True)
                return order.astype(jnp.int32), \
                    jnp.broadcast_to(selected.sum().astype(jnp.int32), (1,))

            self._bag_fn = obs.track_jit("dp.bagging", jax.jit(
                net.run_sharded(
                    _bag,
                    (self._rep_spec, self._row_spec, self._rep_spec),
                    (self._row_spec, self._row_spec))))
        buf, counts = self._bag_fn(jax.random.PRNGKey(seed),
                                   self._n_valid_dev,
                                   jnp.asarray(fraction, jnp.float32))
        counts_np = np.asarray(counts)
        return (buf, counts_np), int(counts_np.sum())

    def goss_state(self, seed: int, score_abs, top_rate: float,
                   other_rate: float):
        """Rank-local GOSS: each shard takes its own top |g*h| rows and
        samples the rest with its own counts, matching the reference's
        GOSS over rank-local rows (goss.hpp:88-133 with pre-partitioned
        data).  Returns the (buffer, counts) state the DP ``_init_state``
        consumes, the global selected count, and the (N,) multiplier."""
        if getattr(self, "_goss_fn", None) is None:
            net = self.net
            n_loc = self.n_loc

            def _goss(key, score, n_valid, top_rate, other_rate):
                w = jax.lax.axis_index(net.axis)
                k = jax.random.fold_in(key, w)
                nv = n_valid[0]
                pos = jnp.arange(n_loc, dtype=jnp.int32)
                valid = pos < nv
                scores = jnp.where(valid, score, -jnp.inf)
                top_k = jnp.maximum(
                    (nv.astype(jnp.float32) * top_rate).astype(jnp.int32),
                    1)
                other_k = jnp.maximum(
                    (nv.astype(jnp.float32) * other_rate).astype(jnp.int32),
                    1)
                sorted_desc = jnp.sort(scores)[::-1]
                threshold = sorted_desc[jnp.clip(top_k - 1, 0, n_loc - 1)]
                is_top = valid & (score >= threshold)
                rest = valid & ~is_top
                n_rest = jnp.maximum(rest.sum(), 1)
                prob = other_k.astype(jnp.float32) \
                    / n_rest.astype(jnp.float32)
                u = jax.random.uniform(k, (n_loc,))
                sampled = rest & (u < prob)
                selected = is_top | sampled
                mult = jnp.where(
                    sampled,
                    (nv - top_k).astype(jnp.float32)
                    / other_k.astype(jnp.float32), 1.0)
                sort_key = jnp.where(selected, 0, jnp.where(valid, 1, 2))
                order = jnp.argsort(sort_key.astype(jnp.int32), stable=True)
                return (order.astype(jnp.int32),
                        jnp.broadcast_to(
                            selected.sum().astype(jnp.int32), (1,)),
                        mult)

            self._goss_fn = obs.track_jit("dp.goss", jax.jit(
                net.run_sharded(
                    _goss,
                    (self._rep_spec, self._row_spec, self._row_spec,
                     self._rep_spec, self._rep_spec),
                    (self._row_spec, self._row_spec, self._row_spec))))
        score_pad = self._pad_rows(jnp.asarray(score_abs, jnp.float32))
        buf, counts, mult = self._goss_fn(
            jax.random.PRNGKey(seed), score_pad, self._n_valid_dev,
            jnp.asarray(top_rate, jnp.float32),
            jnp.asarray(other_rate, jnp.float32))
        counts_np = np.asarray(counts)
        return ((buf, counts_np), int(counts_np.sum()),
                jnp.asarray(mult)[:self.num_data])

    def _init_state(self, indices_buffer, data_count, grad, hess):
        if indices_buffer is None:
            buffer = self._full_buffer
            counts = self.n_valid
            data_count = self.num_data
        else:
            buffer, counts = indices_buffer
            counts = np.asarray(counts)
        # no copy needed: the DP partition path is functional (no donation),
        # so the caller's bagging buffer is never mutated
        self.buffer = buffer
        self.data_count = int(data_count)
        d, L = self.n_shards, self._num_leaves
        lb = np.zeros((d, L), np.int32)
        lc = np.zeros((d, L), np.int32)
        lc[:, 0] = counts
        sh2 = NamedSharding(self.net.mesh, self._row2d_spec)
        self.leaf_begin = jax.device_put(jnp.asarray(lb), sh2)
        self.leaf_count = jax.device_put(jnp.asarray(lc), sh2)
        return self._pad_rows(grad), self._pad_rows(hess)

    # ------------------------------------------------------------------
    def _window_m(self, global_count: int) -> int:
        """Static per-device window size: local count <= global count and
        <= n_loc, so this covers every shard with one compiled program."""
        return min(bucket_size(max(int(global_count), 1)), self.n_loc)

    def _hist_fn(self, m: int):
        if m in self._hist_fns:
            return self._hist_fns[m]
        net, n_loc = self.net, self.n_loc
        num_chunks = num_chunks_for(m)

        def _hist(binned, grad, hess, buffer, lb, lc, leaf):
            begin = lb[0, leaf]
            count = lc[0, leaf]
            b = jnp.clip(begin, 0, n_loc - m)
            start = begin - b
            win = jax.lax.dynamic_slice(buffer, (b,), (m,))
            bins, gh = _gather_rows(binned, grad, hess, win, start, count)
            h = _histogram_scan(bins, gh, num_chunks)
            # the one collective per split: global histogram over ICI
            return net.allreduce(h)

        _hist = obs.track_jit(f"dp.hist_m{m}", jax.jit(net.run_sharded(
            _hist,
            (self._row2d_spec, self._row_spec, self._row_spec,
             self._row_spec, self._row2d_spec, self._row2d_spec,
             self._rep_spec),
            self._rep_spec)))
        self._hist_fns[m] = _hist
        return _hist

    def _leaf_histogram(self, grad, hess, info: _LeafInfo):
        m = self._window_m(info.count)
        fn = self._hist_fn(m)
        return fn(self.binned, grad, hess, self.buffer, self.leaf_begin,
                  self.leaf_count, jnp.asarray(info.leaf_id, jnp.int32))

    def _part_fn(self, m: int):
        if m in self._part_fns:
            return self._part_fns[m]
        net, n_loc = self.net, self.n_loc
        specs = self._row2d_spec, self._row_spec, self._row2d_spec, \
            self._row2d_spec
        rep = (self._rep_spec,) * 12

        def _part(binned, buffer, lb2, lc2, leaf, right_leaf, group, offset,
                  width, default_bin, num_bin, missing, threshold,
                  default_left, is_cat, cat_member):
            lb, lc = lb2[0], lc2[0]
            begin = lb[leaf]
            count = lc[leaf]
            b = jnp.clip(begin, 0, n_loc - m)
            start = begin - b
            win = jax.lax.dynamic_slice(buffer, (b,), (m,))
            new_win, left_cnt = _partition_kernel(
                binned, win, start, count, group, offset, width, default_bin,
                num_bin, missing, threshold, default_left, is_cat, cat_member)
            buffer = jax.lax.dynamic_update_slice(buffer, new_win, (b,))
            lb = lb.at[right_leaf].set(begin + left_cnt)
            lc = lc.at[right_leaf].set(count - left_cnt)
            lc = lc.at[leaf].set(left_cnt)
            return buffer, lb[None], lc[None]

        _part = obs.track_jit(f"dp.partition_m{m}", jax.jit(
            net.run_sharded(
                _part, specs + rep,
                (self._row_spec, self._row2d_spec, self._row2d_spec))))
        self._part_fns[m] = _part
        return _part

    def _partition(self, info: _LeafInfo, sp: SplitParams, left_count: int,
                   right_count: int, right_leaf: int):
        m = self._window_m(info.count)
        fn = self._part_fn(m)
        i32 = lambda v: jnp.asarray(v, jnp.int32)
        self.buffer, self.leaf_begin, self.leaf_count = fn(
            self.binned, self.buffer, self.leaf_begin, self.leaf_count,
            i32(info.leaf_id), i32(right_leaf), i32(sp.group), i32(sp.offset),
            i32(sp.width), i32(sp.default_bin), i32(sp.num_bin),
            i32(sp.missing), i32(sp.threshold),
            jnp.asarray(sp.default_left), jnp.asarray(sp.is_cat),
            jnp.asarray(sp.cat_member))

    # ------------------------------------------------------------------
    def update_score(self, score, tree, multiplier: float = 1.0):
        """Per-device leaf-region scatter into a row-sharded addend, then a
        single add into the replicated score vector.

        NOTE: the leaf-id list must have a static length for the jit cache;
        pad with repeats of the first id (zero-extra effect: duplicated
        regions resolve to the same values)."""
        if self._addend_fn is None:
            net, n_loc = self.net, self.n_loc

            def _addend(buffer, lb2, lc2, ids, vals, n_real):
                lb, lc = lb2[0], lc2[0]
                begins = lb[ids]
                counts = lc[ids]
                is_real = jnp.arange(ids.shape[0]) < n_real
                # lexicographic sort by (begin, count) via two stable
                # passes: zero-count leaves order before the real region
                # starting at the same position; padded duplicates share
                # the real entry's key and value
                ord1 = jnp.argsort(counts, stable=True)
                order = ord1[jnp.argsort(begins[ord1], stable=True)]
                sb = begins[order]
                sv = vals[order]
                pos = jnp.arange(n_loc, dtype=jnp.int32)
                which = jnp.searchsorted(sb, pos, side="right") - 1
                valid_count = jnp.where(is_real, counts, 0).sum()
                addend_pos = jnp.where(pos < valid_count, sv[which], 0.0)
                out = jnp.zeros(n_loc, jnp.float32)
                return out.at[buffer].add(addend_pos)

            self._addend_fn = obs.track_jit("dp.score_addend", jax.jit(
                net.run_sharded(
                    _addend,
                    (self._row_spec, self._row2d_spec, self._row2d_spec,
                     self._rep_spec, self._rep_spec, self._rep_spec),
                    self._row_spec)))
        ids = sorted(self.leaves)
        pad_to = self._num_leaves
        ids_np = np.asarray(ids + [ids[0]] * (pad_to - len(ids)), np.int32)
        vals_np = np.asarray(
            [tree.leaf_value[l] * multiplier for l in ids]
            + [tree.leaf_value[ids[0]] * multiplier] * (pad_to - len(ids)),
            np.float32)
        addend = self._addend_fn(self.buffer, self.leaf_begin,
                                 self.leaf_count, jnp.asarray(ids_np),
                                 jnp.asarray(vals_np),
                                 jnp.asarray(len(ids), jnp.int32))
        return score + addend[:self.num_data]

    def leaf_indices_host(self) -> Dict[int, np.ndarray]:
        buf = np.asarray(self.buffer).reshape(self.n_shards, self.n_loc)
        lb = np.asarray(self.leaf_begin)
        lc = np.asarray(self.leaf_count)
        out = {}
        for leaf in self.leaves:
            parts = [self.n_loc * w + buf[w, lb[w, leaf]:lb[w, leaf]
                                          + lc[w, leaf]]
                     for w in range(self.n_shards)]
            out[leaf] = np.concatenate(parts) if parts else \
                np.empty(0, np.int64)
        return out
