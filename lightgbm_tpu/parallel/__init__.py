"""Distributed tree learners + collective verbs.

Factory mirrors ``TreeLearner::CreateTreeLearner``
(``src/treelearner/tree_learner.cpp:9-33``): ``tree_learner`` picks the
implementation; the device dimension collapses because every learner here is
TPU-resident.  ``num_machines`` (or an externally supplied mesh) sizes the
one-axis worker mesh; with one machine every mode degrades to the serial
learner — loudly, since learner choice is load-bearing in the reference
(``CheckParamConflict`` forces ``is_parallel`` only for ``num_machines>1``,
``src/io/config.cpp:180-280``).
"""

from ..tree.learner import SerialTreeLearner
from ..utils.log import LightGBMError, log_warning


def create_tree_learner(config, dataset, mesh=None):
    kind = config.tree_learner
    if kind not in ("serial", "feature", "data", "voting"):
        raise LightGBMError(f"unknown tree_learner: {kind}")
    from ..ops.shard import sharding_mode
    if (kind != "serial" and int(config.num_machines) > 1
            and sharding_mode(config) == "multi_controller"):
        # the machine-parallel learners drive their own socket network
        # per worker; mixing that with a pod-slice jax.distributed
        # runtime would double-shard the rows and deadlock both planes
        raise LightGBMError(
            f"tree_learner={kind} cannot be combined with "
            f"data_sharding=multi_controller (the pod slice IS the "
            f"data-parallel plane); use tree_learner=serial")
    if int(config.num_machines) <= 1 and mesh is None:
        if kind != "serial":
            log_warning(
                f"tree_learner={kind} with num_machines=1: running the "
                f"serial learner (set num_machines>1 or pass a mesh to "
                f"enable the parallel learners)")
        return SerialTreeLearner(config, dataset)
    from .network import create_network
    net = create_network(config, mesh)
    if kind == "serial":
        log_warning("num_machines>1 with tree_learner=serial: running "
                    "single-device serial training")
        return SerialTreeLearner(config, dataset)
    if kind == "feature":
        from .feature_parallel import FeatureParallelTreeLearner
        return FeatureParallelTreeLearner(config, dataset, net)
    if kind == "data":
        from .data_parallel import DataParallelTreeLearner
        return DataParallelTreeLearner(config, dataset, net)
    from .voting_parallel import VotingParallelTreeLearner
    return VotingParallelTreeLearner(config, dataset, net)
