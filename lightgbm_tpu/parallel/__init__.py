"""Distributed tree learners + collective verbs.

Factory mirrors ``TreeLearner::CreateTreeLearner``
(``src/treelearner/tree_learner.cpp:9-33``): (tree_learner, device) picks
the implementation.  On TPU all learners are device-resident; the parallel
variants add mesh-axis collectives (see ``network.py``).
"""

from ..tree.learner import SerialTreeLearner


def create_tree_learner(config, dataset):
    kind = config.tree_learner
    if kind == "serial" or config.num_machines <= 1:
        from .data_parallel import maybe_sharded_learner
        return maybe_sharded_learner(config, dataset)
    if kind == "feature":
        from .feature_parallel import FeatureParallelTreeLearner
        return FeatureParallelTreeLearner(config, dataset)
    if kind == "data":
        from .data_parallel import DataParallelTreeLearner
        return DataParallelTreeLearner(config, dataset)
    if kind == "voting":
        from .voting_parallel import VotingParallelTreeLearner
        return VotingParallelTreeLearner(config, dataset)
    raise ValueError(f"unknown tree_learner: {kind}")
