"""Voting-parallel tree learner (PV-Tree; reference
``src/treelearner/voting_parallel_tree_learner.cpp``).

Data-parallel by rows, but instead of allreducing the FULL histogram every
split, each worker:

1. finds its local per-feature best splits on LOCAL rows with constraints
   scaled by 1/num_machines (``voting_parallel_tree_learner.cpp:53-55``),
2. proposes its top-k features (``lax.top_k`` of the masked local gains,
   matching the local vote at ``voting_parallel_tree_learner.cpp:322-341``),
3. a global vote elects the 2k most-proposed features
   (``GlobalVoting``, ``:166-195``; ties to the smaller feature id),
4. ONLY the elected features' histogram rows are psum-reduced
   (the reduced-feature ReduceScatter at ``:365-366``) and the final scan
   runs on those global histograms with global counts.

Comm volume per split drops from O(G*256) to O(2k*256) — the PV-Tree
trade: a vote round (one small host sync for the election) buys an
ICI-bandwidth reduction of ~G/2k.  Voting trees can differ from serial
trees when the truly-best feature fails election; with top_k >= num
features the result is exactly serial (asserted in tests).
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import obs
from ..ops.split import (FeatureMeta, NEG_INF, feature_histograms,
                         gather_feature_histograms, masked_feature_gain,
                         min_gain_shift_of, pack_best, per_feature_best,
                         reconstruct_default)
from ..tree.learner import _LeafInfo
from .data_parallel import DataParallelTreeLearner
from .network import Network


@functools.partial(jax.jit, static_argnames=("has_cat",))
def _elected_best_impl(fh_raw, total, constraint, feature_mask, eids,
                       meta_e, hp, has_cat):
    """Final scan over the elected features' GLOBAL histograms."""
    fh = reconstruct_default(fh_raw, total, meta_e)
    shift = min_gain_shift_of(total, hp)
    pf = per_feature_best(fh, total, constraint, meta_e, hp, has_cat, shift)
    nf_total = feature_mask.shape[0]
    mask_e = jnp.where(eids >= 0,
                       feature_mask[jnp.clip(eids, 0, nf_total - 1)], False)
    gain = masked_feature_gain(pf, meta_e, mask_e, shift)
    best = jnp.argmax(gain)   # eids ascending => serial tie-break order
    return pack_best(best, gain, pf, total, constraint, hp, meta_e)


_elected_best = obs.track_jit("vp.elected_best", _elected_best_impl)


class VotingParallelTreeLearner(DataParallelTreeLearner):
    """Data-parallel with top-k feature voting."""

    def __init__(self, config, dataset, network: Network):
        super().__init__(config, dataset, network)
        nf = dataset.num_features
        self.k = max(1, min(int(config.top_k), nf))
        self.n_elect = min(2 * self.k, nf)
        d = network.num_machines
        # local-vote constraints scaled by 1/num_machines
        # (voting_parallel_tree_learner.cpp:53-55)
        hp = self.ctx.hyper
        self._hyper_local = hp._replace(
            min_data_in_leaf=hp.min_data_in_leaf / d,
            min_sum_hessian_in_leaf=hp.min_sum_hessian_in_leaf / d)
        self._local_hist_fns: Dict = {}
        self._vote_fn = None
        self._gather_fn = None
        self._meta_cache: Dict = {}

    # ------------------------------------------------------------------
    # histogram handle = (local hists sharded, local totals, global totals)
    def _hist_fn(self, m: int):
        if m in self._local_hist_fns:
            return self._local_hist_fns[m]
        from ..ops.histogram import _gather_rows, _histogram_scan
        from ..ops.histogram import num_chunks_for
        net, n_loc = self.net, self.n_loc
        num_chunks = num_chunks_for(m)

        def _hist(binned, grad, hess, buffer, lb, lc, leaf):
            begin = lb[0, leaf]
            count = lc[0, leaf]
            b = jnp.clip(begin, 0, n_loc - m)
            start = begin - b
            win = jax.lax.dynamic_slice(buffer, (b,), (m,))
            bins, gh = _gather_rows(binned, grad, hess, win, start, count)
            h = _histogram_scan(bins, gh, num_chunks)      # local (G,256,3)
            loc_tot = h[0].sum(axis=0)                     # local (3,)
            glob_tot = net.allreduce(loc_tot)
            return h, loc_tot[None], glob_tot

        _hist = obs.track_jit(f"vp.hist_m{m}", jax.jit(net.run_sharded(
            _hist,
            (self._row2d_spec, self._row_spec, self._row_spec,
             self._row_spec, self._row2d_spec, self._row2d_spec,
             self._rep_spec),
            (P(net.axis), self._row2d_spec, self._rep_spec))))
        self._local_hist_fns[m] = _hist
        return _hist

    def _leaf_histogram(self, grad, hess, info: _LeafInfo):
        m = self._window_m(info.count)
        fn = self._hist_fn(m)
        return fn(self.binned, grad, hess, self.buffer, self.leaf_begin,
                  self.leaf_count, jnp.asarray(info.leaf_id, jnp.int32))

    def _leaf_totals(self, hist) -> np.ndarray:
        return np.asarray(hist[2], np.float64)

    def _subtract(self, parent, small):
        return jax.tree_util.tree_map(lambda a, b: a - b, tuple(parent),
                                      tuple(small))

    # ------------------------------------------------------------------
    _META_CACHE_MAX = 64

    def _elected_meta(self, eids: tuple):
        """LRU-bounded: elections repeat heavily on strong features, but the
        key space is per-leaf, so an unbounded cache would leak device
        arrays over a long run."""
        hit = self._meta_cache.pop(eids, None)
        if hit is None:
            hit = FeatureMeta.from_dataset(self.dataset,
                                           np.asarray(eids, np.int64))
            if len(self._meta_cache) >= self._META_CACHE_MAX:
                self._meta_cache.pop(next(iter(self._meta_cache)))
        self._meta_cache[eids] = hit
        return hit

    def _find_best(self, info: _LeafInfo, feature_mask):
        net = self.net
        hist_sh, loc_tot, glob_tot = info.hist
        g = self.dataset.num_groups
        has_cat = self.ctx.has_categorical

        # -- stage 1: local per-feature bests -> local top-k vote ---------
        if self._vote_fn is None:
            meta = self.ctx.meta
            k = self.k

            def _vote(h_sh, lt2, constraint, fmask, hp):
                flat = h_sh.reshape(-1, 3)
                tot = lt2[0]
                shift = min_gain_shift_of(tot, hp)
                fh = feature_histograms(flat, tot, meta)
                pf = per_feature_best(fh, tot, constraint, meta, hp,
                                      has_cat, shift)
                gains = masked_feature_gain(pf, meta, fmask, shift)
                topg, topi = jax.lax.top_k(gains, k)
                return topi[None].astype(jnp.int32), topg[None]

            self._vote_fn = obs.track_jit("vp.local_vote", jax.jit(
                net.run_sharded(
                    _vote,
                    (P(net.axis), self._row2d_spec, self._rep_spec,
                     self._rep_spec, self._rep_spec),
                    (self._row2d_spec, self._row2d_spec))))

        constraint = jnp.asarray((info.cmin, info.cmax), jnp.float32)
        ids, gains = self._vote_fn(hist_sh, loc_tot, constraint,
                                   feature_mask, self._hyper_local)

        # -- stage 2: the election (GlobalVoting, :166-195) ---------------
        ids_np = np.asarray(ids)
        gains_np = np.asarray(gains)
        votes = np.zeros(self.ctx.num_features, np.int64)
        valid = gains_np > NEG_INF / 2
        np.add.at(votes, ids_np[valid], 1)
        order = np.lexsort((np.arange(len(votes)), -votes))
        elected = np.sort(order[:self.n_elect][votes[order[:self.n_elect]]
                                               > 0])
        eids = np.full(self.n_elect, -1, np.int64)
        eids[:len(elected)] = elected
        meta_e = self._elected_meta(tuple(eids))

        # -- stage 3: psum only the elected features' histograms ----------
        if self._gather_fn is None:
            meta_rep = jax.tree_util.tree_map(lambda _: self._rep_spec,
                                              self.ctx.meta)

            def _gather(h_sh, me):
                fh_raw = gather_feature_histograms(h_sh.reshape(-1, 3), me)
                return net.allreduce(fh_raw)

            self._gather_fn = obs.track_jit("vp.gather_elected", jax.jit(
                net.run_sharded(_gather, (P(net.axis), meta_rep),
                                self._rep_spec)))
        fh_raw = self._gather_fn(hist_sh, meta_e)

        # -- stage 4: final scan on global histograms + global counts -----
        return _elected_best(fh_raw, jnp.asarray(glob_tot),
                             constraint, feature_mask,
                             jnp.asarray(eids, jnp.int32), meta_e,
                             self.ctx.hyper, has_cat)
