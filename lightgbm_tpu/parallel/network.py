"""Collective-communication verbs over a TPU device mesh.

TPU-native replacement for the reference's from-scratch collective layer
(``include/LightGBM/network.h:86-296``, ``src/network/network.cpp:64-315``:
Bruck / recursive-halving / ring algorithms over socket/MPI point-to-point
links).  On TPU none of that is re-implemented: the five verbs map directly
onto XLA collectives over a named mesh axis, and XLA lowers them to ICI
ring/tree collectives (DCN for multi-slice) — the literal hardware analog of
the reference's ``AllgatherRing``/``ReduceScatterRing``
(``network.cpp:212-226,299-314``).

Two usage levels:

* **inside ``shard_map``** — the learners call the ``Network.*`` verbs with
  data already device-local; these are thin ``jax.lax`` wrappers bound to
  the mesh axis name.
* **host level** — ``global_sum`` / ``sync_up_by_*`` mirror the reference's
  scalar syncs (``GlobalSyncUpByMin/Max/Mean``, ``network.h:165-257``) used
  by e.g. distributed seed/fraction agreement (``application.cpp:187-192``)
  and boost-from-average (``gbdt.cpp:300-309``).  In a single-controller
  JAX program every host already sees the same scalars, so these are
  identities kept for API parity — they become real collectives only under
  multi-controller ``jax.distributed``, where the caller feeds per-process
  values through ``psum`` via ``run_sharded``.

The reference's external-reduce-function hook (``LGBM_NetworkInitWithFunctions``,
``c_api.h:810``) lets an embedder supply its own transport; the analog here
is ``Network(mesh=...)`` accepting any existing ``jax.sharding.Mesh``.
"""

from __future__ import annotations

import functools
import socket
import struct
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import obs
from ..ops.shard import shard_map_compat
from ..robust import faults
from ..robust.retry import RetryError, RetryPolicy, with_retries
from ..utils.log import LightGBMError, log_info

AXIS = "workers"

#: bound ONCE at module scope: a per-call ``jax.jit`` builds a fresh
#: compile cache every invocation (recompiles every time) — the JL002
#: hazard the static analyzer flagged on the old inline form
_sum_leading_axis = obs.track_jit("net.global_sum",
                                  jax.jit(lambda a: a.sum(axis=0)))


def make_mesh(num_machines: int, devices=None) -> Mesh:
    """One-axis mesh over the first ``num_machines`` local devices."""
    if devices is None:
        devices = jax.devices()
    if num_machines > len(devices):
        raise LightGBMError(
            f"num_machines={num_machines} exceeds available devices "
            f"({len(devices)}); reduce num_machines or provision a larger "
            f"mesh")
    return Mesh(np.asarray(devices[:num_machines]), (AXIS,))


class Network:
    """A one-axis mesh + the reference's five collective verbs.

    The in-``shard_map`` verbs (psum/psum_scatter/all_gather/pmax/pmin) are
    static because they only bind the axis name; the mesh instance carries
    topology for the host-level helpers and sharding constructors.
    """

    def __init__(self, mesh: Optional[Mesh] = None, num_machines: int = 1,
                 devices=None):
        self.mesh = mesh if mesh is not None else make_mesh(num_machines,
                                                            devices)
        if len(self.mesh.axis_names) != 1:
            raise LightGBMError("Network expects a one-axis mesh; wrap "
                                "multi-axis meshes in a flat view")
        self.axis = self.mesh.axis_names[0]
        # trace-time comm accounting: every verb call below corresponds to
        # ONE collective op in the compiled program, so logging the
        # payload bytes at trace time records the per-execution comm
        # volume of each program (the analog of the reference's
        # "Network::Allreduce" buffer sizes) — used by tests and the
        # multichip dryrun to substantiate the O(total_bins) vs
        # O(2k*256) per-split claims.
        self.comm_log: list = []

    def _log(self, verb: str, x):
        try:
            nbytes = int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize
        except Exception:   # noqa: BLE001 — non-array payloads
            nbytes = 0
        self.comm_log.append((verb, nbytes))

    def reset_comm_log(self):
        self.comm_log = []

    @property
    def num_machines(self) -> int:
        return self.mesh.devices.size

    # -- in-shard_map verbs (Network::Allreduce etc.) -------------------
    def allreduce(self, x):
        """Sum-allreduce (HistogramBinEntry::SumReducer analog)."""
        self._log("allreduce", x)
        return jax.lax.psum(x, self.axis)

    def reduce_scatter(self, x):
        """Sum + scatter along leading axis (Network::ReduceScatter)."""
        self._log("reduce_scatter", x)
        return jax.lax.psum_scatter(x, self.axis, tiled=True)

    def all_gather(self, x):
        """Concatenate along a fresh leading axis (Network::Allgather)."""
        self._log("all_gather", x)
        return jax.lax.all_gather(x, self.axis)

    def allreduce_max(self, x):
        self._log("allreduce_max", x)
        return jax.lax.pmax(x, self.axis)

    def allreduce_min(self, x):
        self._log("allreduce_min", x)
        return jax.lax.pmin(x, self.axis)

    def rank(self):
        return jax.lax.axis_index(self.axis)

    def argmax_allreduce(self, key, payload, tie_id):
        """Pick the payload of the rank whose ``key`` is globally maximal,
        ties broken by the smaller ``tie_id`` — the SplitInfo max-reduce
        (``parallel_tree_learner.h:183-207``) as pmax/pmin + masked psum."""
        self._log("argmax_allreduce:key", key)
        self._log("argmax_allreduce:tie", tie_id)
        kmax = jax.lax.pmax(key, self.axis)
        is_max = key == kmax
        tid = jnp.where(is_max, tie_id, jnp.iinfo(jnp.int32).max)
        tmin = jax.lax.pmin(tid, self.axis)
        owner = is_max & (tie_id == tmin)

        def sel(v):
            self._log("argmax_allreduce:payload", v)
            return jax.lax.psum(
                jnp.where(owner, v.astype(jnp.float32), 0.0), self.axis)

        return jax.tree_util.tree_map(sel, payload), owner

    # -- sharding constructors ------------------------------------------
    def row_sharding(self):
        return NamedSharding(self.mesh, P(self.axis))

    def row2d_sharding(self):
        return NamedSharding(self.mesh, P(self.axis, None))

    def replicated(self):
        return NamedSharding(self.mesh, P())

    def shard_rows(self, x):
        """Place a (D*k, ...) array so each device owns a contiguous k-row
        block (the pre-partitioned data distribution, ``dataset.h:82``)."""
        spec = P(self.axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    def replicate(self, x):
        return jax.device_put(x, self.replicated())

    # -- host-level scalar syncs (network.h:165-257) --------------------
    # Single-controller: every process sees the same host scalars, so these
    # are identities; kept so learner code reads like the reference.
    def sync_up_by_min(self, v):
        return v

    def sync_up_by_max(self, v):
        return v

    def sync_up_by_mean(self, v):
        return v

    def global_sum(self, x):
        """Sum a per-device-sharded array across the axis on host."""
        return _sum_leading_axis(x)

    # -- generic sharded runner -----------------------------------------
    def run_sharded(self, fn, in_specs, out_specs):
        """``shard_map`` bound to this mesh/axis (replication checking
        off: the verb wrappers above make collective use explicit; the
        compat shim covers jax versions where shard_map still lives
        under jax.experimental)."""
        return shard_map_compat(fn, self.mesh, in_specs, out_specs)


# ---------------------------------------------------------------------------
# fault-tolerant point-to-point helpers (the host-blob plane)
# ---------------------------------------------------------------------------
# XLA owns the on-device collectives above, but multi-controller
# bring-up still rides plain TCP: the jax.distributed coordinator
# handshake, and any embedder exchanging serialized mappers / machine
# lists over its own sockets (the reference's Linkers).  The reference
# blocks forever on a dead peer (linkers_socket.cpp Construct/Recv);
# these helpers bound every operation with a timeout and give connects
# capped-backoff retries, so a missing worker fails the mesh FAST and
# with context instead of hanging it (docs/Robustness.md).

DEFAULT_NETWORK_TIMEOUT_S = 30.0
DEFAULT_NETWORK_RETRIES = 5
#: recv_bytes length-prefix sanity bound: a corrupt/misbehaving peer
#: must produce a bounded protocol error, not a giant allocation
MAX_MESSAGE_BYTES = 1 << 30

_LEN_PREFIX = struct.Struct("<Q")


def connect_with_retries(host: str, port: int, *,
                         attempts: Optional[int] = None,
                         timeout_s: Optional[float] = None,
                         base_delay_s: float = 0.1,
                         config=None, sleep=time.sleep) -> socket.socket:
    """TCP connect with ``attempts`` bounded tries and capped
    exponential backoff; raises a clear "peer unreachable after N
    attempts" :class:`LightGBMError` instead of hanging the worker
    mesh.  The returned socket keeps ``timeout_s`` as its per-op
    timeout.  Explicit arguments win; otherwise ``config``'s
    ``network_retries`` / ``network_timeout`` params apply, then the
    schema defaults."""
    cfg_attempts, cfg_timeout = network_policy_from_config(config)
    if attempts is None:
        attempts = cfg_attempts
    if timeout_s is None:
        timeout_s = cfg_timeout
    attempts = max(int(attempts), 1)

    def attempt():
        faults.check("net.connect")
        return socket.create_connection((host, int(port)),
                                        timeout=float(timeout_s))

    policy = RetryPolicy(max_attempts=attempts,
                         base_delay_s=float(base_delay_s),
                         max_delay_s=2.0,
                         retry_on=(OSError, faults.InjectedFault))
    try:
        sock = with_retries(attempt, policy, site="net.connect",
                            sleep=sleep)
    except RetryError as e:
        raise LightGBMError(
            f"peer {host}:{port} unreachable after {attempts} "
            f"attempt{'s' if attempts != 1 else ''} (last error: "
            f"{e.__cause__!r}); check the machine list / coordinator "
            f"address and that the peer process is up") from e
    sock.settimeout(float(timeout_s))
    return sock


def wait_for_peer(address: str, *, attempts: Optional[int] = None,
                  timeout_s: Optional[float] = None,
                  base_delay_s: float = 0.1, config=None,
                  sleep=time.sleep) -> None:
    """Probe a ``host:port`` peer (e.g. the ``jax.distributed``
    coordinator) until it accepts a connection, then close — called
    BEFORE ``jax.distributed.initialize`` so a dead/mistyped
    coordinator fails fast with a clear error instead of stalling the
    whole mesh inside the runtime's own (much longer) handshake."""
    host, _, port = str(address).rpartition(":")
    if not host or not port.isdigit():
        raise LightGBMError(
            f"bad peer address {address!r} (expected host:port)")
    sock = connect_with_retries(host, int(port), attempts=attempts,
                                timeout_s=timeout_s,
                                base_delay_s=base_delay_s,
                                config=config, sleep=sleep)
    sock.close()


def _netop(sock: socket.socket, site: str, timeout_s: Optional[float],
           fn, what: str):
    """Shared wrapper for send/recv: fault site, optional per-op
    timeout override, and timeout/OS errors re-raised with context."""
    faults.check(site)
    if timeout_s is not None:
        sock.settimeout(float(timeout_s))
    try:
        return fn()
    except socket.timeout as e:
        peer = _peer_name(sock)
        raise LightGBMError(
            f"network timeout {what} {peer} (after "
            f"{sock.gettimeout():g} s); peer dead or partitioned — "
            f"the mesh should be rebuilt") from e
    except OSError as e:
        peer = _peer_name(sock)
        raise LightGBMError(f"network error {what} {peer}: {e}") from e


def _peer_name(sock: socket.socket) -> str:
    try:
        addr = sock.getpeername()
    except OSError:
        return "peer <unknown>"
    if isinstance(addr, tuple) and len(addr) >= 2:
        return f"peer {addr[0]}:{addr[1]}"
    return f"peer {addr!r}"     # AF_UNIX etc.


def send_bytes(sock: socket.socket, payload: bytes,
               timeout_s: Optional[float] = None) -> None:
    """Length-prefixed blocking send with a bounded timeout (the
    reference's ``Linkers::Send`` had none)."""
    def run():
        sock.sendall(_LEN_PREFIX.pack(len(payload)))
        sock.sendall(payload)
    _netop(sock, "net.send", timeout_s, run, "sending to")


def recv_bytes(sock: socket.socket,
               timeout_s: Optional[float] = None) -> bytes:
    """Length-prefixed blocking recv with a bounded timeout; a peer
    closing mid-message raises instead of returning a short read."""
    def read_exact(n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            # cap the per-call request so a large n never asks the
            # kernel for one giant buffer
            chunk = sock.recv(min(n - len(buf), 1 << 20))
            if not chunk:
                raise LightGBMError(
                    f"connection closed by {_peer_name(sock)} "
                    f"mid-message ({len(buf)}/{n} bytes)")
            buf.extend(chunk)
        return bytes(buf)

    def run():
        (length,) = _LEN_PREFIX.unpack(read_exact(_LEN_PREFIX.size))
        if length > MAX_MESSAGE_BYTES:
            # corrupt / torn / hostile prefix: a bounded protocol
            # error with context, never a giant allocation
            raise LightGBMError(
                f"{_peer_name(sock)} announced a {length}-byte message "
                f"(limit {MAX_MESSAGE_BYTES}); corrupt length prefix "
                f"or protocol mismatch")
        return read_exact(length)
    return _netop(sock, "net.recv", timeout_s, run, "receiving from")


def network_policy_from_config(config):
    """(attempts, timeout_s) from a Config's ``network_retries`` /
    ``network_timeout`` params (schema defaults otherwise)."""
    return (int(getattr(config, "network_retries",
                        DEFAULT_NETWORK_RETRIES)),
            float(getattr(config, "network_timeout",
                          DEFAULT_NETWORK_TIMEOUT_S)))


# ---------------------------------------------------------------------------
# pod-slice blob broadcast (rank 0 -> every peer)
# ---------------------------------------------------------------------------
# jax.distributed has no host-payload channel, and the mapper reference
# a pod host needs BEFORE it can bin its shard cannot ride a device
# collective (the mesh does not exist yet).  So the multi-controller
# ingest handshake reuses the length-prefixed blob plane above: rank 0
# serves the serialized payload on ``coordinator port + 1``, every peer
# dials it with the same retry/timeout policy as the coordinator probe.
# Rounds are SPMD-sequenced — every process calls broadcast_blob the
# same number of times in the same order — so one well-known port
# serves any number of sequential rounds.

#: offset from the jax.distributed coordinator port to the blob
#: broadcast port (the coordinator owns its own port on rank 0)
BROADCAST_PORT_OFFSET = 1


def pod_broadcast_address(coordinator_address: str) -> str:
    """``host:port`` of the blob broadcast endpoint derived from the
    coordinator address."""
    host, _, port = str(coordinator_address).rpartition(":")
    if not host or not port.isdigit():
        raise LightGBMError(
            f"bad coordinator address {coordinator_address!r} "
            f"(expected host:port)")
    return f"{host}:{int(port) + BROADCAST_PORT_OFFSET}"


def broadcast_blob(payload: Optional[bytes], *, address: str,
                   num_hosts: int, rank: int, config=None) -> bytes:
    """One broadcast round: rank 0 sends ``payload`` to every peer and
    returns it; peers pass ``payload=None`` and return the received
    bytes.  Fail-fast on both sides: rank 0 bounds the accept loop by
    the ``network_timeout``-derived deadline and names the ranks that
    never dialed in; peers ride ``connect_with_retries`` so a dead
    rank 0 surfaces as "peer unreachable after N attempts"."""
    faults.check("net.broadcast")
    attempts, timeout_s = network_policy_from_config(config)
    host, _, port = str(address).rpartition(":")
    if not host or not port.isdigit():
        raise LightGBMError(
            f"bad broadcast address {address!r} (expected host:port)")
    port = int(port)
    num_hosts = int(num_hosts)
    if int(rank) != 0:
        sock = connect_with_retries(host, port, config=config)
        try:
            send_bytes(sock, struct.pack("<i", int(rank)),
                       timeout_s=timeout_s)
            blob = recv_bytes(sock, timeout_s=timeout_s)
        finally:
            sock.close()
        obs.inc("net.broadcast_bytes", len(blob))
        return blob
    if payload is None:
        raise LightGBMError("broadcast_blob: rank 0 must supply the "
                            "payload")
    deadline = time.monotonic() + max(10.0, attempts * timeout_s)
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    pending = set(range(1, num_hosts))
    try:
        try:
            # peers dial the coordinator hostname; rank 0 accepts on
            # every interface so "localhost" vs the public name both
            # land here
            server.bind(("", port))
        except OSError as e:
            raise LightGBMError(
                f"broadcast endpoint {address} unavailable on host 0: "
                f"{e}") from e
        server.listen(max(num_hosts, 1))
        while pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise LightGBMError(
                    f"pod broadcast on {address}: host(s) "
                    f"{sorted(pending)} never connected within the "
                    f"network_timeout budget — peer dead at ingest "
                    f"bring-up")
            server.settimeout(min(remaining, 1.0))
            try:
                conn, _addr = server.accept()
            except socket.timeout:
                continue
            try:
                (peer_rank,) = struct.unpack(
                    "<i", recv_bytes(conn, timeout_s=timeout_s))
                send_bytes(conn, payload, timeout_s=timeout_s)
            finally:
                conn.close()
            pending.discard(peer_rank)
    finally:
        server.close()
    obs.inc("net.broadcast_bytes", len(payload))
    return payload


@functools.lru_cache(maxsize=8)
def _default_network(num_machines: int) -> Network:
    log_info(f"Initializing TPU collective mesh with {num_machines} "
             f"worker(s)")
    return Network(num_machines=num_machines)


def create_network(config, mesh: Optional[Mesh] = None) -> Network:
    """Network for a config: ``num_machines`` workers over local devices,
    or an externally supplied mesh (the LGBM_NetworkInitWithFunctions
    analog)."""
    if mesh is not None:
        return Network(mesh=mesh)
    return _default_network(int(config.num_machines))
