"""Bounded retries with capped exponential backoff, plus a circuit
breaker for repeatedly-failing dependencies.

The reference's transports either block forever (socket ``Recv``) or
abort the process (``MPI_SAFE_CALL``); neither survives a production
windowed-retrain loop.  :func:`with_retries` is the shared policy
wrapper every transient-failure path routes through — network
connect/send/recv (``parallel/network.py``), device dispatch
(``boosting/gbdt.py``) — so attempt counts, backoff shape and
telemetry are defined in exactly one place.

Backoff is capped exponential with hash-derived jitter (no live RNG):
the fraction is keyed on ``(process, site, attempt)``, so sleeps are
deterministic within a process — the property tests rely on — while
co-failing worker PROCESSES decorrelate instead of retrying in
lockstep.

Telemetry: ``retry.attempts`` (total), ``retry.<site>`` (per site) and
the ``retry.backoff`` timing histogram — see docs/Observability.md.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from .. import obs
from ..utils.log import LightGBMError
from .faults import InjectedFault, _hash_uniform

#: per-process jitter key: co-failing WORKERS must not retry in
#: lockstep, so the jitter hash includes the pid — while within one
#: process the sleeps stay fully deterministic and replayable
_PROCESS_KEY = os.getpid()


@dataclass(frozen=True)
class RetryPolicy:
    """How many times to try and how long to wait between tries.

    ``max_attempts`` counts the FIRST try too (3 = one try + two
    retries).  ``retry_on`` is the exception tuple worth retrying —
    anything else propagates immediately (a shape error does not become
    less wrong on attempt two).
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.25           # fraction of the delay shaved off
    retry_on: Tuple = (Exception,)


class RetryError(LightGBMError):
    """All attempts failed; ``__cause__`` is the last exception."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(
            f"{site or 'operation'} failed after {attempts} attempt"
            f"{'s' if attempts != 1 else ''}: {last!r}")
        self.site = site
        self.attempts = attempts
        self.__cause__ = last


def backoff_delay(policy: RetryPolicy, attempt: int,
                  site: str = "") -> float:
    """Delay before retry number ``attempt`` (0-based): capped
    exponential, shaved by a (process, site, attempt)-keyed jitter —
    deterministic WITHIN a process (a failing run replays its own
    sleeps) while co-failing worker PROCESSES land on different delays
    instead of retrying in lockstep."""
    raw = min(policy.base_delay_s * (2.0 ** attempt), policy.max_delay_s)
    if policy.jitter <= 0.0:
        return raw
    return raw * (1.0 - policy.jitter * _hash_uniform(
        "retry", _PROCESS_KEY, site, attempt))


def with_retries(fn: Callable, policy: Optional[RetryPolicy] = None,
                 site: str = "", sleep: Callable = time.sleep):
    """Call ``fn()`` under ``policy``; returns its value or raises
    :class:`RetryError` once attempts are exhausted.  ``sleep`` is
    injectable for tests."""
    policy = policy or RetryPolicy()
    attempts = max(int(policy.max_attempts), 1)
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn()
        except policy.retry_on as e:   # noqa: PERF203 — the point
            last = e
            obs.inc("retry.attempts")
            if site:
                obs.inc(f"retry.{site}")
            if attempt + 1 >= attempts:
                break
            delay = backoff_delay(policy, attempt, site)
            obs.observe("retry.backoff", delay)
            sleep(delay)
    raise RetryError(site, attempts, last)


def transient_dispatch_errors() -> Tuple:
    """Exception types a device dispatch may transiently raise (plus
    the injected flavors so chaos runs exercise the same path).  The
    JAX runtime error type moved across versions; resolve what exists."""
    errs = [InjectedFault, OSError, TimeoutError]
    try:
        from jax.errors import JaxRuntimeError
        errs.append(JaxRuntimeError)
    except ImportError:
        try:
            from jaxlib.xla_extension import XlaRuntimeError
            errs.append(XlaRuntimeError)
        except ImportError:
            pass
    return tuple(errs)


class CircuitBreaker:
    """Consecutive-failure circuit breaker with timed re-probe.

    States: **closed** (normal — every call may attempt the guarded
    operation), **open** (``failure_threshold`` consecutive failures
    seen — :meth:`allow` answers False so callers go straight to their
    fallback, except once per ``reprobe_interval_s`` when it answers
    True so ONE caller probes whether the dependency recovered).  A
    recorded success closes the breaker; a failure while open re-arms
    the re-probe timer.

    Thread-safe; ``clock`` is injectable for tests.
    """

    def __init__(self, failure_threshold: int = 3,
                 reprobe_interval_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(int(failure_threshold), 1)
        self.reprobe_interval_s = float(reprobe_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: Optional[float] = None    # degraded duration
        self._next_probe_at = 0.0                  # probe scheduling
        self._dark_total = 0.0                     # closed dark periods

    @property
    def state(self) -> str:
        with self._lock:
            return "open" if self._opened_at is not None else "closed"

    def dark_seconds(self) -> float:
        """Total seconds this breaker has spent open, INCLUDING the
        current still-open period.  ``record_success`` reports a dark
        period only at recovery; live availability accounting (the SLO
        engine's window evaluation, obs/slo.py) cannot wait for one."""
        with self._lock:
            total = self._dark_total
            if self._opened_at is not None:
                total += self._clock() - self._opened_at
            return total

    def allow(self) -> bool:
        """May the caller attempt the guarded operation right now?
        Closed: always.  Open: exactly ONE caller per re-probe window —
        granting a probe immediately pushes the window out, so
        concurrent requests during the degraded period do not all pay
        the device-failure latency (failure re-arms the window too;
        success closes the breaker)."""
        with self._lock:
            if self._opened_at is None:
                return True
            now = self._clock()
            if now >= self._next_probe_at:
                self._next_probe_at = now + self.reprobe_interval_s
                return True
            return False

    def record_success(self) -> Optional[float]:
        """Note a successful guarded call.  Returns the TOTAL seconds
        the breaker spent open when this success RECOVERS it, else
        None."""
        with self._lock:
            self._failures = 0
            if self._opened_at is None:
                return None
            dark = self._clock() - self._opened_at
            self._dark_total += dark
            self._opened_at = None
            return dark

    def record_failure(self) -> bool:
        """Note a failed guarded call.  Returns True exactly when this
        failure TRIPS the breaker closed -> open."""
        with self._lock:
            self._failures += 1
            now = self._clock()
            if self._opened_at is not None:
                # failed re-probe: stay open, push the next probe out
                self._next_probe_at = now + self.reprobe_interval_s
                return False
            if self._failures >= self.failure_threshold:
                self._opened_at = now
                self._next_probe_at = now + self.reprobe_interval_s
                return True
            return False
