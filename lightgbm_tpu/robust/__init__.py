"""Cross-cutting fault-tolerance layer (docs/Robustness.md).

Four pillars, each consumed by the subsystem it hardens:

* :mod:`~lightgbm_tpu.robust.faults` — deterministic, seed-keyed fault
  injection at named sites (armed via ``LGBM_TPU_FAULTS`` or the
  ``fault_spec`` param), so every failure mode below is testable in CI
  without hardware;
* :mod:`~lightgbm_tpu.robust.retry` — the shared
  :func:`~lightgbm_tpu.robust.retry.with_retries` policy wrapper
  (capped exponential backoff, deterministic jitter) and the
  :class:`~lightgbm_tpu.robust.retry.CircuitBreaker` behind serving's
  degrade-to-host path;
* :mod:`~lightgbm_tpu.robust.checkpoint` — atomic
  (write-temp-then-rename) training snapshots and pipeline window
  checkpoints;
* graceful degradation lives where the traffic is:
  ``serve.engine.PredictionServer`` (host fallback + breaker) and
  ``pipeline.core.RetrainPipeline`` (checkpoint/resume).
"""

from . import faults  # noqa: F401  (site API: robust.faults.check(...))
from .checkpoint import (atomic_replace_from, atomic_write_bytes,
                         atomic_write_text, has_pipeline_checkpoint,
                         latest_snapshot, load_pipeline_checkpoint,
                         load_train_state, save_pipeline_checkpoint,
                         save_train_state)
from .faults import (InjectedFault, InjectedOSError, InjectedTimeout,
                     parse_fault_spec)
from .retry import (CircuitBreaker, RetryError, RetryPolicy,
                    backoff_delay, transient_dispatch_errors,
                    with_retries)

__all__ = [
    "faults", "InjectedFault", "InjectedOSError", "InjectedTimeout",
    "parse_fault_spec", "RetryPolicy", "RetryError", "with_retries",
    "backoff_delay", "CircuitBreaker", "transient_dispatch_errors",
    "atomic_write_bytes", "atomic_write_text", "atomic_replace_from",
    "save_train_state", "load_train_state", "latest_snapshot",
    "save_pipeline_checkpoint", "load_pipeline_checkpoint",
    "has_pipeline_checkpoint",
]
