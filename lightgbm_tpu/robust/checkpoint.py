"""Atomic checkpoints: training snapshots and pipeline window state.

Two checkpoint families live here, both with the same crash contract —
**a reader never observes a partial file**: every write lands in a
same-directory temp file first and is moved into place with
``os.replace`` (atomic on POSIX), so a process killed mid-write leaves
either the previous checkpoint or the new one, never a torn mix.

* **Training snapshots** (``save_train_state``/``load_train_state``,
  used by ``GBDT.save_checkpoint``): the model text file plus a
  ``.state.npz`` sidecar holding the EXACT float32 training scores and
  the iteration counter.  Restoring the scores bit-exactly is what
  makes continued boosting byte-identical to an uninterrupted run —
  rebuilding them from leaf values would round differently (see
  docs/Robustness.md).  Bagging / feature_fraction / quantization
  draws need no state: they are all derived from (seed, iteration) or
  (seed, tree index).

* **Pipeline checkpoints** (``save_pipeline_checkpoint``/
  ``load_pipeline_checkpoint``): one directory per retrain loop holding
  ``model.txt`` (the last completed window's ensemble), ``bins.pkl``
  (the :class:`~lightgbm_tpu.pipeline.bins.BinMapperCache` reference
  mappers + drift occupancy) and ``checkpoint.json`` — the manifest,
  written LAST, which is the commit point: a resume only trusts what
  the manifest names.

The ``io.write`` fault site sits between temp-write and rename so chaos
tests can simulate a crash at the worst moment and assert the previous
checkpoint survives intact.
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

from ..utils.log import LightGBMError, log_info
from . import faults

MANIFEST = "checkpoint.json"
MANIFEST_VERSION = 1

_SNAPSHOT_RE = re.compile(r"\.snapshot_iter_(\d+)$")


def _tmp_path(path: str) -> str:
    return f"{path}.tmp.{os.getpid()}"


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write-temp-then-rename; fsynced so the rename never outruns the
    data.  The ``io.write`` fault site fires BEFORE the rename — an
    injected fault (or a real crash there) leaves the old file intact
    and at most a stray ``.tmp.<pid>`` behind."""
    tmp = _tmp_path(path)
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    faults.check("io.write")
    os.replace(tmp, path)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode())


def atomic_replace_from(writer, path: str) -> None:
    """Atomic wrapper for APIs that insist on writing a path themselves
    (e.g. ``BinMapperCache.save``): ``writer(tmp)`` then rename."""
    tmp = _tmp_path(path)
    writer(tmp)
    faults.check("io.write")
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# training snapshots (GBDT.save_checkpoint sidecar)
# ---------------------------------------------------------------------------

def save_train_state(path: str, score: np.ndarray, iteration: int,
                     rng_state: Optional[tuple] = None) -> None:
    """Atomic ``.npz`` sidecar with the exact (K, N) float32 training
    scores, the iteration counter and (optionally) the host learner's
    sequential Mersenne-Twister state — the one draw stream that is NOT
    (seed, iteration)-derived (the host path's feature_fraction)."""
    arrays = {"score": np.asarray(score, np.float32),
              "iteration": np.int64(iteration)}
    if rng_state is not None:
        name, keys, pos, has_gauss, cached = rng_state
        arrays.update(rng_name=np.asarray(str(name)),
                      rng_keys=np.asarray(keys, np.uint32),
                      rng_pos=np.int64(pos),
                      rng_has_gauss=np.int64(has_gauss),
                      rng_cached=np.float64(cached))
    tmp = _tmp_path(path)
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
        fh.flush()
        os.fsync(fh.fileno())
    faults.check("io.write")
    os.replace(tmp, path)


def load_train_state(path: str
                     ) -> Optional[Tuple[np.ndarray, int,
                                         Optional[tuple]]]:
    """-> (score float32, iteration, rng_state | None) or None when no
    sidecar exists."""
    if not os.path.exists(path):
        return None
    with np.load(path) as state:
        rng_state = None
        if "rng_name" in state.files:
            rng_state = (str(state["rng_name"]),
                         np.asarray(state["rng_keys"], np.uint32),
                         int(state["rng_pos"]),
                         int(state["rng_has_gauss"]),
                         float(state["rng_cached"]))
        return (np.asarray(state["score"], np.float32),
                int(state["iteration"]), rng_state)


def latest_snapshot(output_model: str) -> Optional[str]:
    """The highest-iteration ``<output_model>.snapshot_iter_N`` whose
    state sidecar exists (a snapshot without one cannot resume
    byte-identically, so it is skipped), or None."""
    best, best_iter = None, -1
    for cand in glob.glob(glob.escape(output_model) + ".snapshot_iter_*"):
        m = _SNAPSHOT_RE.search(cand)
        if m is None or not os.path.exists(cand + ".state.npz"):
            continue
        it = int(m.group(1))
        if it > best_iter:
            best, best_iter = cand, it
    return best


# ---------------------------------------------------------------------------
# pod-slice commit protocol (data_sharding=multi_controller)
# ---------------------------------------------------------------------------
#
# A pod checkpoint is only real once EVERY host has materialized its
# state: host 0 must not publish a snapshot a dead peer never reached,
# or resume would silently diverge.  The protocol (docs/Sharding.md):
#
#   1. every host writes an atomic ack file ``<path>.ack.h<rank>``
#      carrying a digest of its view of the snapshot state (model trees
#      + scores + iteration — byte-identical across hosts by the
#      sharding contract, so the digest doubles as a divergence check);
#   2. host 0 polls for all acks (network_timeout-derived deadline) and
#      verifies every digest matches its own;
#   3. host 0 writes the payload (model text, .state.npz sidecar) and
#      THEN the ``<path>.commit`` marker — the commit point;
#   4. peers poll for a marker with the matching digest before
#      returning, so no host proceeds past an uncommitted snapshot.
#
# A host killed mid-window never acks, host 0 times out, no marker
# lands, and the pod resumes from the previous committed snapshot.
# The ``ckpt.ack`` fault site arms step 1 for LGBM_TPU_FAULTS chaos.

_POLL_INTERVAL_S = 0.05


def _pod_ack_path(path: str, rank: int) -> str:
    return f"{path}.ack.h{int(rank)}"


def pod_commit_path(path: str) -> str:
    return f"{path}.commit"


def pod_state_digest(model_trees: str, score: np.ndarray,
                     iteration: int) -> str:
    """Digest of one host's snapshot view.  Callers pass the model text
    WITHOUT the parameters echo (``host_rank`` legitimately differs per
    host); scores and iteration are byte-identical across hosts under
    the replicated-score contract."""
    import hashlib
    h = hashlib.sha256()
    h.update(model_trees.encode())
    h.update(np.ascontiguousarray(score, np.float32).tobytes())
    h.update(str(int(iteration)).encode())
    return h.hexdigest()


def write_pod_ack(path: str, rank: int, digest: str) -> None:
    """Atomically publish this host's readiness for the snapshot at
    ``path`` (step 1 of the pod commit protocol)."""
    faults.check("ckpt.ack")
    atomic_write_text(_pod_ack_path(path, rank),
                      json.dumps({"rank": int(rank), "digest": digest}))


def await_pod_acks(path: str, num_hosts: int, digest: str,
                   timeout_s: float, sleep=None) -> None:
    """Host 0: block until every host's ack lands with a matching
    digest; raises :class:`LightGBMError` naming the missing ranks on
    timeout and the diverging rank on digest mismatch."""
    import time
    sleep = sleep or time.sleep
    deadline = time.monotonic() + float(timeout_s)
    missing = list(range(int(num_hosts)))
    while True:
        still = []
        for rank in missing:
            ack = _pod_ack_path(path, rank)
            if not os.path.exists(ack):
                still.append(rank)
                continue
            try:
                with open(ack) as fh:
                    got = json.load(fh)
            except (OSError, ValueError):
                still.append(rank)   # mid-replace read; retry
                continue
            if str(got.get("digest")) != digest:
                raise LightGBMError(
                    f"pod checkpoint {path}: host {rank} acked digest "
                    f"{got.get('digest')!r} but host 0 computed "
                    f"{digest!r} — pod state diverged, refusing to "
                    f"commit")
        missing = still
        if not missing:
            return
        if time.monotonic() >= deadline:
            raise LightGBMError(
                f"pod checkpoint {path}: no ack from host(s) "
                f"{missing} within {timeout_s:.1f}s — a peer died "
                f"mid-window; snapshot NOT committed")
        sleep(_POLL_INTERVAL_S)


def commit_pod(path: str, digest: str) -> None:
    """Step 3's commit point: the marker is written LAST, after every
    payload file, so its presence certifies a complete snapshot."""
    atomic_write_text(pod_commit_path(path),
                      json.dumps({"digest": digest}))


def await_pod_commit(path: str, digest: str, timeout_s: float,
                     sleep=None) -> None:
    """Peers: block until host 0's commit marker lands with the
    matching digest (a stale marker from an earlier snapshot at the
    same path keeps polling until the fresh one replaces it)."""
    import time
    sleep = sleep or time.sleep
    deadline = time.monotonic() + float(timeout_s)
    while True:
        marker = pod_commit_path(path)
        if os.path.exists(marker):
            try:
                with open(marker) as fh:
                    got = json.load(fh)
            except (OSError, ValueError):
                got = {}
            if str(got.get("digest")) == digest:
                return
        if time.monotonic() >= deadline:
            raise LightGBMError(
                f"pod checkpoint {path}: host 0 never committed "
                f"within {timeout_s:.1f}s — snapshot abandoned")
        sleep(_POLL_INTERVAL_S)


def has_pod_commit(path: str) -> bool:
    """Whether the snapshot at ``path`` was pod-committed (resume
    pickers must skip uncommitted pod snapshots)."""
    return os.path.exists(pod_commit_path(path))


def clear_pod_acks(path: str, num_hosts: int) -> None:
    """Best-effort ack cleanup after a commit (stale acks from an
    earlier snapshot at the same path would short-circuit step 2)."""
    for rank in range(int(num_hosts)):
        try:
            os.remove(_pod_ack_path(path, rank))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# pipeline window checkpoints
# ---------------------------------------------------------------------------

@dataclass
class PipelineCheckpoint:
    """A loaded pipeline checkpoint (the manifest's view)."""

    directory: str
    window: int
    model_path: Optional[str]
    bins_path: Optional[str]
    meta: dict = field(default_factory=dict)

    @property
    def trace_id(self) -> Optional[str]:
        """The originating run's causal trace id (obs/tracing.py), when
        the checkpointing pipeline recorded one — a resumed pipeline
        reuses it so the resumed windows stay on the same trace."""
        return str(self.meta.get("trace_id") or "") or None

    def model_string(self) -> Optional[str]:
        if self.model_path is None:
            return None
        with open(self.model_path) as fh:
            return fh.read()


def save_pipeline_checkpoint(directory: str, *, window: int,
                             model_str: str, bins=None,
                             meta: Optional[dict] = None) -> None:
    """Persist one completed window: model text, optional bin-mapper
    cache, then the manifest (the commit point — always written last).

    The payload files are VERSIONED per window (``model.<w>.txt``)
    precisely so the manifest really is the commit point: with fixed
    names, a crash after replacing window N's model but before the
    manifest rename would pair window N-1's manifest with window N's
    model and resume would warm-start/evaluate against the wrong
    ensemble.  With versioned names that crash leaves window N-1's
    manifest pointing at window N-1's untouched files.  Files from
    windows older than the committed one are garbage-collected after
    the manifest lands."""
    os.makedirs(directory, exist_ok=True)
    model_name = f"model.{int(window)}.txt"
    atomic_write_text(os.path.join(directory, model_name), model_str)
    bins_name = None
    if bins is not None and bins.reference is not None:
        bins_name = f"bins.{int(window)}.pkl"
        atomic_replace_from(bins.save,
                            os.path.join(directory, bins_name))
    manifest = {
        "version": MANIFEST_VERSION,
        "window": int(window),
        "model": model_name,
        "bins": bins_name,
        "meta": dict(meta or {}),
    }
    atomic_write_text(os.path.join(directory, MANIFEST),
                      json.dumps(manifest, indent=1))
    _gc_stale_payloads(directory, int(window))


def _gc_stale_payloads(directory: str, committed_window: int) -> None:
    """Best-effort removal of payload/temp files from windows OLDER
    than the committed one (the manifest no longer references them)."""
    keep = {f"model.{committed_window}.txt",
            f"bins.{committed_window}.pkl", MANIFEST}
    for name in os.listdir(directory):
        if name in keep:
            continue
        m = re.match(r"^(?:model|bins)\.(\d+)\.(?:txt|pkl)", name)
        if m is None or int(m.group(1)) >= committed_window:
            continue
        try:
            os.remove(os.path.join(directory, name))
        except OSError:
            pass


def has_pipeline_checkpoint(directory: str) -> bool:
    return os.path.exists(os.path.join(directory, MANIFEST))


def load_pipeline_checkpoint(directory: str) -> Optional[PipelineCheckpoint]:
    """Read the manifest and resolve the files it names; None when no
    manifest was ever committed."""
    path = os.path.join(directory, MANIFEST)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        manifest = json.load(fh)
    if int(manifest.get("version", 0)) != MANIFEST_VERSION:
        raise LightGBMError(
            f"pipeline checkpoint {path} has version "
            f"{manifest.get('version')!r}; this build reads "
            f"{MANIFEST_VERSION}")
    def resolve(name):
        if not name:
            return None
        full = os.path.join(directory, name)
        if not os.path.exists(full):
            raise LightGBMError(
                f"pipeline checkpoint manifest names missing file "
                f"{full}")
        return full
    cp = PipelineCheckpoint(
        directory=directory,
        window=int(manifest["window"]),
        model_path=resolve(manifest.get("model")),
        bins_path=resolve(manifest.get("bins")),
        meta=dict(manifest.get("meta") or {}))
    log_info(f"Loaded pipeline checkpoint (window {cp.window}) from "
             f"{directory}")
    return cp
