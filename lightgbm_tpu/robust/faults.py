"""Deterministic, seed-keyed fault injection for the hot paths.

Every failure mode the fault-tolerance layer defends against — device
dispatch dying mid-window, a poisoned serve batch, a peer that never
answers, a checkpoint write interrupted between temp-file and rename —
is rare on real hardware and IMPOSSIBLE to schedule in CI.  This
registry makes them schedulable: production code calls
:func:`check` at a handful of **named sites**, and a fault spec (the
``LGBM_TPU_FAULTS`` env var, the ``fault_spec`` param, or a direct
:func:`configure` call) decides deterministically which invocation of
which site raises.  Disarmed (the default), ``check`` is one attribute
read — the hot path pays nothing.

Named sites wired in this codebase::

    grow.dispatch    DeviceGrower dispatch (per-iteration and fused)
    serve.dispatch   packed-forest device traversal in PredictionServer
    serve.fleet.dispatch  packed-fleet replica traversal in FleetServer
    pipeline.prep    RetrainPipeline host prep (runs on the prep thread)
    pipeline.train   RetrainPipeline device-training stage
    net.connect      socket connect (parallel/network.py helpers)
    net.send         socket send
    net.recv         socket recv
    io.read          streaming text reader (data/stream_loader.py)
    io.write         atomic checkpoint writes (robust/checkpoint.py)
    stream.parse     chunk parsing in the streaming loader
    obs.export       telemetry snapshot/write path (obs/export.py)

Spec grammar — comma-separated entries, each ``site[:key=value|flag]*``::

    serve.dispatch:persist            every call fails until clear()
    pipeline.prep:at=2                exactly invocation #2 (0-based)
    grow.dispatch:n=2                 the first 2 invocations
    net.send:after=3:n=1              invocation #3 only
    io.read:p=0.1:seed=7              each call fails w.p. 0.1, keyed by
                                      hash(site, index, seed) — the SAME
                                      seed reproduces the SAME failures
    net.connect:n=2:error=oserror     raise an OSError flavor
    serve.dispatch:at=0:persist       trip at #0, stay failed afterwards

Error flavors: ``fault`` (default, :class:`InjectedFault`),
``oserror`` (:class:`InjectedOSError`, an ``OSError`` subclass so
socket/file retry paths treat it like the real thing), ``timeout``
(:class:`InjectedTimeout`, a ``TimeoutError`` subclass).

Injections are counted in obs (``fault.injected`` total plus
``fault.<site>`` per site) so chaos runs can assert the fault actually
fired.  See docs/Robustness.md.
"""

from __future__ import annotations

import hashlib
import os
import threading
from dataclasses import dataclass
from typing import Dict, Optional

from .. import obs
from ..utils.log import LightGBMError, log_warning

ENV_VAR = "LGBM_TPU_FAULTS"

#: sites production code is instrumented with (typo guard at configure;
#: jaxlint JL161 verifies both directions of this registry statically)
KNOWN_SITES = (
    "grow.dispatch", "serve.dispatch", "serve.fleet.dispatch",
    "pipeline.prep", "pipeline.train",
    "net.connect", "net.send", "net.recv", "net.broadcast",
    "io.read", "io.write",
    "stream.parse", "obs.export", "ckpt.ack",
    # soak harness process-level chaos (lightgbm_tpu/soak, docs/Soak.md):
    # kill-and-resume at a scheduled retrain window's ingestion, dead
    # ingest peer on the query-load feed, clock skew at an SLO stamp
    "soak.kill", "soak.load", "soak.clock",
)


def known_sites() -> tuple:
    """The instrumented fault sites, for error messages and tooling —
    the single source the runtime typo guard and JL161 both read."""
    return KNOWN_SITES


class InjectedFault(RuntimeError):
    """A fault raised by the injection registry (never by real code)."""

    def __init__(self, site: str, index: int):
        super().__init__(f"injected fault at site {site!r} "
                         f"(invocation {index})")
        self.site = site
        self.index = index


class InjectedOSError(InjectedFault, OSError):
    """OSError flavor: retry paths guarding sockets/files see it as a
    real transport error."""


class InjectedTimeout(InjectedFault, TimeoutError):
    """TimeoutError flavor for deadline paths."""


_ERROR_KINDS = {
    "fault": InjectedFault,
    "oserror": InjectedOSError,
    "timeout": InjectedTimeout,
}


def _hash_uniform(*key) -> float:
    """Deterministic uniform in [0, 1) from a tuple of hashables —
    stable across processes (unlike ``hash``)."""
    blob = "\x1f".join(str(k) for k in key).encode()
    digest = hashlib.sha256(blob).digest()
    return int.from_bytes(digest[:8], "big") / float(1 << 64)


@dataclass
class FaultRule:
    """One site's parsed injection rule (see module docstring)."""

    site: str
    count: int = 1              # n=: how many eligible calls fail
    after: int = 0              # after=: first eligible invocation index
    at: Optional[int] = None    # at=: exactly this invocation
    prob: float = 0.0           # p=: per-call failure probability
    seed: int = 0               # seed= for the p= mode
    persist: bool = False       # once tripped, fail every later call
    error: str = "fault"        # fault | oserror | timeout
    tripped: bool = False

    def should_fail(self, index: int) -> bool:
        if self.persist and self.tripped:
            return True
        if self.at is not None:
            hit = index == self.at
        elif self.prob > 0.0:
            hit = (index >= self.after
                   and _hash_uniform(self.site, index, self.seed)
                   < self.prob)
        else:
            hit = self.after <= index < self.after + self.count
        if hit:
            self.tripped = True
        return hit

    def make_error(self, index: int) -> InjectedFault:
        return _ERROR_KINDS[self.error](self.site, index)


def parse_fault_spec(spec: str) -> Dict[str, FaultRule]:
    """Parse the spec grammar into per-site rules (last entry wins)."""
    rules: Dict[str, FaultRule] = {}
    for entry in str(spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        site = parts[0].strip()
        if not site:
            raise LightGBMError(f"fault spec entry {entry!r} has no site")
        rule = FaultRule(site=site)
        for tok in parts[1:]:
            tok = tok.strip()
            if tok == "persist":
                rule.persist = True
                continue
            if "=" not in tok:
                raise LightGBMError(
                    f"bad fault spec token {tok!r} in {entry!r} "
                    f"(expected key=value or 'persist')")
            k, v = tok.split("=", 1)
            k = k.strip()
            v = v.strip()
            if k == "n":
                rule.count = int(v)
            elif k == "at":
                rule.at = int(v)
            elif k == "after":
                rule.after = int(v)
            elif k == "p":
                rule.prob = float(v)
            elif k == "seed":
                rule.seed = int(v)
            elif k == "error":
                if v not in _ERROR_KINDS:
                    raise LightGBMError(
                        f"unknown fault error kind {v!r} (expected one "
                        f"of {sorted(_ERROR_KINDS)})")
                rule.error = v
            else:
                raise LightGBMError(
                    f"unknown fault spec key {k!r} in {entry!r}")
        if site not in known_sites():
            log_warning(f"fault spec names unknown site {site!r} "
                        f"(known: {', '.join(known_sites())}); armed "
                        f"anyway for custom check() sites")
        rules[site] = rule
    return rules


class _FaultRegistry:
    """Process-global armed-rule store.  ``active`` is a plain bool read
    on the disarmed fast path; all mutation happens under the lock."""

    def __init__(self):
        self._lock = threading.RLock()
        self._rules: Dict[str, FaultRule] = {}
        self._calls: Dict[str, int] = {}
        self._injected: Dict[str, int] = {}
        self.spec: Optional[str] = None
        self.active = False

    def configure(self, spec: Optional[str]) -> None:
        rules = parse_fault_spec(spec) if spec else {}
        with self._lock:
            self._rules = rules
            self._calls = {}
            self._injected = {}
            self.spec = spec or None
            self.active = bool(rules)

    def clear(self) -> None:
        self.configure(None)

    def check(self, site: str) -> None:
        if not self.active:
            return
        with self._lock:
            rule = self._rules.get(site)
            if rule is None:
                return
            index = self._calls.get(site, 0)
            self._calls[site] = index + 1
            if not rule.should_fail(index):
                return
            self._injected[site] = self._injected.get(site, 0) + 1
        obs.inc("fault.injected")
        obs.inc(f"fault.{site}")
        raise rule.make_error(index)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._injected)

    def calls(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._calls)


_REGISTRY = _FaultRegistry()


def configure(spec: Optional[str]) -> None:
    """Arm the registry from a spec string (``None``/empty disarms)."""
    _REGISTRY.configure(spec)


def configure_from_env() -> None:
    """Arm from ``LGBM_TPU_FAULTS`` if set (no-op otherwise, so library
    import never disturbs an explicitly configured registry)."""
    spec = os.environ.get(ENV_VAR, "")
    if spec:
        _REGISTRY.configure(spec)


def configure_from_config(cfg) -> None:
    """Arm from a Config's ``fault_spec`` param if set.  Idempotent for
    an unchanged spec: re-reading the same config (every retrain
    window's ``init_train`` does) must NOT reset invocation counters —
    an ``at=``/``n=`` rule's progress would restart forever."""
    spec = str(getattr(cfg, "fault_spec", "") or "")
    if spec and spec != _REGISTRY.spec:
        _REGISTRY.configure(spec)


def clear() -> None:
    """Disarm every site and reset call/injection counters."""
    _REGISTRY.clear()


def active() -> bool:
    return _REGISTRY.active


def check(site: str) -> None:
    """The injection point: raises the armed error when ``site``'s rule
    says this invocation fails; near-free when disarmed."""
    _REGISTRY.check(site)


def counts() -> Dict[str, int]:
    """Per-site injected-fault counts since the last configure/clear."""
    return _REGISTRY.counts()


def calls() -> Dict[str, int]:
    """Per-site invocation counts since the last configure/clear."""
    return _REGISTRY.calls()


# arm from the environment at import (like obs): the chaos smokes run
# unmodified entry points with LGBM_TPU_FAULTS exported
configure_from_env()

