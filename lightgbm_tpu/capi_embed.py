"""Flat adapters for the native ``liblgbm_tpu`` shared library.

``src/capi/lgbm_capi.cpp`` embeds CPython and calls these functions to
implement the fork's C/C++ ABI (``/root/reference/include/LightGBM/
c_api.h:38-815``): each adapter takes memoryviews over the CALLER'S
buffers plus plain ints/strings, forwards to the ``c_api.py``
compatibility layer, and RAISES on failure — the C++ layer converts the
exception into the C return-code convention (0 ok / -1 + message via
``LGBM_GetLastError``).

Zero-copy discipline: input pointers arrive as read-only memoryviews
(``np.frombuffer`` wraps them without copying); prediction output is
written directly into the caller's pre-allocated buffer through a
writable memoryview.

Telemetry: importing this module initialises :mod:`lightgbm_tpu.obs`,
which reads ``LGBM_TPU_METRICS`` / ``LGBM_TPU_TRACE`` — so the native
windowed harness gets per-window retrain spans, recompile counts and
memory peaks by exporting two env vars, no C++ change.  Each
``booster_create`` marks a retrain window boundary.
"""
# jaxlint: abi-header=../include/lightgbm_tpu/c_api.h
# jaxlint: abi-impl=../src/capi/lgbm_capi.cpp
# (JL151 cross-checks header<->cpp parity, every call_adapter name and
# Py_BuildValue format against the adapters below, and each forwarded
# _call(C.LGBM_*, ...) against the header's arity and parameter order)

from __future__ import annotations

import numpy as np

from . import c_api as C
from . import compile_cache
from . import obs

# persistent XLA compile cache: the native harness exports
# LGBM_TPU_COMPILE_CACHE=<dir> and every window's programs load from /
# persist to disk — a restarted harness process starts warm (the
# LGBM_WarmupTrain/LGBM_WarmupServe ABI calls pre-fill the same dir)
compile_cache.configure_from_env()


def _arr(mv, dtype_const):
    return np.frombuffer(mv, dtype=C._DTYPE_MAP[dtype_const])


def _call(fn, *args):
    if fn(*args) != 0:
        raise RuntimeError(C.LGBM_GetLastError())


def dataset_from_csr(indptr_mv, indptr_type, indices_mv, data_mv,
                     data_type, nindptr, nelem, num_col, params,
                     ref_handle):
    out = C.Ref()
    with obs.span("capi.dataset_from_csr", cat="capi",
                  rows=int(nindptr) - 1):
        _call(C.LGBM_DatasetCreateFromCSR,
              _arr(indptr_mv, indptr_type), indptr_type,
              _arr(indices_mv, C.C_API_DTYPE_INT32),
              _arr(data_mv, data_type), data_type,
              int(nindptr), int(nelem), int(num_col), params,
              ref_handle or None, out)
    return int(out.value)


def dataset_from_mat(data_mv, data_type, nrow, ncol, is_row_major,
                     params, ref_handle):
    out = C.Ref()
    _call(C.LGBM_DatasetCreateFromMat, _arr(data_mv, data_type),
          data_type, int(nrow), int(ncol), int(is_row_major), params,
          ref_handle or None, out)
    return int(out.value)


def dataset_set_field(handle, field_name, field_mv, num_element, type_):
    _call(C.LGBM_DatasetSetField, handle, field_name,
          _arr(field_mv, type_), int(num_element), type_)


def dataset_num_data(handle):
    out = C.Ref()
    _call(C.LGBM_DatasetGetNumData, handle, out)
    return int(out.value)


def dataset_free(handle):
    _call(C.LGBM_DatasetFree, handle)


def booster_create(train_handle, params):
    out = C.Ref()
    # each fresh booster is one retrain window in the LRB-style harness
    obs.inc("capi.retrain_windows")
    with obs.span("capi.booster_create", cat="capi"):
        _call(C.LGBM_BoosterCreate, train_handle, params, out)
    return int(out.value)


def booster_free(handle):
    _call(C.LGBM_BoosterFree, handle)


def booster_update_one_iter(handle):
    fin = C.Ref()
    with obs.span("capi.update_one_iter", cat="capi"):
        _call(C.LGBM_BoosterUpdateOneIter, handle, fin)
    return int(fin.value)


def booster_update_chunked(handle, n_iters, chunk):
    fin = C.Ref()
    with obs.span("capi.update_chunked", cat="capi",
                  n_iters=int(n_iters), chunk=int(chunk)):
        _call(C.LGBM_BoosterUpdateChunked, handle, int(n_iters),
              int(chunk), fin)
    return int(fin.value)


def booster_calc_num_predict(handle, num_row, predict_type,
                             num_iteration):
    out = C.Ref()
    _call(C.LGBM_BoosterCalcNumPredict, handle, int(num_row),
          predict_type, num_iteration, out)
    return int(out.value)


def booster_predict_for_csr(handle, indptr_mv, indptr_type, indices_mv,
                            data_mv, data_type, nindptr, nelem, num_col,
                            predict_type, num_iteration, params, out_mv):
    out_len = C.Ref()
    out_arr = np.frombuffer(out_mv, np.float64)
    with obs.span("capi.predict_for_csr", cat="capi",
                  rows=int(nindptr) - 1):
        _call(C.LGBM_BoosterPredictForCSR, handle,
              _arr(indptr_mv, indptr_type), indptr_type,
              _arr(indices_mv, C.C_API_DTYPE_INT32),
              _arr(data_mv, data_type), data_type,
              int(nindptr), int(nelem), int(num_col), predict_type,
              num_iteration, params, out_len, out_arr)
    return int(out_len.value)


def serve_create(booster_handle, params):
    out = C.Ref()
    with obs.span("capi.serve_create", cat="capi"):
        _call(C.LGBM_ServeCreate, booster_handle, params, out)
    return int(out.value)


def serve_swap(serve_handle, booster_handle):
    # one swap per retrain window: the server atomically adopts the
    # freshly trained booster's packed ensemble
    with obs.span("capi.serve_swap", cat="capi"):
        _call(C.LGBM_ServeSwap, serve_handle, booster_handle)


def serve_calc_num_predict(serve_handle, num_row):
    out = C.Ref()
    _call(C.LGBM_ServeCalcNumPredict, serve_handle, int(num_row), out)
    return int(out.value)


def serve_predict_for_csr(serve_handle, indptr_mv, indptr_type,
                          indices_mv, data_mv, data_type, nindptr,
                          nelem, num_col, predict_type, out_mv):
    out_len = C.Ref()
    out_arr = np.frombuffer(out_mv, np.float64)
    with obs.span("capi.serve_predict_for_csr", cat="capi",
                  rows=int(nindptr) - 1):
        _call(C.LGBM_ServePredictForCSR, serve_handle,
              _arr(indptr_mv, indptr_type), indptr_type,
              _arr(indices_mv, C.C_API_DTYPE_INT32),
              _arr(data_mv, data_type), data_type,
              int(nindptr), int(nelem), int(num_col), predict_type,
              out_len, out_arr)
    return int(out_len.value)


def serve_free(serve_handle):
    _call(C.LGBM_ServeFree, serve_handle)


def fleet_create(booster_handle, num_tenants, params):
    out = C.Ref()
    with obs.span("capi.fleet_create", cat="capi",
                  tenants=int(num_tenants)):
        _call(C.LGBM_FleetCreate, booster_handle, int(num_tenants),
              params, out)
    return int(out.value)


def fleet_swap_tenant(fleet_handle, tenant_id, booster_handle):
    # one per-tenant swap per retrain window: the fleet index-writes the
    # freshly trained booster while the other tenants keep serving
    with obs.span("capi.fleet_swap_tenant", cat="capi",
                  tenant=int(tenant_id)):
        _call(C.LGBM_FleetSwapTenant, fleet_handle, int(tenant_id),
              booster_handle)


def fleet_calc_num_predict(fleet_handle, num_row):
    out = C.Ref()
    _call(C.LGBM_FleetCalcNumPredict, fleet_handle, int(num_row), out)
    return int(out.value)


def fleet_predict_for_csr(fleet_handle, tenant_ids_mv, num_tenant_ids,
                          indptr_mv, indptr_type, indices_mv, data_mv,
                          data_type, nindptr, nelem, num_col,
                          predict_type, out_mv):
    out_len = C.Ref()
    out_arr = np.frombuffer(out_mv, np.float64)
    with obs.span("capi.fleet_predict_for_csr", cat="capi",
                  rows=int(nindptr) - 1):
        _call(C.LGBM_FleetPredictForCSR, fleet_handle,
              _arr(tenant_ids_mv, C.C_API_DTYPE_INT32),
              int(num_tenant_ids),
              _arr(indptr_mv, indptr_type), indptr_type,
              _arr(indices_mv, C.C_API_DTYPE_INT32),
              _arr(data_mv, data_type), data_type,
              int(nindptr), int(nelem), int(num_col), predict_type,
              out_len, out_arr)
    return int(out_len.value)


def fleet_free(fleet_handle):
    _call(C.LGBM_FleetFree, fleet_handle)


def warmup_train(params, num_row, num_feature):
    out = C.Ref()
    with obs.span("capi.warmup_train", cat="capi", rows=int(num_row)):
        _call(C.LGBM_WarmupTrain, params, int(num_row),
              int(num_feature), out)
    return int(out.value)


def warmup_serve(params, num_row, num_feature):
    out = C.Ref()
    with obs.span("capi.warmup_serve", cat="capi", rows=int(num_row)):
        _call(C.LGBM_WarmupServe, params, int(num_row),
              int(num_feature), out)
    return int(out.value)


def booster_save_model(handle, start_iteration, num_iteration, filename):
    _call(C.LGBM_BoosterSaveModel, handle, start_iteration,
          num_iteration, filename)


def booster_current_iteration(handle):
    out = C.Ref()
    _call(C.LGBM_BoosterGetCurrentIteration, handle, out)
    return int(out.value)
