"""Config: alias resolution, coercion, conflict checking.

Mirrors the behaviour of the reference's ``Config::Set`` pipeline
(``src/io/config.cpp:1-280``): resolve aliases via the generated table, coerce
types, resolve objective/boosting/tree-learner/metric enum aliases, then run
``check_param_conflict``-style fixups (e.g. force parallelism flags, default
metric from objective).  The schema lives in :mod:`lightgbm_tpu.params`.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

from .params import (
    BOOSTING_ALIASES,
    METRIC_ALIASES,
    OBJECTIVE_ALIASES,
    PARAM_ALIASES,
    PARAM_BY_NAME,
    TREE_LEARNER_ALIASES,
)
from .utils.log import log_warning

_RANKING_OBJECTIVES = ("lambdarank",)
_MULTICLASS_OBJECTIVES = ("multiclass", "multiclassova")

# default metric per resolved objective (reference: objective name doubles as
# the default metric string; see config.cpp metric defaulting)
_DEFAULT_METRIC = {
    "regression": "l2",
    "regression_l1": "l1",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "quantile": "quantile",
    "mape": "mape",
    "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss",
    "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg",
}


def _check_range(param, value):
    """Enforce the schema's declared constraint (reference CHECK failures).

    Constraint strings use a small grammar: "> 0", ">= 0.0",
    "0.0 < x <= 1.0", "0.0 <= x < 1.0".
    """
    spec = param.check
    if not spec or not isinstance(value, (int, float)) or isinstance(value, bool):
        return
    ops = {"<": float.__lt__, "<=": float.__le__,
           ">": float.__gt__, ">=": float.__ge__}
    v = float(value)
    parts = spec.split()
    ok = True
    if "x" in parts:
        # "LO <op> x <op> HI"
        lo, op1, _, op2, hi = parts
        ok = ops[op1](float(lo), v) and ops[op2](v, float(hi))
    else:
        op, bound = parts
        ok = ops[op](v, float(bound))
    if not ok:
        raise ValueError(
            f"parameter {param.name}={value} violates constraint {spec}")


def resolve_alias(key: str) -> str:
    """Map a parameter alias to its canonical name (unknown keys pass through)."""
    k = key.strip().lower()
    return PARAM_ALIASES.get(k, k)


def normalize_params(params: Optional[Mapping[str, Any]]) -> Dict[str, Any]:
    """Alias-resolve + type-coerce a raw param mapping.

    Later duplicate aliases of the same canonical key warn and are ignored,
    matching the reference's first-alias-wins ``KV2Map`` behaviour.
    """
    out: Dict[str, Any] = {}
    if not params:
        return out
    for key, value in params.items():
        canon = resolve_alias(key)
        if canon in out and out[canon] != value:
            log_warning(f"{key} is set with {value}, will be ignored. "
                        f"Current value: {canon}={out[canon]}")
            continue
        param = PARAM_BY_NAME.get(canon)
        if param is not None and value is not None:
            try:
                value = param.coerce(value)
            except (TypeError, ValueError) as e:
                raise ValueError(f"bad value for parameter {canon}: {e}") from e
        out[canon] = value
    return out


class Config:
    """Flat config object with one attribute per schema parameter."""

    def __init__(self, params: Optional[Mapping[str, Any]] = None, **kwargs):
        for p in PARAM_BY_NAME.values():
            default = list(p.default) if isinstance(p.default, list) else p.default
            setattr(self, p.name, default)
        self.extra: Dict[str, Any] = {}   # unknown (pass-through) params
        merged = dict(params or {})
        merged.update(kwargs)
        self.raw_params = dict(merged)    # as passed, pre-normalization
        self.set(merged)

    # -- main entry -------------------------------------------------------
    def set(self, params: Mapping[str, Any]) -> "Config":
        norm = normalize_params(params)
        for key, value in norm.items():
            if key in PARAM_BY_NAME:
                _check_range(PARAM_BY_NAME[key], value)
                setattr(self, key, value)
            else:
                self.extra[key] = value
        if "seed" in norm and norm["seed"]:
            # master seed deterministically derives the sub-seeds that were
            # not set explicitly (reference Config behaviour for `seed`)
            from .utils.random import derive_seeds
            derived = derive_seeds(int(norm["seed"]))
            for key, sub in (("data_random_seed", "data"),
                             ("feature_fraction_seed", "feature_fraction"),
                             ("bagging_seed", "bagging"),
                             ("drop_seed", "drop")):
                if key not in norm:
                    setattr(self, key, derived[sub] & 0x7FFFFFFF)
        self._resolve_enums()
        self._check_conflicts()
        return self

    # -- enum-style value aliases ----------------------------------------
    def _resolve_enums(self):
        obj = str(self.objective).strip().lower()
        if obj in OBJECTIVE_ALIASES:
            self.objective = OBJECTIVE_ALIASES[obj]
        else:
            raise ValueError(f"unknown objective: {self.objective}")

        boost = str(self.boosting).strip().lower()
        if boost in BOOSTING_ALIASES:
            self.boosting = BOOSTING_ALIASES[boost]
        else:
            raise ValueError(f"unknown boosting type: {self.boosting}")

        tl = str(self.tree_learner).strip().lower()
        if tl in TREE_LEARNER_ALIASES:
            self.tree_learner = TREE_LEARNER_ALIASES[tl]
        else:
            raise ValueError(f"unknown tree learner: {self.tree_learner}")

        metrics = []
        raw_metric = self.metric if isinstance(self.metric, list) else [self.metric]
        for m in raw_metric:
            m = str(m).strip().lower()
            if m not in METRIC_ALIASES:
                raise ValueError(f"unknown metric: {m}")
            m = METRIC_ALIASES[m]
            if m and m not in metrics:
                metrics.append(m)
        self.metric = metrics

        self.device_type = str(self.device_type).strip().lower()
        if self.device_type == "gpu":
            # the reference's gpu learner maps onto the tpu learner here
            self.device_type = "tpu"
        if self.device_type not in ("cpu", "tpu"):
            raise ValueError(f"unknown device_type: {self.device_type}")

    # -- conflict fixups (reference: Config::CheckParamConflict) ----------
    def _check_conflicts(self):
        if not self.metric and self.objective != "none":
            default = _DEFAULT_METRIC.get(self.objective)
            if default:
                self.metric = [default]
        if "none" in self.metric:
            self.metric = []

        is_parallel = self.tree_learner != "serial"
        if is_parallel and self.num_machines <= 1:
            # single worker: parallel learners degrade to serial, like the
            # reference does when num_machines == 1
            pass
        if self.num_machines > 1:
            self.is_parallel = True
        else:
            self.is_parallel = is_parallel

        if self.objective in _MULTICLASS_OBJECTIVES:
            if self.num_class <= 1:
                raise ValueError("num_class must be > 1 for multiclass objectives")
        elif self.objective not in ("none",):
            if self.num_class != 1:
                raise ValueError(f"num_class must be 1 for objective {self.objective}")

        if self.objective in _RANKING_OBJECTIVES:
            if isinstance(self.eval_at, list):
                self.eval_at = sorted(int(v) for v in self.eval_at)

        # feature_fraction with feature-parallel: reference disables sampling
        if self.tree_learner == "feature" and self.feature_fraction < 1.0:
            log_warning("feature_fraction is ignored with feature-parallel "
                        "tree learner; setting to 1.0")
            self.feature_fraction = 1.0

        if self.boosting == "rf":
            if not (self.bagging_freq > 0 and self.bagging_fraction < 1.0
                    and self.bagging_fraction > 0.0):
                raise ValueError("random forest needs bagging "
                                 "(bagging_freq > 0, 0 < bagging_fraction < 1)")
        if self.boosting == "goss":
            if self.bagging_freq > 0 and self.bagging_fraction != 1.0:
                log_warning("goss ignores bagging_fraction/bagging_freq")
            self.bagging_freq = 0
            self.bagging_fraction = 1.0

        if self.max_depth > 0:
            # like the reference, cap num_leaves implied by depth
            self.num_leaves = min(self.num_leaves, 1 << self.max_depth)

        if self.is_unbalance and self.scale_pos_weight != 1.0:
            raise ValueError("cannot set both is_unbalance and scale_pos_weight")

        if self.tpu_double_precision:
            self.gpu_use_dp = True

        if self.grad_quant_bits not in (0, 8):
            raise ValueError(
                f"grad_quant_bits={self.grad_quant_bits} is not supported:"
                f" use 0 (off) or 8 (int8 quantized histograms)")
        if self.grad_quant_bits and self.gpu_use_dp:
            # dp asks for extra-precision accumulation; quantization asks
            # for less — precision wins, like the reference's gpu_use_dp
            # overriding its single-precision histogram default
            log_warning("grad_quant_bits is ignored with gpu_use_dp "
                        "(double-precision accumulation requested); "
                        "disabling quantized histograms")
            self.grad_quant_bits = 0

        wp = str(self.wave_plan).strip().lower()
        if wp not in ("auto", "fixed", "profiled"):
            raise ValueError(f"unknown wave_plan: {self.wave_plan}")
        self.wave_plan = wp

        fbf = str(self.find_best_fusion).strip().lower()
        if fbf not in ("auto", "fused", "two_pass"):
            raise ValueError(
                f"unknown find_best_fusion: {self.find_best_fusion}")
        self.find_best_fusion = fbf

        dp = str(self.device_predict).strip().lower()
        if dp not in ("auto", "force", "off"):
            raise ValueError(f"unknown device_predict: "
                             f"{self.device_predict}")
        self.device_predict = dp

    # -- misc -------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        d = {p: getattr(self, p) for p in PARAM_BY_NAME}
        d.update(self.extra)
        return d

    def clone(self) -> "Config":
        c = Config.__new__(Config)
        for p in PARAM_BY_NAME.values():
            v = getattr(self, p.name)
            setattr(c, p.name, list(v) if isinstance(v, list) else v)
        c.extra = dict(self.extra)
        c.is_parallel = self.is_parallel
        return c

    def __repr__(self):
        changed = {}
        for p in PARAM_BY_NAME.values():
            v = getattr(self, p.name)
            if v != p.default and not (isinstance(p.default, list)
                                       and list(v) == list(p.default)):
                changed[p.name] = v
        return f"Config({changed})"


def parse_config_str(content: str) -> Dict[str, str]:
    """Parse ``key=value`` lines (CLI config file format; '#' comments)."""
    out: Dict[str, str] = {}
    for line in content.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line or "=" not in line:
            continue
        k, v = line.split("=", 1)
        out[k.strip()] = v.strip()
    return out
