"""CLI application: train / predict / convert_model / refit / pipeline.

Re-implements the reference ``Application`` lifecycle
(``src/application/application.cpp``, ``include/LightGBM/application.h:91-103``)
for the TPU runtime: `key=value` arguments plus a ``config=`` file, side
files (``.weight``/``.query``/``.init``), snapshotting, and metric output
every ``metric_freq`` iterations.  Entry: ``python -m lightgbm_tpu config=...``.
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List

import numpy as np

from .boosting import create_boosting
from .boosting.gbdt import GBDT
from .config import Config, parse_config_str
from .data.dataset import BinnedDataset
from .data.parser import (load_init_score_file, load_query_file,
                          load_text_file, load_weight_file)
from .engine import steps_to_boundary
from .utils.log import LightGBMError, log_info


def parse_cli_args(argv: List[str]) -> Dict[str, str]:
    params: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            continue
        k, v = arg.split("=", 1)
        params[k.strip()] = v.strip()
    if "config" in params and params["config"]:
        from .utils.file_io import open_text
        with open_text(params["config"]) as fh:
            file_params = parse_config_str(fh.read())
        # CLI args take precedence over config-file values
        file_params.update(params)
        params = file_params
    return params


def _load_dataset(path: str, cfg: Config, reference=None) -> BinnedDataset:
    if BinnedDataset.is_binary_file(path):
        return BinnedDataset.load_binary(path)
    from .ops.shard import sharding_mode
    if sharding_mode(cfg) == "multi_controller" and reference is None:
        # pod-slice ingest: host 0 finds bins, every host streams and
        # bins only its own contiguous row block (docs/Sharding.md)
        from .data.stream_loader import load_text_multihost
        cats = _parse_categorical(cfg, 1 << 30)
        ds, _ = load_text_multihost(path, cfg, categorical=cats)
        md = ds.metadata
        w = load_weight_file(path + ".weight")
        if w is not None:
            md.set_weights(w)
        q = load_query_file(path + ".query")
        if q is not None:
            md.set_query(q)
        init = load_init_score_file(path + ".init")
        if init is not None:
            md.set_init_score(init.T.reshape(-1) if init.ndim > 1
                              else init)
        return ds
    if getattr(cfg, "two_round", False):
        # streaming two-round load: never materializes the float64
        # matrix (dataset_loader.cpp:161-264, pipeline_reader.h:19-66)
        from .data.stream_loader import load_text_two_round
        cats = _parse_categorical(cfg, 1 << 30)
        ds, _ = load_text_two_round(path, cfg, categorical=cats,
                                    reference=reference)
        md = ds.metadata
        w = load_weight_file(path + ".weight")
        if w is not None:
            md.set_weights(w)
        q = load_query_file(path + ".query")
        if q is not None:
            md.set_query(q)
        init = load_init_score_file(path + ".init")
        if init is not None:
            md.set_init_score(init.T.reshape(-1) if init.ndim > 1 else init)
        return ds
    arr, label, names = load_text_file(path, cfg)
    cats = _parse_categorical(cfg, arr.shape[1])
    ds = BinnedDataset.construct_from_matrix(
        arr, cfg, cats, feature_names=names, reference=reference)
    ds._raw = arr
    md = ds.metadata
    if label is not None:
        md.set_label(label)
    w = load_weight_file(path + ".weight")
    if w is not None:
        md.set_weights(w)
    q = load_query_file(path + ".query")
    if q is not None:
        md.set_query(q)
    init = load_init_score_file(path + ".init")
    if init is not None:
        md.set_init_score(init.T.reshape(-1) if init.ndim > 1 else init)
    return ds


def _parse_categorical(cfg: Config, num_features: int) -> List[int]:
    spec = getattr(cfg, "categorical_feature", []) or []
    out = []
    for c in spec:
        c = str(c)
        if c.startswith("name:"):
            continue
        try:
            out.append(int(c))
        except ValueError:
            pass
    return [c for c in out if 0 <= c < num_features]


def run_train(cfg: Config):
    start = time.time()
    train_ds = _load_dataset(cfg.data, cfg)
    log_info(f"Finished loading data in {time.time() - start:.6f} seconds")
    booster = create_boosting(cfg)
    booster.init_train(train_ds)
    valid_paths = cfg.valid if isinstance(cfg.valid, list) else [cfg.valid]
    for i, vp in enumerate(v for v in valid_paths if v):
        vds = _load_dataset(str(vp), cfg, reference=train_ds)
        booster.add_valid(vds, f"valid_{i + 1}")

    num_iters = int(cfg.num_iterations)
    snapshot_freq = int(getattr(cfg, "snapshot_freq", -1) or -1)
    metric_freq = max(int(cfg.metric_freq), 1)
    fused_cap = max(int(getattr(cfg, "fused_chunk", 20)), 0)
    out_model = cfg.output_model or "LightGBM_model.txt"
    if getattr(cfg, "resume_training", False):
        # fault tolerance (docs/Robustness.md): adopt the newest
        # snapshot whose exact-score sidecar exists and continue —
        # byte-identical to the uninterrupted run
        from .robust.checkpoint import latest_snapshot
        snap = latest_snapshot(out_model)
        if snap is not None:
            booster.resume_from_checkpoint(snap)
        else:
            from .utils.log import log_warning
            log_warning(f"resume_training requested but no resumable "
                        f"{out_model}.snapshot_iter_* found; training "
                        f"from scratch")
    start = time.time()
    # fused driving (GBDT.train_chunked): iterations between metric /
    # snapshot boundaries run as one device dispatch; per-iteration
    # fallback otherwise.  Boundary cadence — when metrics or snapshots
    # are due — is byte-identical to the per-iteration loop.
    can_fuse = fused_cap > 1 and booster.fused_eligible()
    it = booster.iter          # nonzero after resume_training
    while it < num_iters:
        step = 1
        if can_fuse:
            step = num_iters - it
            if booster.train_metrics or booster.valid_sets:
                step = min(step, steps_to_boundary(it, metric_freq))
            if snapshot_freq > 0:
                step = min(step, steps_to_boundary(it, snapshot_freq))
        if step > 1:
            before = booster.iter
            finished = booster.train_chunked(step,
                                             chunk=min(step, fused_cap))
            advanced = max(booster.iter - before, 1)
        else:
            finished = booster.train_one_iter()
            advanced = 1
        it_done = it + advanced - 1
        if (it_done + 1) % metric_freq == 0 or it_done == num_iters - 1:
            for dname, mname, value, _ in (booster.eval_train()
                                           + booster.eval_valid()):
                log_info(f"Iteration:{it_done + 1}, {dname} {mname} : "
                         f"{value:g}")
        # one progress line per iteration like the reference CLI — for a
        # fused chunk the covered iterations' lines are emitted together
        # at chunk end (same count and format, so log parsers keep
        # working; elapsed is read at print time)
        for j in range(it, it + advanced):
            log_info(f"{time.time() - start:.6f} seconds elapsed, "
                     f"finished iteration {j + 1}")
        if snapshot_freq > 0 and (it_done + 1) % snapshot_freq == 0:
            # atomic model + exact-score state sidecar: the snapshot a
            # killed run resumes from (resume_training=true / --resume)
            booster.save_checkpoint(
                f"{out_model}.snapshot_iter_{it_done + 1}")
        it += advanced
        if finished:
            break
    booster.save_model_to_file(out_model)
    log_info("Finished training")


def run_predict(cfg: Config):
    """Streaming file prediction: parse chunks behind a double-buffered
    reader, predict each on device, append to the output file — the
    TPU build's analog of the reference's parallel line pipeline
    (``predictor.hpp:170-259``); peak memory is one chunk, not the file."""
    model_path = cfg.input_model or "LightGBM_model.txt"
    booster = GBDT.load_model_from_file(model_path, cfg)
    out = cfg.output_result or "LightGBM_predict_result.txt"
    num_it = int(getattr(cfg, "num_iteration_predict", -1) or -1)
    kw = dict(num_iteration=num_it,
              raw_score=bool(cfg.predict_raw_score),
              pred_leaf=bool(cfg.predict_leaf_index),
              pred_contrib=bool(cfg.predict_contrib))

    from .data.stream_loader import iter_parsed_chunks
    from .utils.file_io import exists
    if not exists(cfg.data):
        # validate BEFORE truncating the output file: the chunk iterator
        # is lazy and would only fail after open(out, "w") destroyed any
        # previous predictions
        raise LightGBMError(f"could not open data file {cfg.data}")
    nf = booster.max_feature_idx + 1
    n_rows = 0
    with open(out, "w") as fh:
        for x, _ in iter_parsed_chunks(cfg.data, cfg, nf):
            if x.shape[0] == 0:
                continue
            if x.shape[1] < nf:
                x = np.pad(x, ((0, 0), (0, nf - x.shape[1])),
                           constant_values=np.nan)
            pred = np.asarray(booster.predict(x[:, :nf], **kw))
            pred2 = np.atleast_2d(pred)
            if pred2.shape[0] == 1 and pred.ndim == 1:
                pred2 = pred2.T
            np.savetxt(fh, pred2, delimiter="\t", fmt="%g")
            n_rows += pred2.shape[0]
    log_info(f"Finished prediction of {n_rows} rows, saved to {out}")


def run_convert_model(cfg: Config):
    model_path = cfg.input_model or "LightGBM_model.txt"
    booster = GBDT.load_model_from_file(model_path, cfg)
    out = cfg.convert_model or "gbdt_prediction.cpp"
    lines = ["#include <cmath>", "#include <cstdint>", ""]
    for i, tree in enumerate(booster.models):
        lines.append(tree.to_if_else(i, False))
    n = len(booster.models)
    calls = " + ".join(f"PredictTree{i}(arr)" for i in range(n)) or "0.0"
    lines.append("double Predict(const double* arr) {\n"
                 f"  return {calls};\n}}\n")
    with open(out, "w") as fh:
        fh.write("\n".join(lines))
    log_info(f"Finished converting model to C++ code {out}")


def run_refit(cfg: Config):
    model_path = cfg.input_model or "LightGBM_model.txt"
    from .basic import Booster
    booster = Booster(model_file=model_path, params={})
    arr, label, _ = load_text_file(cfg.data, cfg)
    new_booster = booster.refit(arr, label,
                                decay_rate=float(cfg.refit_decay_rate))
    out = cfg.output_model or "LightGBM_model.txt"
    new_booster.save_model(out)
    log_info("Finished refitting")


def run_pipeline(cfg: Config):
    """Windowed-retrain pipeline over the training file
    (docs/Pipeline.md): the rows are replayed as ``pipeline_windows``
    equal windows; each window is scored against the currently served
    model (test-then-train), then retrained per ``window_policy`` with
    host prep of the NEXT window overlapped against device training,
    and hot-swapped into the serving ensemble.  The final window's
    model is saved to ``output_model``."""
    import json

    from .pipeline import PreppedWindow, RetrainPipeline

    arr, label, _ = load_text_file(cfg.data, cfg)
    if label is None:
        raise LightGBMError("task=pipeline requires labeled data")
    nw = max(int(cfg.pipeline_windows), 1)
    bounds = np.linspace(0, arr.shape[0], nw + 1).astype(np.int64)
    payloads = [(int(bounds[i]), int(bounds[i + 1])) for i in range(nw)]
    cats = _parse_categorical(cfg, arr.shape[1])
    objective = str(cfg.objective)

    def prep(payload):
        lo, hi = payload
        return PreppedWindow(label=label[lo:hi], dense=arr[lo:hi],
                             eval_label=label[lo:hi],
                             eval_dense=arr[lo:hi])

    def eval_fn(pred, pw):
        # test-then-train quality of the PREVIOUS model on this window
        y = np.asarray(pw.eval_label, np.float64)
        p = np.asarray(pred, np.float64)
        if objective.startswith("binary"):
            return {"prev_model_error":
                    round(float(np.mean((p >= 0.5) != (y >= 0.5))), 5)}
        if p.ndim > 1:   # multiclass: argmax error
            return {"prev_model_error":
                    round(float(np.mean(np.argmax(p, axis=1) != y)), 5)}
        return {"prev_model_rmse":
                round(float(np.sqrt(np.mean((p - y) ** 2))), 6)}

    ckpt_dir = str(getattr(cfg, "pipeline_checkpoint_dir", "") or "")
    if getattr(cfg, "resume_training", False):
        from .robust.checkpoint import has_pipeline_checkpoint
        if not ckpt_dir:
            raise LightGBMError(
                "task=pipeline resume_training needs "
                "pipeline_checkpoint_dir")
        if has_pipeline_checkpoint(ckpt_dir):
            pipe = RetrainPipeline.resume(ckpt_dir, cfg,
                                          categorical=cats,
                                          keep_boosters=False)
        else:
            from .utils.log import log_warning
            log_warning(f"resume_training requested but no pipeline "
                        f"checkpoint in {ckpt_dir}; starting at "
                        f"window 0")
            pipe = RetrainPipeline(cfg, categorical=cats,
                                   keep_boosters=False)
    else:
        pipe = RetrainPipeline(cfg, categorical=cats, keep_boosters=False)
    results = pipe.run(payloads, prep, eval_fn=eval_fn,
                       on_window=lambda r: log_info(
                           "pipeline window " + json.dumps(r.to_json())))
    frac = pipe.overlap_fraction
    if frac is not None:
        log_info(f"pipeline prep overlap fraction: {frac:.3f}")
    booster = pipe.final_booster()
    if booster is not None:
        booster.save_model_to_file(cfg.output_model
                                   or "LightGBM_model.txt")
    log_info(f"Finished pipeline ({len(results)} windows)")


def run_soak(cfg: Config):
    """Composed fleet chaos soak (docs/Soak.md): stand up the
    scenario's M-tenant fleet, drive mixed-tenant load + per-tenant
    retrains under the seed-keyed fault timeline, and print the
    SLO-gated verdict JSON.  Exits nonzero when any gate fails."""
    import json

    from .soak import SoakScenario, run_and_report

    sc = SoakScenario.from_config(cfg)
    verdict = run_and_report(sc)
    print(json.dumps(verdict, sort_keys=True, default=str))
    if sc.out:
        log_info(f"soak verdict written to {sc.out}")
    if not verdict["ok"]:
        raise LightGBMError(
            "soak verdict FAILED: "
            + ", ".join(name for name, g in verdict["gates"].items()
                        if not g["ok"]))
    log_info("Finished soak (verdict ok)")


def run_warmup(cfg: Config):
    """Ahead-of-time compile warmup (docs/ColdStart.md): precompile the
    declared (rows, features, config) training + serving program
    families into the persistent compile cache, so a deployment's first
    real window runs warm."""
    from .warmup import run_warmup as _run
    _run(cfg)
    log_info("Finished warmup")


def main(argv=None):
    argv = list(argv if argv is not None else sys.argv[1:])
    # `lightgbm-tpu warmup|pipeline key=value...` subcommand sugar
    if argv and argv[0] in ("warmup", "pipeline", "soak"):
        argv = argv[1:] + [f"task={argv[0]}"]
    # `--resume` sugar: continue a killed run from its last snapshot /
    # pipeline checkpoint (docs/Robustness.md)
    argv = ["resume_training=true" if a == "--resume" else a
            for a in argv]
    params = parse_cli_args(argv)
    if not params:
        print("usage: python -m lightgbm_tpu config=train.conf [key=value...]\n"
              "       python -m lightgbm_tpu warmup warmup_rows=N "
              "warmup_features=F [key=value...]")
        return 1
    cfg = Config(params)
    # every task benefits from the persistent compile cache (train via
    # init_train too, but predict/convert/warmup configure here)
    from . import compile_cache
    compile_cache.configure_from_config(cfg)
    from .robust import faults
    faults.configure_from_config(cfg)
    task = cfg.task
    if task == "train":
        run_train(cfg)
    elif task in ("predict", "prediction", "test"):
        run_predict(cfg)
    elif task == "convert_model":
        run_convert_model(cfg)
    elif task in ("refit", "refit_tree"):
        run_refit(cfg)
    elif task == "warmup":
        run_warmup(cfg)
    elif task == "pipeline":
        run_pipeline(cfg)
    elif task == "soak":
        run_soak(cfg)
    else:
        raise LightGBMError(f"unknown task: {task}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
