"""Training callbacks (reference ``python-package/lightgbm/callback.py``)."""

from __future__ import annotations

import collections

from .utils.log import log_info, log_warning


class EarlyStopException(Exception):
    def __init__(self, best_iteration, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "LightGBMCallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _format_eval_result(value, show_stdv=True):
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def print_evaluation(period=1, show_stdv=True):
    def _callback(env):
        if (period > 0 and env.evaluation_result_list
                and (env.iteration + 1) % period == 0):
            result = "\t".join(_format_eval_result(x, show_stdv)
                               for x in env.evaluation_result_list)
            log_info(f"[{env.iteration + 1}]\t{result}")
    _callback.order = 10
    # acts only on iterations that carry evaluation results, so the
    # fused driver may skip its empty-list invocations (engine.train)
    _callback.eval_cadence_only = True
    return _callback


def record_evaluation(eval_result):
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")
    eval_result.clear()

    def _init(env):
        for data_name, eval_name, _, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env):
        if not eval_result:
            _init(env)
        for data_name, eval_name, result, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)
    _callback.order = 20
    _callback.eval_cadence_only = True
    return _callback


def reset_parameter(**kwargs):
    def _callback(env):
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to "
                        f"'num_boost_round'.")
                new_param = value[env.iteration - env.begin_iteration]
            else:
                new_param = value(env.iteration - env.begin_iteration)
            new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds, first_metric_only=False, verbose=True):
    best_score = []
    best_iter = []
    best_score_list = []
    cmp_op = []
    enabled = [True]

    def _init(env):
        enabled[0] = not any(
            env.params.get(alias, "") == "dart"
            for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            log_warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        if verbose:
            log_info(f"Training until validation scores don't improve for "
                     f"{stopping_rounds} rounds.")
        for eval_ret in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if eval_ret[3]:
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _callback(env):
        if not best_score:
            mf = max(int(env.params.get("metric_freq", 1) or 1), 1)
            if (not env.evaluation_result_list
                    and (env.iteration + 1) % mf != 0
                    and env.iteration != env.end_iteration - 1):
                # evaluation was SKIPPED this iteration (metric_freq>1,
                # off-cadence): defer init to the first eval-carrying
                # invocation so fused and per-iteration driving behave
                # identically.  An empty list ON an eval-cadence
                # iteration means no eval data is configured at all —
                # _init raises its configuration error immediately,
                # before device time is wasted
                return
            _init(env)
        if not enabled[0]:
            return
        for i in range(len(env.evaluation_result_list)):
            score = env.evaluation_result_list[i][2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            elif env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log_info("Early stopping, best iteration is:\n"
                             f"[{best_iter[i] + 1}]\t"
                             + "\t".join(_format_eval_result(x)
                                         for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if (env.iteration == env.end_iteration - 1):
                if verbose:
                    log_info("Did not meet early stopping. Best iteration "
                             "is:\n"
                             f"[{best_iter[i] + 1}]\t"
                             + "\t".join(_format_eval_result(x)
                                         for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if first_metric_only:
                break
    _callback.order = 30
    _callback.eval_cadence_only = True
    # engine.train refuses to fuse when this callback is present with no
    # eval data configured, so _init's configuration error still fires
    # on the FIRST iteration, not after a full fused run
    _callback.requires_eval = True
    return _callback
