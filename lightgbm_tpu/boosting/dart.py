"""DART boosting (reference ``src/boosting/dart.hpp``)."""

from __future__ import annotations

import numpy as np

from ..ops.traverse import add_tree_score, device_tree
from .gbdt import GBDT


class DART(GBDT):
    """Dropout trees: per iteration drop a random subset of prior trees from
    the training score, train on the modified residual, then run the
    three-step normalization (dart.hpp:86-186)."""


    def init_train(self, train_set, objective=None):
        super().init_train(train_set, objective)
        self._drop_rng = np.random.RandomState(
            self.config.drop_seed & 0x7FFFFFFF)
        self.tree_weight = []
        self.sum_weight = 0.0
        self.drop_index = []
        self.is_constant_hessian = False

    # -- score helpers -------------------------------------------------
    def _add_tree_everywhere(self, tree, k, train=True, valid=True):
        dt = device_tree(tree, self.train_set, self.config.num_leaves)
        if train:
            self.train_score = self.train_score.at[k].set(
                add_tree_score(self.train_score[k], self.learner.binned,
                               dt, 1.0))
        if valid:
            for v in self.valid_sets:
                v.score = v.score.at[k].set(
                    add_tree_score(v.score[k], v.binned_d, dt, 1.0))

    # ------------------------------------------------------------------
    def _dropping_trees(self):
        cfg = self.config
        self.drop_index = []
        is_skip = self._drop_rng.rand() < cfg.skip_drop
        if not is_skip and self.iter > 0:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                inv_avg = len(self.tree_weight) / max(self.sum_weight, 1e-35)
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate, cfg.max_drop * inv_avg
                                    / max(self.sum_weight, 1e-35))
                for i in range(self.iter):
                    if self._drop_rng.rand() < (drop_rate
                                                * self.tree_weight[i]
                                                * inv_avg):
                        self.drop_index.append(self.num_init_iteration + i)
                        if (cfg.max_drop > 0
                                and len(self.drop_index) >= cfg.max_drop):
                            break
            else:
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / self.iter)
                for i in range(self.iter):
                    if self._drop_rng.rand() < drop_rate:
                        self.drop_index.append(self.num_init_iteration + i)
                        if (cfg.max_drop > 0
                                and len(self.drop_index) >= cfg.max_drop):
                            break
        # device path: dropped trees are re-scaled in place, so pending
        # device records must be materialized first — and the valid
        # scores caught up NOW, because _normalize edits them with
        # per-tree deltas that are only sound once every prior tree
        # actually reached them.  Both happen only when something was
        # dropped: skip_drop iterations stay fully async (flushing or
        # catching up every iteration would block the one-dispatch
        # pipeline the device grower is built around).
        if self.drop_index and self._grower is not None:
            if self.valid_sets:
                self._catch_up_valid_scores()
            else:
                self._flush_pending()
            if self._device_stop:
                # the flush trimmed trailing stalled iterations (training
                # is over): drop_index was drawn over the pre-trim range
                # and may index past the shrunk model list — and there is
                # nothing left to train on anyway
                self.drop_index = []
                return
        # subtract dropped trees from the training score
        self._negate_dropped_into_train()
        k_drop = len(self.drop_index)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + k_drop)
        else:
            self.shrinkage_rate = (cfg.learning_rate if k_drop == 0
                                   else cfg.learning_rate
                                   / (cfg.learning_rate + k_drop))

    def _negate_dropped_into_train(self):
        """Flip every dropped tree's sign in place and fold the delta into
        the training score.  Called once to drop (original -> -1x) and
        again to undo when training stops before _normalize."""
        for i in self.drop_index:
            for k in range(self.num_model):
                tree = self.models[i * self.num_model + k]
                tree.apply_shrinkage(-1.0)
                self._add_tree_everywhere(tree, k, train=True, valid=False)

    def _normalize(self):
        # valid scores were caught up in _dropping_trees (device path)
        cfg = self.config
        k = float(len(self.drop_index))
        for i in self.drop_index:
            for cid in range(self.num_model):
                tree = self.models[i * self.num_model + cid]
                if not cfg.xgboost_dart_mode:
                    tree.apply_shrinkage(1.0 / (k + 1.0))
                    self._add_tree_everywhere(tree, cid, train=False,
                                              valid=True)
                    tree.apply_shrinkage(-k)
                    self._add_tree_everywhere(tree, cid, train=True,
                                              valid=False)
                else:
                    tree.apply_shrinkage(self.shrinkage_rate)
                    self._add_tree_everywhere(tree, cid, train=False,
                                              valid=True)
                    tree.apply_shrinkage(-k / cfg.learning_rate)
                    self._add_tree_everywhere(tree, cid, train=True,
                                              valid=False)
            if not cfg.uniform_drop:
                if not cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[
                        i - self.num_init_iteration] * (1.0 / (k + 1.0))
                    self.tree_weight[i - self.num_init_iteration] *= \
                        k / (k + 1.0)
                else:
                    self.sum_weight -= self.tree_weight[
                        i - self.num_init_iteration] \
                        * (1.0 / (k + cfg.learning_rate))
                    self.tree_weight[i - self.num_init_iteration] *= \
                        k / (k + cfg.learning_rate)

    # ------------------------------------------------------------------
    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        self._dropping_trees()
        ret = super().train_one_iter(gradients, hessians)
        if ret:
            # training stopped before _normalize could restore the
            # dropped trees: undo the drop (re-negate back to the
            # original values and re-add to the training score) so the
            # stored model is consistent with predict().  The reference
            # leaves the trees sign-flipped here (dart.hpp:52-58 returns
            # before Normalize) — a latent defect in a stopped-training
            # edge case, deliberately not reproduced; the device path's
            # retroactive stall trim would hit it on every DART stall.
            self._negate_dropped_into_train()
            self.drop_index = []
            return ret
        self._normalize()
        if not self.config.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False
