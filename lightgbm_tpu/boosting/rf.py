"""Random-forest mode (reference ``src/boosting/rf.hpp``)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..utils.log import LightGBMError
from .gbdt import GBDT


class RF(GBDT):
    """Random forest: fixed targets (-label / -onehot), unit hessians, no
    shrinkage, bagging mandatory, averaged output (rf.hpp:18-207)."""


    def __init__(self, config):
        super().__init__(config)
        self.average_output = True

    def init_train(self, train_set, objective=None):
        super().init_train(train_set, objective)
        cfg = self.config
        if not (cfg.bagging_freq > 0 and 0.0 < cfg.bagging_fraction < 1.0):
            raise LightGBMError("RF mode requires bagging "
                                "(bagging_freq > 0, bagging_fraction in (0,1))")
        self.shrinkage_rate = 1.0
        label = np.asarray(train_set.metadata.label, np.float32)
        n = train_set.num_data
        if self.num_model == 1:
            grad = -label[None, :]
        else:
            grad = np.zeros((self.num_model, n), np.float32)
            grad[label.astype(np.int64), np.arange(n)] = -1.0
        self._rf_grad = jnp.asarray(grad)
        self._rf_hess = jnp.ones((self.num_model, n), jnp.float32)
        self.is_constant_hessian = False

    def boost_from_average(self, class_id):
        return 0.0

    def _device_gradients(self):
        return self._rf_grad, self._rf_hess, [0.0] * self.num_model

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        if gradients is not None or hessians is not None:
            raise LightGBMError("RF mode does not support custom objectives")
        if self._grower is not None:
            return self._train_one_iter_device()
        self.bagging(self.iter)
        should_continue = False
        for k in range(self.num_model):
            from ..tree.tree import Tree
            tree = Tree(2)
            if self.train_set.num_features > 0:
                tree = self.learner.train(
                    self._rf_grad[k], self._rf_hess[k],
                    indices_buffer=self.bag_buffer,
                    data_count=self.bag_count
                    if self.bag_buffer is not None else None)
            if tree.num_leaves > 1:
                should_continue = True
                self._renew_tree_output(tree, k)
                self.update_score(tree, k)   # no shrinkage; scores are sums
            self.models.append(tree)
        if not should_continue:
            del self.models[-self.num_model:]
            return True
        self.iter += 1
        return False

    def _averaged(self, score):
        iters = max(self.num_iterations(), 1)
        return score / iters

    # The averaged score already IS the output (e.g. a probability for
    # binary labels), so metrics must NOT re-convert through the objective
    # (reference rf.hpp EvalOneMetric passes nullptr).
    def eval_train(self):
        out = []
        if not self.train_metrics:
            return out
        score = self._averaged(np.asarray(self.train_score, np.float64))
        for m in self.train_metrics:
            for name, value in m.eval(score, None):
                out.append(("training", name, value, m.bigger_is_better))
        return out

    def eval_valid(self):
        out = []
        if self._grower is not None:
            self._catch_up_valid_scores()
        for v in self.valid_sets:
            score = self._averaged(np.asarray(v.score, np.float64))
            for m in v.metrics:
                for name, value in m.eval(score, None):
                    out.append((v.name, name, value, m.bigger_is_better))
        return out
