"""GBDT: the boosting iteration loop, bagging, scores, model ser/de.

Re-design of the reference ``GBDT`` (``src/boosting/gbdt.cpp``,
``gbdt_model_text.cpp``) for the TPU runtime: scores live on device as
(num_model, N) float32; gradients come from jitted objectives; the tree
learner owns the device partition; validation scores update through the
on-device tree traversal.  Model text format is the reference's "v2".
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..config import Config
from ..data.dataset import BinnedDataset
from ..metrics import create_metrics
from ..objectives import create_objective
from ..ops import stage_plan as stage_plan_mod
from ..ops.grow import DeviceGrower, device_growth_eligible
from ..ops.traverse import add_tree_score, device_tree
from ..robust import checkpoint as _checkpoint
from ..robust import faults
from ..robust.retry import (RetryPolicy, transient_dispatch_errors,
                            with_retries)
from ..tree.tree import Tree
from ..utils.log import LightGBMError, log_info, log_warning
from ..parallel import create_tree_learner

K_EPSILON = 1e-15
MODEL_VERSION = "v2"

#: dispatch errors worth a bounded retry (resolved once: the JAX
#: runtime error type moved across versions)
_TRANSIENT_DISPATCH = transient_dispatch_errors()


class _ValidSet:
    __slots__ = ("dataset", "binned_d", "score", "metrics", "name",
                 "applied_models")

    def __init__(self, dataset, binned_d, score, metrics, name):
        self.dataset = dataset
        self.binned_d = binned_d
        self.score = score
        self.metrics = metrics
        self.name = name
        self.applied_models = 0     # models already added to `score`


def _replay_records(rec_i, rec_f, rec_c, nl, shrinkage, bias, dataset,
                    config) -> Tree:
    """Replay host-side split records of one device-grown tree into a
    ``Tree`` (rec_i/rec_f/rec_c are numpy, nl an int)."""
    tree = Tree(config.num_leaves)
    if nl <= 1:
        # stump: the grower applied NOTHING to the training scores
        # (grow.py zeroes the update when nl<=1), so the materialized
        # tree must carry 0 too — only the boost_from_average bias
        # (added below) reaches the model, matching the host path at
        # GBDT.train_one_iter's stump branch
        tree.leaf_value[0] = 0.0
    else:
        from ..tree.tree import categorical_bitsets
        is_cat_f = np.asarray(dataset.f_is_categorical)
        for s in range(nl - 1):
            leaf, right, f, thr, dl = (int(v) for v in rec_i[s])
            (gain, lg, lh, lc, rg, rh, rc, lout, rout) = (
                float(v) for v in rec_f[s])
            real_f = dataset.used_features[f]
            mapper = dataset.bin_mappers[real_f]
            missing = dataset.f_missing_type[f]
            if is_cat_f[f]:
                words = rec_c[s].astype(np.uint32)
                member_bins = [
                    b for b in range(min(mapper.num_bin, 256))
                    if (words[b >> 5] >> (b & 31)) & 1]
                bitset_inner, bitset = categorical_bitsets(
                    mapper, member_bins)
                tree.split_categorical(
                    leaf, f, real_f, bitset_inner, bitset, lout,
                    rout, int(lc), int(rc), gain, missing)
            else:
                tree.split(leaf, f, real_f, thr,
                           mapper.bin_to_value(thr), lout, rout,
                           int(lc), int(rc), gain, missing, bool(dl))
        tree.apply_shrinkage(shrinkage)
    if abs(bias) > K_EPSILON:
        tree.add_bias(bias)
    return tree


class _Pending:
    """Marker base for lazily-materialized device-grown trees."""


class _PendingTree(_Pending):
    """Device-side split records of a tree grown by the DeviceGrower;
    replayed into a host ``Tree`` lazily (``GBDT._flush_pending``)."""

    __slots__ = ("rec_i", "rec_f", "rec_c", "nl", "root_value",
                 "shrinkage", "bias")

    def __init__(self, rec_i, rec_f, rec_c, nl, root_value, shrinkage,
                 bias):
        self.rec_i = rec_i
        self.rec_f = rec_f
        self.rec_c = rec_c
        self.nl = nl
        self.root_value = root_value
        self.shrinkage = shrinkage
        self.bias = bias
        for arr in (rec_i, rec_f, rec_c, nl, root_value):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass

    def materialize(self, dataset, config) -> Tree:
        return _replay_records(np.asarray(self.rec_i),
                               np.asarray(self.rec_f),
                               np.asarray(self.rec_c),
                               int(np.asarray(self.nl)),
                               self.shrinkage, self.bias, dataset, config)


class _RecStack:
    """Stacked split records of a fused chunk of trees
    (``DeviceGrower.fused_train`` output): ONE async device->host copy
    serves every tree in the chunk."""

    __slots__ = ("arrs", "_host", "qscales")

    def __init__(self, rec_i, rec_f, rec_c, nl, qscales=None):
        self.arrs = (rec_i, rec_f, rec_c, nl)
        self._host = None
        # (K, 2) per-tree quantization scales (grad_quant_bits only);
        # fetched lazily with the lagged stall check so gauge recording
        # never blocks the dispatch pipeline
        self.qscales = qscales
        for a in self.arrs + ((qscales,) if qscales is not None else ()):
            try:
                a.copy_to_host_async()
            except AttributeError:
                pass

    def host(self):
        if self._host is None:
            self._host = tuple(np.asarray(a) for a in self.arrs)
            self.arrs = None
        return self._host


class _PendingChunkTree(_Pending):
    """One tree of a fused chunk: index ``idx`` into a shared _RecStack."""

    __slots__ = ("stack", "idx", "shrinkage", "bias")

    def __init__(self, stack, idx, shrinkage, bias):
        self.stack = stack
        self.idx = idx
        self.shrinkage = shrinkage
        self.bias = bias

    def materialize(self, dataset, config) -> Tree:
        rec_i, rec_f, rec_c, nl = self.stack.host()
        return _replay_records(rec_i[self.idx], rec_f[self.idx],
                               rec_c[self.idx], int(nl[self.idx]),
                               self.shrinkage, self.bias, dataset, config)


class GBDT:
    """Gradient Boosting Decision Tree driver."""

    def __init__(self, config: Config):
        self.config = config
        self.models: List[Tree] = []
        self.iter = 0
        self.train_set: Optional[BinnedDataset] = None
        self.objective = None
        self.num_model = 1
        self.shrinkage_rate = config.learning_rate
        self.valid_sets: List[_ValidSet] = []
        self.train_metrics = []
        self.num_init_iteration = 0
        self.average_output = False
        self.loaded_objective_str = ""
        self.loaded_parameters = ""
        self.max_feature_idx = 0
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self._bag_rng = np.random.RandomState(config.bagging_seed & 0x7FFFFFFF)
        self.class_need_train: List[bool] = [True]
        self.best_iteration = -1
        self._grower = None
        self._device_stop = False
        # in-flight (num_leaves handles, quant-scale handle) per
        # iteration, fetched with a 4-iteration lag
        self._nl_queue: List = []
        self._wave_handles: List = []  # per-iter wave counts (device scalars)
        self._fused_grad = False    # cached objective.device_grad() result
        self._last_chunk_stack = None   # previous fused chunk's _RecStack
        self._row_mask_cache = None     # device bagging mask (per draw)
        self._bag_buffer = None

    # ------------------------------------------------------------------
    def init_train(self, train_set: BinnedDataset, objective=None):
        cfg = self.config
        # telemetry: params may enable the obs subsystem; in the windowed
        # harness this runs once per retrain window, so it must stay
        # additive (cross-window recompile/memory totals are the point)
        obs.configure_from_config(cfg)
        # persistent XLA compile cache: params/env may point every jit
        # this booster compiles at an on-disk store, so a fresh process
        # (the windowed harness restarts, deployments roll) re-loads
        # executables instead of recompiling (docs/ColdStart.md)
        from .. import compile_cache
        compile_cache.configure_from_config(cfg)
        # fault injection arms from params the same way (chaos/CI only;
        # idempotent for an unchanged spec so windows share counters)
        faults.configure_from_config(cfg)
        obs.inc("train.init_train")
        obs.instant("init_train", cat="boost",
                    rows=int(train_set.num_data),
                    features=int(train_set.num_features))
        # re-init invalidates the fused-path caches (gargs hold the OLD
        # dataset's label arrays; a stale stall stack would trip the
        # first chunk's lagged check)
        self._fused_grad = False
        self._last_chunk_stack = None
        self.train_set = train_set
        self.objective = objective if objective is not None \
            else create_objective(cfg)
        if self.objective is not None:
            self.objective.init(train_set.metadata, train_set.num_data)
            self.num_model = self.objective.num_model_per_iteration
            self.class_need_train = [
                self.objective.class_need_train(k)
                for k in range(self.num_model)]
        else:
            self.num_model = max(int(cfg.num_class), 1)
            self.class_need_train = [True] * self.num_model
        self.learner = create_tree_learner(cfg, train_set)
        if getattr(cfg, "forcedsplits_filename", ""):
            import json
            with open(cfg.forcedsplits_filename) as fh:
                self.learner.forced_splits = json.load(fh)
            log_info(f"Loaded forced splits from "
                     f"{cfg.forcedsplits_filename}")
        n = train_set.num_data
        self.num_data = n
        self.train_score = jnp.zeros((self.num_model, n), jnp.float32)
        md = train_set.metadata
        self.has_init_score = md.init_score is not None
        if self.has_init_score:
            # class-major layout [k*num_data + i], like the reference's
            # Metadata (metadata.cpp checks the exact size and Fatal()s on
            # mismatch; a silently clamped (1, N) here trained wrong
            # multiclass models)
            init = np.asarray(md.init_score, np.float64).reshape(-1)
            if len(init) != n * self.num_model:
                raise LightGBMError(
                    f"Initial score size doesn't match data size: got "
                    f"{len(init)}, expected num_data * num_model = "
                    f"{n} * {self.num_model}")
            self.train_score = jnp.asarray(
                init.reshape(self.num_model, n), jnp.float32)
        self.train_metrics = create_metrics(cfg)
        for m in self.train_metrics:
            m.init(md, n)
        self.feature_names = list(train_set.feature_names)
        self.max_feature_idx = train_set.num_total_features - 1
        self.feature_infos = [
            m.feature_info_str() if m is not None else "none"
            for m in train_set.bin_mappers]
        # bagging state
        self.bag_fraction = cfg.bagging_fraction
        self.bag_freq = cfg.bagging_freq
        self.need_bagging = self.bag_fraction < 1.0 and self.bag_freq > 0
        self.bag_buffer = None
        self.bag_count = n
        self.is_constant_hessian = bool(
            self.objective and self.objective.is_constant_hessian
            and not self.need_bagging)
        # on-device wave grower (one dispatch per iteration, no per-split
        # host sync) when the configuration is eligible
        mode = str(getattr(cfg, "device_growth", "off")).lower()
        from ..ops import shard as shard_mod
        shard_wanted = shard_mod.sharding_mode(cfg) in (
            "single_controller", "multi_controller")
        # data_sharding is an explicit opt-in, so device_growth=auto
        # turns the grower on for it even off-TPU (the sharded scan IS
        # the device grower; the host learner cannot shard this way)
        want = mode == "on" or (mode == "auto"
                                and (jax.default_backend() == "tpu"
                                     or shard_wanted))
        if want:
            serial = (cfg.tree_learner == "serial"
                      or int(cfg.num_machines) <= 1)
            mesh = shard_mod.resolve_shard_mesh(cfg) \
                if (serial and shard_wanted) else None
            n_shards = int(mesh.devices.size) if mesh is not None else 1
            if serial and device_growth_eligible(cfg, train_set,
                                                 self.objective,
                                                 self.num_model,
                                                 n_shards=n_shards):
                # row bucketing needs row-local fused gradients (a
                # bucket-padded row must not perturb real rows):
                # lambdarank's query-segment formula opts out
                bucket_ok = (bool(getattr(cfg, "train_row_bucketing",
                                          True))
                             and getattr(self.objective,
                                         "device_grad_rowwise", True))
                self._grower = DeviceGrower(train_set, cfg,
                                            row_bucketing=bucket_ok,
                                            mesh=mesh)
                log_info("Using on-device tree growth (device_growth="
                         f"{mode})")
                wp = str(getattr(cfg, "wave_plan", "auto")).lower()
                if getattr(self._grower, "_multihost", False):
                    # plan profiling is TIMING-derived: two pod hosts
                    # measuring independently could adopt different
                    # stage plans and trace DIFFERENT programs — the
                    # mesh would deadlock on the first psum.  Every
                    # host keeps the deterministic default ladder
                    # (profiled plans come back when a broadcast-
                    # verdict path exists)
                    if wp == "profiled":
                        log_warning(
                            "wave_plan=profiled is disabled under "
                            "data_sharding=multi_controller (per-host "
                            "timing verdicts may diverge); using the "
                            "fixed ladder")
                elif wp == "profiled":
                    # measure per-stage wave cost on the real binned
                    # matrix and install the derived stage plan; the
                    # plan is cached per (shape, config) signature (in
                    # process + persisted beside the compile cache), so
                    # later windows AND fresh processes skip the
                    # measurement
                    self._grower.profile_stage_plan()
                elif (wp == "auto"
                      and self._grower.plan_source == "default"
                      and self._grower.num_data
                      >= stage_plan_mod.AUTO_PROFILE_MIN_ROWS
                      and stage_plan_mod.store_dir() is not None):
                    # profile-on-first-use at production scale: measure
                    # once, install the derived plan only when it beats
                    # the byte-stable legacy ladder by the 2% bar, and
                    # persist the verdict either way (a persisted or
                    # in-process plan sets plan_source != "default", so
                    # this never re-measures).  Gated on an ACTIVE plan
                    # store (= a persistent compile cache): probe
                    # timings are noisy, so an unpersistable plan would
                    # make same-config processes grow different trees —
                    # breaking the checkpoint-resume byte-identity
                    # contract (docs/Robustness.md) across process
                    # restarts.  With the store active, the first
                    # process persists its verdict at init and every
                    # later process (including a crash-resume) adopts
                    # it from disk instead of re-measuring.
                    self._grower.profile_stage_plan(
                        require_beat_legacy=True)
            elif shard_mod.sharding_mode(cfg) == "multi_controller":
                # a pod host cannot silently fall back to the host
                # learner: its dataset may be a local shard and its
                # peers would wedge on the histogram psum
                raise LightGBMError(
                    "data_sharding=multi_controller requires the "
                    "device grower (tree_learner=serial and an "
                    "eligible configuration: no monotone constraints/"
                    "renew objective/forced splits, dataset under the "
                    "striped-count bound) — refusing to fall back on "
                    "a pod slice")
            elif mode == "on":
                log_warning("device_growth=on requested but the "
                            "configuration is not eligible (monotone "
                            "constraints/renew objective/forced splits); "
                            "falling back to the host-driven learner")
        elif shard_mod.sharding_mode(cfg) == "multi_controller":
            raise LightGBMError(
                "data_sharding=multi_controller requires device_growth"
                "=on|auto (the pod-slice trainer IS the fused device "
                "scan)")

    def add_valid(self, valid_set: BinnedDataset, name: str):
        if not valid_set.check_align(self.train_set):
            raise LightGBMError(
                "cannot add validation data, since it has different bin "
                "mappers with training data")
        metrics = create_metrics(self.config)
        for m in metrics:
            m.init(valid_set.metadata, valid_set.num_data)
        score = jnp.zeros((self.num_model, valid_set.num_data), jnp.float32)
        if valid_set.metadata.init_score is not None:
            init = np.asarray(valid_set.metadata.init_score,
                              np.float64).reshape(-1)
            if len(init) != valid_set.num_data * self.num_model:
                raise LightGBMError(
                    f"Initial score size doesn't match data size: got "
                    f"{len(init)}, expected "
                    f"{valid_set.num_data} * {self.num_model}")
            score = jnp.asarray(
                init.reshape(self.num_model, valid_set.num_data),
                jnp.float32)
        vs = _ValidSet(valid_set, jnp.asarray(valid_set.binned), score,
                       metrics, name)
        # device path: models that predate this valid set are skipped in
        # catch-up, matching the host path (which only applies new trees)
        vs.applied_models = len(self.models)
        self.valid_sets.append(vs)

    # ------------------------------------------------------------------
    def boost_from_average(self, class_id: int) -> float:
        cfg = self.config
        if (self.models or self.has_init_score or self.objective is None):
            return 0.0
        if cfg.boost_from_average or self.train_set.num_features == 0:
            init_score = self.objective.boost_from_score(class_id)
            if abs(init_score) > K_EPSILON:
                self.train_score = self.train_score.at[class_id].add(
                    init_score)
                if self._grower is None:
                    # device path: valid sets receive the bias through the
                    # materialized first tree at catch-up time instead
                    for v in self.valid_sets:
                        v.score = v.score.at[class_id].add(init_score)
                log_info(f"Start training from score {init_score:f}")
                return init_score
        elif self.objective.name in ("regression_l1", "quantile", "mape"):
            log_warning(f"Disabling boost_from_average in "
                        f"{self.objective.name} may cause the slow "
                        f"convergence")
        return 0.0

    # ------------------------------------------------------------------
    @property
    def bag_buffer(self):
        return self._bag_buffer

    @bag_buffer.setter
    def bag_buffer(self, value):
        # every assignment (GBDT.bagging, GOSS's per-iteration selection)
        # invalidates the cached device row mask derived from it
        self._bag_buffer = value
        self._row_mask_cache = None

    def bagging(self, it: int):
        """Row bagging via a device bernoulli mask partition
        (gbdt.cpp:161-243 semantics, binomial count).  The selection layout
        is the learner's (serial: one permutation buffer; data-parallel:
        per-shard buffers), so it delegates to ``learner.bagging_state``."""
        if not self.need_bagging or it % self.bag_freq != 0:
            return
        seed = (self.config.bagging_seed + it) & 0x7FFFFFFF
        self.bag_buffer, self.bag_count = self.learner.bagging_state(
            seed, self.bag_fraction)

    def _tree_multiplier(self) -> float:
        return 1.0

    # ------------------------------------------------------------------
    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        """One boosting iteration; returns True when training should stop
        (no splittable leaves), mirroring GBDT::TrainOneIter."""
        device = (self._grower is not None and gradients is None
                  and hessians is None)
        if not obs.enabled():
            return self._train_one_iter_device() if device \
                else self._train_one_iter_host(gradients, hessians)
        # note: without obs sync the device path's span covers dispatch,
        # not device execution (dispatch is async); enable sync profiling
        # for honest per-iteration device attribution
        with obs.span("train.iter", cat="boost", iteration=self.iter,
                      path="device" if device else "host") as sp:
            out = self._train_one_iter_device() if device \
                else self._train_one_iter_host(gradients, hessians)
            sp.sync_value = self.train_score
        obs.sample_device_memory()
        return out

    def _forbid_host_path(self, what: str) -> None:
        """The host learner's row-global paths (its own ``train``,
        traversal-based score updates) index the FULL binned matrix; a
        pod-slice host only holds its own row block, so reaching them
        under ``data_sharding=multi_controller`` must fail loudly
        instead of training on garbage rows."""
        if getattr(self._grower, "_multihost", False):
            raise LightGBMError(
                f"{what} is not supported under data_sharding="
                f"multi_controller: it needs the host learner's full "
                f"binned matrix, and a pod-slice host holds only its "
                f"own row block")

    def _train_one_iter_host(self, gradients=None, hessians=None) -> bool:
        self._forbid_host_path("host-path training (custom gradients "
                              "or device_growth fallback)")
        init_scores = [0.0] * self.num_model
        if gradients is None or hessians is None:
            for k in range(self.num_model):
                init_scores[k] = self.boost_from_average(k)
            grad, hess = self.objective.get_gradients(self.train_score)
            if grad.ndim == 1:
                grad, hess = grad[None, :], hess[None, :]
        else:
            grad = jnp.asarray(np.asarray(gradients, np.float32)
                               ).reshape(self.num_model, -1)
            hess = jnp.asarray(np.asarray(hessians, np.float32)
                               ).reshape(self.num_model, -1)
        grad, hess = self._adjust_gradients(grad, hess)
        self.bagging(self.iter)
        grad, hess = self._post_bagging_adjust(grad, hess)

        should_continue = False
        for k in range(self.num_model):
            tree = Tree(2)
            if self.class_need_train[k] and self.train_set.num_features > 0:
                tree = self.learner.train(
                    grad[k], hess[k],
                    indices_buffer=self.bag_buffer,
                    data_count=self.bag_count
                    if self.bag_buffer is not None else None)
            if tree.num_leaves > 1:
                should_continue = True
                self._renew_tree_output(tree, k)
                tree.apply_shrinkage(self.shrinkage_rate
                                     * self._tree_multiplier())
                self.update_score(tree, k)
                if abs(init_scores[k]) > K_EPSILON:
                    tree.add_bias(init_scores[k])
            else:
                if len(self.models) < self.num_model:
                    if not self.class_need_train[k]:
                        output = (self.objective.boost_from_score(k)
                                  if self.objective else 0.0)
                    else:
                        output = init_scores[k]
                    tree = Tree(2)
                    tree.leaf_value[0] = output
                    if abs(output) > K_EPSILON:
                        self.train_score = self.train_score.at[k].add(output)
                        for v in self.valid_sets:
                            v.score = v.score.at[k].add(output)
            self.models.append(tree)

        if not should_continue:
            log_warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            if len(self.models) > self.num_model:
                del self.models[-self.num_model:]
            return True
        self.iter += 1
        return False

    # ------------------------------------------------------------------
    # on-device fast path: one dispatch per class per iteration, no
    # per-split sync
    def _device_row_mask(self):
        """(N,) f32 0/1 in-bag indicator from the learner's permutation
        buffer, or None when every row is in the bag.  Cached until the
        next bagging draw: the scatter that builds it costs ~30 ns/row
        on TPU, which at bagging_freq > 1 would otherwise dominate small
        trees (measured ~60 ms/iteration at 2M rows)."""
        if self.bag_buffer is None or self.bag_count >= self.num_data:
            return None
        if self._row_mask_cache is None:
            buf = jnp.asarray(self.bag_buffer)
            sel = (jnp.arange(buf.shape[0]) < self.bag_count)
            mask = jnp.zeros((buf.shape[0],), jnp.float32).at[buf].set(
                sel.astype(jnp.float32), mode="drop")
            self._row_mask_cache = mask[:self.num_data]
        return self._row_mask_cache

    def _device_gradients(self):
        """(grad (K,N), hess (K,N), per-class init biases) for the
        device path; RF overrides with its fixed targets."""
        init_scores = [self.boost_from_average(k)
                       for k in range(self.num_model)]
        grad, hess = self.objective.get_gradients(self.train_score)
        if grad.ndim == 1:
            grad, hess = grad[None, :], hess[None, :]
        grad, hess = self._adjust_gradients(grad, hess)
        return grad, hess, init_scores

    def _dispatch_guard(self, fn):
        """Run a device-dispatch thunk under the ``grow.dispatch`` fault
        site with ``dispatch_retries`` bounded retries on TRANSIENT
        runtime errors (accelerator preemption, a wedged runtime, an
        injected fault).  Deterministic programs re-dispatch with
        identical inputs, so a retry can never change results; anything
        non-transient (shape/type errors) propagates immediately."""
        def attempt():
            faults.check("grow.dispatch")
            return fn()
        retries = int(getattr(self.config, "dispatch_retries", 2))
        if retries <= 0:
            return attempt()
        policy = RetryPolicy(max_attempts=retries + 1, base_delay_s=0.05,
                             max_delay_s=1.0,
                             retry_on=_TRANSIENT_DISPATCH)
        return with_retries(attempt, policy, site="grow.dispatch")

    def _train_one_iter_device(self) -> bool:
        if self._device_stop:
            return True
        grad, hess, init_scores = self._device_gradients()
        self.bagging(self.iter)
        grad, hess = self._post_bagging_adjust(grad, hess)
        row_mask = self._device_row_mask()
        shrink = self.shrinkage_rate * self._tree_multiplier()
        nls = []
        last_qscale = None
        first_iter = len(self.models) < self.num_model
        for k in range(self.num_model):
            if not self.class_need_train[k]:
                # fixed stump, host-path semantics (train_one_iter's
                # stump branch): only the first iteration's stump
                # carries the class's constant output
                tree = Tree(2)
                if first_iter:
                    output = (self.objective.boost_from_score(k)
                              if self.objective else 0.0)
                    tree.leaf_value[0] = output
                    if abs(output) > K_EPSILON:
                        self.train_score = \
                            self.train_score.at[k].add(output)
                self.models.append(tree)
                continue
            # fresh feature_fraction draw per tree, fold_in-keyed by the
            # global tree index so the fused scan draws the SAME masks
            # (grow.feature_fraction_mask; the host learner keeps its
            # own numpy stream)
            tree_idx = self.iter * self.num_model + k
            mask = self._grower.feature_mask_for(tree_idx)
            score, rec_i, rec_f, rec_c, nl, root_val, waves, qscale = \
                self._dispatch_guard(functools.partial(
                    self._grower.grow_one_iter, self.train_score[k],
                    grad[k], hess[k], mask, shrink, row_mask,
                    tree_idx=tree_idx))
            self.train_score = self.train_score.at[k].set(score)
            last_qscale = qscale
            self._wave_handles.append(waves)
            self.models.append(_PendingTree(
                rec_i, rec_f, rec_c, nl, root_val, shrink,
                init_scores[k]))
            nls.append(nl)
        self.iter += 1
        # stump check: inspect num_leaves with a 4-iteration lag — the
        # handles' async copies have long landed by then (each iteration
        # is hundreds of ms of device work), so this never blocks the
        # host and never stalls the dispatch pipeline, yet training
        # stops at most 4 wasted dispatches after a stall (the reference
        # checks every iteration, gbdt.cpp:412).  Quantization-scale
        # gauge handles ride the same queue (same lag, same fetch point).
        if not (last_qscale is not None and obs.enabled()
                and getattr(self._grower, "quant_bits", 0)):
            last_qscale = None
        self._nl_queue.append((nls, last_qscale))
        if len(self._nl_queue) > 4:
            old, old_qs = self._nl_queue.pop(0)
            if old_qs is not None:
                self._record_quant_scales(jax.device_get(old_qs).tolist())
            # one batched fetch of the lagged handles (their async copies
            # landed iterations ago) instead of a blocking per-class
            # round trip
            if old and max(jax.device_get(old)) <= 1:
                self._trim_device_stumps()
                return True
        return False

    # ------------------------------------------------------------------
    # fused multi-iteration device path: K whole boosting iterations per
    # dispatch (lax.scan over trees, gradients computed on device)
    def _fused_grad_fn(self):
        """(grad_fn, gargs) when fused multi-iteration training is sound
        for the CURRENT state, else None.  Sound means: plain GBDT (no
        DART/GOSS/RF overrides), single model, and an objective exposing
        a pure device gradient.  Bagging and feature_fraction no longer
        disqualify: their draws moved inside the fused scan
        (DeviceGrower.fused_train), which is what lets the fork
        harness's exact config (feature_fraction=0.8, bagging_freq=5)
        use the fastest path."""
        if (self._grower is None or type(self) is not GBDT
                or self.num_model != 1
                or self.train_set.num_features == 0
                or self.objective is None
                or not self.class_need_train[0]):
            return None
        if (getattr(self._grower, "mesh", None) is not None
                and not getattr(self.objective, "device_grad_rowwise",
                                True)):
            # sharded fused gradients run per shard on LOCAL rows, so
            # the formula must be row-local (lambdarank's query-segment
            # sums are not); the per-iteration sharded path still works
            # (gradients come in globally computed)
            return None
        if self._fused_grad is False:
            self._fused_grad = self.objective.device_grad()
        return self._fused_grad

    def fused_eligible(self) -> bool:
        """Whether train_chunked will actually fuse (public accessor)."""
        return self._fused_grad_fn() is not None

    def train_chunked(self, n_iters: int, chunk: int = 20,
                      snapshot_freq: int = 0,
                      snapshot_path: str = "") -> bool:
        """Train ``n_iters`` boosting iterations, fusing ``chunk`` whole
        iterations into one device dispatch when the configuration
        allows (see :meth:`_train_chunked_inner`); with
        ``snapshot_freq > 0``, additionally cut each dispatch at the
        snapshot boundaries and write an atomic checkpoint
        (``<snapshot_path>.snapshot_iter_N`` + exact-score state
        sidecar, :meth:`save_checkpoint`) every ``snapshot_freq``
        iterations — a killed 500-iteration run then resumes from the
        last snapshot (:meth:`resume_from_checkpoint`) instead of
        iteration 0.  Returns True when training stopped early."""
        freq = int(snapshot_freq)
        if freq <= 0 or n_iters <= 0:
            return self._train_chunked_inner(n_iters, chunk)
        path = str(snapshot_path
                   or self.config.output_model or "LightGBM_model.txt")
        done = 0
        while done < n_iters:
            step = min(n_iters - done, freq - self.iter % freq)
            before = self.iter
            stopped = self._train_chunked_inner(step, chunk)
            done += self.iter - before
            if (self.iter > before and self.iter % freq == 0
                    and not stopped):
                with obs.span("train.snapshot", cat="boost",
                              iteration=self.iter):
                    self.save_checkpoint(
                        f"{path}.snapshot_iter_{self.iter}")
                obs.inc("train.snapshots")
            if stopped:
                return True
        return False

    def _train_chunked_inner(self, n_iters: int, chunk: int = 20) -> bool:
        """The chunked training core (no snapshotting).  Returns True
        when training stopped early (no more splittable leaves).

        The fused path exists because the per-iteration driver loop is
        host-latency-bound under CPU contention (each tree takes ~5
        Python-side steps); one dispatch per ``chunk`` trees keeps the
        device fed regardless of host load.  Semantics match the
        per-iteration device path: same gradients, same trees, same
        scores; the stall check lags by one chunk instead of 4
        iterations, and ``_flush_pending`` trims trailing stump
        iterations exactly as before.
        """
        fg = self._fused_grad_fn()
        # a request smaller than the chunk still deserves ONE fused
        # dispatch of its own length (otherwise update_chunked(15) with
        # the default chunk=20 would silently run fully per-iteration)
        chunk = min(chunk, n_iters)
        if fg is None or chunk <= 1:
            for _ in range(n_iters):
                if self.train_one_iter():
                    return True
            return False
        grad_fn, gargs = fg
        lr = jnp.asarray(self.shrinkage_rate * self._tree_multiplier(),
                         jnp.float32)
        done = 0
        fused_ran = False
        while done < n_iters:
            if self._device_stop:
                return True
            k = min(chunk, n_iters - done)
            if k < chunk:
                # remainder: per-iteration path (a second scan length
                # would cost a fresh XLA compile of the whole program)
                if fused_ran:
                    self._sync_fused_bagging()
                for _ in range(k):
                    if self.train_one_iter():
                        return True
                return False
            bias = self.boost_from_average(0) if not self.models else 0.0
            fused = self._grower.fused_train(chunk)
            t0 = time.perf_counter() if obs.enabled() else None
            score, (rec_i, rec_f, rec_c, nl, _root, waves, qscales) = \
                self._dispatch_guard(lambda: fused(
                    self._grower.binned, self._grower.binned_t,
                    self.train_score[0], lr, gargs,
                    jnp.asarray(self.iter, jnp.int32), grad_fn=grad_fn))
            if t0 is not None:
                self._obs_chunk(t0, chunk, score)
            self.train_score = self.train_score.at[0].set(score)
            quant = bool(getattr(self._grower, "quant_bits", 0))
            stack = _RecStack(rec_i, rec_f, rec_c, nl,
                              qscales if quant else None)
            for i in range(chunk):
                self.models.append(_PendingChunkTree(
                    stack, i, self.shrinkage_rate * self._tree_multiplier(),
                    bias if i == 0 else 0.0))
            self._wave_handles.append(waves)
            self.iter += chunk
            done += chunk
            fused_ran = True
            # lagged stall check: the PREVIOUS chunk's records have
            # landed by now (this chunk is seconds of device work), so
            # reading them never blocks the dispatch pipeline
            prev, self._last_chunk_stack = self._last_chunk_stack, stack
            if prev is not None:
                if prev.qscales is not None and obs.enabled():
                    # lagged fetch (the previous chunk's copies landed
                    # long ago): record the chunk's last per-tree
                    # quantization scales without stalling dispatch
                    self._record_quant_scales(
                        np.asarray(prev.qscales)[-1].tolist())
                if (prev.host()[3] <= 1).all():
                    self._trim_device_stumps()
                    return True
        if fused_ran:
            self._sync_fused_bagging()
        return False

    def _sync_fused_bagging(self):
        """Restore the host-side bagging state to what a pure
        per-iteration run would hold at ``self.iter``: fused chunks draw
        their row masks inside the scan without touching
        ``bag_buffer``, so a later per-iteration step (chunk remainder,
        ``Booster.update``, ``rollback_one_iter``'s traversal) must
        first re-materialize the draw of the last redraw boundary to
        continue bit-identically."""
        if not self.need_bagging or self.iter <= 0:
            return
        # the draw active after iteration (self.iter - 1) — NOT
        # self.iter's own boundary: when self.iter is itself a redraw
        # multiple, the per-iteration path still holds the previous
        # boundary's mask until bagging(self.iter) runs, and a
        # rollback_one_iter + update continues from that one
        last_done = self.iter - 1
        it_last = last_done - last_done % self.bag_freq
        seed = (self.config.bagging_seed + it_last) & 0x7FFFFFFF
        self.bag_buffer, self.bag_count = self.learner.bagging_state(
            seed, self.bag_fraction)

    def _obs_chunk(self, t0, chunk, score):
        """Record one fused multi-iteration dispatch: a ``train.chunk``
        span plus ``chunk`` synthetic ``train.iter`` observations (the
        chunk mean) so iteration counts/percentiles stay comparable with
        the per-iteration paths.  Without obs sync this times the
        dispatch, not device execution."""
        from ..obs.state import STATE
        if STATE.sync:
            jax.block_until_ready(score)
        dt = time.perf_counter() - t0
        STATE.registry.observe("train.chunk", dt)
        STATE.registry.inc("train.fused_chunks")
        STATE.registry.set_gauge("train.fused_chunk_len", chunk)
        STATE.trace.add("train.chunk", cat="boost", t0=t0, dur=dt,
                        args={"iteration": self.iter, "chunk": chunk})
        for _ in range(chunk):
            STATE.registry.observe("train.iter", dt / chunk)
        obs.sample_device_memory()

    @staticmethod
    def _record_quant_scales(pair) -> None:
        """Record an already-fetched lagged (scale_g, scale_h) pair —
        the single place the gauge names live for both the
        per-iteration and fused paths."""
        sg_v, sh_v = pair
        obs.set_gauge("quant.scale_g", sg_v)
        obs.set_gauge("quant.scale_h", sh_v)

    def _trim_device_stumps(self):
        """Remove trailing stump iterations (the device path keeps
        dispatching until the lagged check notices training stalled).
        A first-iteration stump (carrying the boost_from_average bias)
        is kept, matching the host path's stump branch."""
        self._device_stop = True
        self._nl_queue.clear()
        self._flush_pending()
        log_warning("Stopped training because there are no more leaves "
                    "that meet the split requirements")

    def _flush_pending(self):
        """Materialize all device-grown trees into host ``Tree`` objects,
        then drop trailing all-stump iterations: an iteration where
        EVERY class produced a stump is exactly the host path's
        should_continue=False stop condition (train_one_iter), so the
        device path trims those iterations here (not just at the lagged
        stall check) to keep predict()/save consistent with the training
        scores no matter when training stopped."""
        pending = [i for i, m in enumerate(self.models)
                   if isinstance(m, _Pending)]
        if pending:
            with obs.span("flush_pending", cat="boost",
                          trees=len(pending)):
                for i in pending:
                    self.models[i] = self.models[i].materialize(
                        self.train_set, self.config)
        if self._grower is not None:
            nm = max(self.num_model, 1)
            while (len(self.models) > nm
                   and all(t.num_leaves <= 1
                           for t in self.models[-nm:])):
                del self.models[-nm:]
                self.iter -= 1
                self._device_stop = True

    def _catch_up_valid_scores(self):
        """Apply not-yet-applied models to every valid set's score (the
        device path defers valid updates to evaluation time)."""
        if not self.valid_sets:
            return
        self._flush_pending()
        total = len(self.models)
        for v in self.valid_sets:
            while v.applied_models < total:
                idx = v.applied_models
                tree = self.models[idx]
                if tree.num_leaves > 1:
                    dt = device_tree(tree, self.train_set,
                                     self.config.num_leaves)
                    v.score = v.score.at[idx % self.num_model].set(
                        add_tree_score(v.score[idx % self.num_model],
                                       v.binned_d, dt, 1.0))
                else:
                    # stump carrying the boost_from_average bias: one
                    # host read reused for check and update (a 1-leaf
                    # traversal would apply the same constant)
                    stump = tree.leaf_value[0]
                    if abs(stump) > K_EPSILON:
                        v.score = v.score.at[idx % self.num_model].add(
                            stump)
                v.applied_models = idx + 1

    def _adjust_gradients(self, grad, hess):
        return grad, hess

    def _post_bagging_adjust(self, grad, hess):
        return grad, hess

    # ------------------------------------------------------------------
    def _renew_tree_output(self, tree: Tree, class_id: int):
        """Percentile leaf renewal for L1-style objectives
        (serial_tree_learner.cpp:780-818)."""
        obj = self.objective
        if obj is None or not obj.is_renew_tree_output:
            return
        score = np.asarray(self.train_score[class_id], np.float64)
        label = np.asarray(obj.label, np.float64)
        leaf_rows = self.learner.leaf_indices_host()
        if obj.name == "mape":
            w = obj.label_weight
        else:
            w = obj.weights
        for leaf, rows in leaf_rows.items():
            if len(rows) == 0:
                continue
            residuals = label[rows] - score[rows]
            lw = w[rows] if w is not None else None
            tree.set_leaf_output(
                leaf, obj.renew_tree_output(tree.leaf_value[leaf],
                                            residuals, lw))

    def update_score(self, tree: Tree, class_id: int):
        """Train (partition or traversal when bagging) + valid scores."""
        if self.bag_buffer is not None and self.bag_count < self.num_data:
            dt = device_tree(tree, self.train_set, self.config.num_leaves)
            self.train_score = self.train_score.at[class_id].set(
                add_tree_score(self.train_score[class_id],
                               self.learner.traverse_binned, dt, 1.0))
        else:
            self.train_score = self.train_score.at[class_id].set(
                self.learner.update_score(self.train_score[class_id], tree))
            dt = None
        for v in self.valid_sets:
            if dt is None:
                dt = device_tree(tree, self.train_set, self.config.num_leaves)
            v.score = v.score.at[class_id].set(
                add_tree_score(v.score[class_id], v.binned_d, dt, 1.0))

    # ------------------------------------------------------------------
    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        if not self.train_metrics:
            return out
        score = np.asarray(self.train_score, np.float64)
        for m in self.train_metrics:
            for name, value in m.eval(score, self.objective):
                out.append(("training", name, value, m.bigger_is_better))
        return out

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        if self._grower is not None:
            self._catch_up_valid_scores()
        for v in self.valid_sets:
            score = np.asarray(v.score, np.float64)
            for m in v.metrics:
                for name, value in m.eval(score, self.objective):
                    out.append((v.name, name, value, m.bigger_is_better))
        return out

    # ------------------------------------------------------------------
    def num_iterations(self) -> int:
        return len(self.models) // max(self.num_model, 1)

    def rollback_one_iter(self):
        """Remove the last iteration's trees and scores (gbdt.cpp:414-430).

        Valid-set scores on the device path lag behind the model list
        (they are caught up lazily at eval time), so a popped tree is
        only subtracted from a valid set that actually received it, and
        ``applied_models`` is clamped so the replacement tree trained at
        the same index is re-applied at the next catch-up."""
        if not self.models:
            return
        self._forbid_host_path("rollback_one_iter")
        self._flush_pending()
        base = len(self.models) - self.num_model
        for k in range(self.num_model):
            tree = self.models[base + k]
            if tree.num_leaves > 1:
                dt = device_tree(tree, self.train_set, self.config.num_leaves)
                self.train_score = self.train_score.at[k].set(
                    add_tree_score(self.train_score[k], self.learner.traverse_binned,
                                   dt, -1.0))
                for v in self.valid_sets:
                    # host path applies trees to valid scores eagerly in
                    # update_score (without touching applied_models), so
                    # the lag guard only applies on the device path
                    if (self._grower is None
                            or v.applied_models > base + k):
                        v.score = v.score.at[k].set(
                            add_tree_score(v.score[k], v.binned_d, dt, -1.0))
        del self.models[-self.num_model:]
        for v in self.valid_sets:
            v.applied_models = min(v.applied_models, len(self.models))
        self.iter -= 1

    # ------------------------------------------------------------------
    # prediction (raw host data)
    def _early_stop_instance(self):
        """Row-wise prediction early stopping
        (src/boosting/prediction_early_stop.cpp:1-89): binary stops a row
        once 2*|margin| exceeds the threshold, multiclass once the top-two
        class margin does; checked every ``pred_early_stop_freq`` trees."""
        cfg = self.config
        if not getattr(cfg, "pred_early_stop", False):
            return None
        obj_name = (self.objective.name if self.objective is not None
                    else (self.loaded_objective_str.split()[0]
                          if self.loaded_objective_str else ""))
        margin = float(cfg.pred_early_stop_margin)
        freq = max(int(cfg.pred_early_stop_freq), 1)
        if obj_name.startswith("binary") and self.num_model == 1:
            return freq, lambda out: 2.0 * np.abs(out[0]) > margin
        if self.num_model > 1:
            def mc(out):
                part = np.partition(out, self.num_model - 2, axis=0)
                return part[-1] - part[-2] > margin
            return freq, mc
        log_warning("pred_early_stop is only supported for binary and "
                    "multiclass objectives; ignoring")
        return None

    def _predict_raw_packed(self, data, end_iter, start_iteration):
        """Batch prediction through the packed-forest kernel
        (``serve/packed.py``): the whole tree slice flattens into one
        set of padded device arrays keyed on RAW feature values and the
        batch routes through every tree in a SINGLE jitted dispatch —
        no binning, no ``train_set``, so file-loaded models take this
        path too.  Leaf ROUTING is bit-identical to the host walk
        (hi/lo float32 threshold pairs reproduce the float64 compare);
        ACCUMULATION is float32 on device vs the host path's float64,
        so values differ ~1e-6 relative across the row threshold (see
        docs/Serving.md).

        The pack is cached per (slice, model count): repeated big-batch
        predicts (per-window eval loops) skip the re-flatten + upload.
        Training/rollback changes ``len(self.models)`` and invalidates
        the key; in-place leaf edits on a Tree do NOT — use a fresh
        Booster (like ``refit`` does) for that."""
        from ..serve.packed import pack_ensemble, predict_scores
        key = (start_iteration, end_iter, len(self.models),
               self.num_model)
        cached = getattr(self, "_packed_cache", None)
        if cached is None or cached[0] != key:
            pe = pack_ensemble(self.models, self.num_model,
                               start_iteration=start_iteration,
                               num_iteration=end_iter - start_iteration,
                               num_features=self.max_feature_idx + 1)
            self._packed_cache = cached = (key, pe)
        return predict_scores(cached[1], data)

    def _device_predict_wanted(self, n: int, early) -> bool:
        """Routing for ``predict_raw``: ``device_predict`` force/off
        override the ``device_predict_min_rows`` auto threshold;
        row-wise prediction early stopping is host-only (the device
        kernel runs all trees unconditionally)."""
        if early is not None:
            return False
        mode = str(getattr(self.config, "device_predict", "auto")).lower()
        if mode == "off":
            return False
        if mode == "force":
            return True
        return n >= int(getattr(self.config, "device_predict_min_rows",
                                65536))

    def predict_raw(self, data: np.ndarray, num_iteration: int = -1,
                    start_iteration: int = 0) -> np.ndarray:
        self._flush_pending()
        data = np.ascontiguousarray(np.asarray(data, np.float64))
        n = data.shape[0]
        out = np.zeros((self.num_model, n), np.float64)
        total_iter = self.num_iterations()
        end_iter = total_iter if num_iteration <= 0 \
            else min(start_iteration + num_iteration, total_iter)
        early = self._early_stop_instance()
        if (n > 0 and end_iter > start_iteration
                and self._device_predict_wanted(n, early)):
            out = self._predict_raw_packed(data, end_iter,
                                           start_iteration)
            if self.average_output and end_iter > start_iteration:
                out /= (end_iter - start_iteration)
            return out
        active = None if early is None else np.ones(n, bool)
        for it in range(start_iteration, end_iter):
            for k in range(self.num_model):
                tree = self.models[it * self.num_model + k]
                if active is None:
                    out[k] += tree.predict(data)
                elif active.all():
                    out[k] += tree.predict(data)
                else:
                    out[k, active] += tree.predict(data[active])
            if early is not None and (it + 1 - start_iteration) \
                    % early[0] == 0:
                active &= ~early[1](out)
                if not active.any():
                    break
        if self.average_output and end_iter > start_iteration:
            out /= (end_iter - start_iteration)
        return out

    def predict(self, data, num_iteration: int = -1, raw_score=False,
                pred_leaf=False, pred_contrib=False, start_iteration=0):
        self._flush_pending()
        if pred_leaf:
            data = np.ascontiguousarray(np.asarray(data, np.float64))
            total_iter = self.num_iterations()
            # same slice semantics as predict_raw: [start_iteration,
            # start_iteration + num_iteration) — pred_leaf used to
            # ignore start_iteration and slice [0, num_iteration)
            start_iteration = max(0, min(start_iteration, total_iter))
            end_iter = total_iter if num_iteration <= 0 \
                else min(start_iteration + num_iteration, total_iter)
            base = start_iteration * self.num_model
            n_trees = max(end_iter - start_iteration, 0) * self.num_model
            leaves = np.zeros((data.shape[0], n_trees), np.int32)
            for i in range(n_trees):
                leaves[:, i] = self.models[base + i].predict_leaf(data)
            return leaves
        if pred_contrib:
            return self._predict_contrib(data, num_iteration)
        raw = self.predict_raw(data, num_iteration, start_iteration)
        # averaged-output models (RF) already emit converted values
        # (gbdt.cpp:600: convert only when !average_output_)
        if not raw_score and not self.average_output:
            if self.objective is not None:
                raw = self.objective.convert_output(raw)
            elif self.loaded_objective_str:
                raw = _convert_by_name(self.loaded_objective_str, raw)
        if self.num_model == 1:
            return raw[0]
        return raw.T   # (N, K)

    def _predict_contrib(self, data, num_iteration=-1):
        data = np.ascontiguousarray(np.asarray(data, np.float64))
        n = data.shape[0]
        nf = self.max_feature_idx + 1
        total_iter = self.num_iterations()
        end_iter = total_iter if num_iteration <= 0 \
            else min(num_iteration, total_iter)
        from ..tree.tree import tree_shap_batch
        out = np.zeros((n, self.num_model, nf + 1), np.float64)
        # batched TreeSHAP: the recursion is vectorized over rows
        # (tree.py tree_shap_batch); chunk rows to bound the (depth x
        # rows) path-state working set
        chunk = 4096
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            for it in range(end_iter):
                for k in range(self.num_model):
                    tree = self.models[it * self.num_model + k]
                    tree_shap_batch(tree, data[lo:hi], out[lo:hi, k])
        if self.num_model == 1:
            return out[:, 0, :]
        return out.reshape(n, -1)

    # ------------------------------------------------------------------
    # leaf refit on new data (reference GBDT::RefitTree, gbdt.cpp:265-288)
    def _refit_objective(self):
        """A FRESH objective bound to nothing, for refit gradients — the
        live training objective (if any) must keep its original labels,
        so refit never reuses it.  Loaded models reconstruct from the
        model string's objective line including its ``key:value`` extras
        (``binary sigmoid:2`` keeps sigmoid=2, which the old refit path
        dropped)."""
        if self.objective is not None:
            name = self.objective.name
            extras = {}
        elif self.loaded_objective_str:
            toks = self.loaded_objective_str.split()
            name = toks[0]
            extras = dict(t.split(":", 1) for t in toks[1:] if ":" in t)
        else:
            name = "regression"
            extras = {}
        if name in ("none", ""):
            raise LightGBMError(
                "refit requires an objective; this model was trained "
                "with a custom objective function")
        keys = ("sigmoid", "alpha", "fair_c", "poisson_max_delta_step",
                "tweedie_variance_power", "scale_pos_weight",
                "is_unbalance", "reg_sqrt", "num_class", "max_position",
                "label_gain")
        params = {k: getattr(self.config, k) for k in keys}
        params.update(extras)
        params["objective"] = name
        params["num_class"] = max(self.num_model, 1)
        return create_objective(Config(params))

    def refit_leaves(self, data, label, decay_rate: float = 0.9,
                     leaf_ids=None) -> "GBDT":
        """Refit every tree's leaf values IN PLACE against ``label`` on
        new data, keeping the routing structure: for each leaf that
        received rows, ``new = decay * old + (1 - decay) * optimal *
        learning_rate`` where ``optimal`` is the L1/L2-regularized leaf
        output from the new data's gradients (the reference's
        RefitTree / CalculateSplittedLeafOutput).  Leaves that received
        no rows keep their old value.

        ``data`` is a dense raw-feature matrix; ``leaf_ids`` (optional)
        is a precomputed per-tree leaf-assignment list — the windowed
        pipeline passes assignments from the on-device binned traversal
        so refit never walks host trees row by row.  Callers wanting a
        copy clone first (``Booster.refit`` does).
        """
        self._flush_pending()
        label = np.asarray(label, np.float64)
        from ..data.dataset import Metadata
        obj = self._refit_objective()
        md = Metadata(len(label))
        md.set_label(label)
        obj.init(md, len(label))
        if leaf_ids is None:
            arr = np.ascontiguousarray(np.asarray(data, np.float64))
            raw = self.predict_raw(arr)
            leaf_ids = [tree.predict_leaf(arr) if tree.num_leaves > 1
                        else None for tree in self.models]
        else:
            # assignments given: raw scores rebuild from leaf values, so
            # the (possibly binned-only) feature matrix is never touched
            raw = np.zeros((self.num_model, len(label)), np.float64)
            for idx, tree in enumerate(self.models):
                k = idx % self.num_model
                if leaf_ids[idx] is None:
                    raw[k] += tree.leaf_value[0]    # host stump value
                else:
                    raw[k] += tree.leaf_value[leaf_ids[idx]]
        grad, hess = obj.get_gradients(jnp.asarray(raw, jnp.float32))
        if grad.ndim == 1:
            grad, hess = grad[None, :], hess[None, :]
        grad = np.asarray(grad, np.float64)
        hess = np.asarray(hess, np.float64)
        shrink = float(self.config.learning_rate)
        for idx, tree in enumerate(self.models):
            k = idx % self.num_model
            refit_tree_leaves(tree, leaf_ids[idx], grad[k], hess[k],
                              self.config, decay_rate, shrink)
        # in-place leaf edits invalidate the packed-predict cache (its
        # key only sees the model COUNT, not leaf values)
        self._packed_cache = None
        return self

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type="split",
                           iteration: int = -1) -> np.ndarray:
        self._flush_pending()
        nf = self.max_feature_idx + 1
        out = np.zeros(nf, np.float64)
        total_iter = self.num_iterations()
        end_iter = total_iter if iteration <= 0 else min(iteration, total_iter)
        for tree in self.models[:end_iter * self.num_model]:
            for node in range(tree.num_leaves - 1):
                f = tree.split_feature[node]
                if importance_type == "split":
                    out[f] += 1
                else:
                    out[f] += max(tree.split_gain[node], 0.0)
        return out

    # ------------------------------------------------------------------
    # model serialization (gbdt_model_text.cpp:243-330 format "v2")
    def model_to_string(self, start_iteration=0, num_iteration=-1) -> str:
        self._flush_pending()
        label_index = (int(self.config.label_column or 0)
                       if str(self.config.label_column).isdigit() else 0)
        lines = ["tree", f"version={MODEL_VERSION}",
                 f"num_class={max(int(self.config.num_class), 1)}",
                 f"num_tree_per_iteration={self.num_model}",
                 f"label_index={label_index}",
                 f"max_feature_idx={self.max_feature_idx}"]
        if self.objective is not None:
            lines.append(f"objective={self.objective.to_string()}")
        elif self.loaded_objective_str:
            lines.append(f"objective={self.loaded_objective_str}")
        if self.average_output:
            lines.append("average_output")
        lines.append("feature_names=" + " ".join(self.feature_names))
        lines.append("feature_infos=" + " ".join(self.feature_infos))

        total_iter = self.num_iterations()
        start_iteration = max(0, min(start_iteration, total_iter))
        num_used = total_iter * self.num_model
        if num_iteration > 0:
            num_used = min((start_iteration + num_iteration) * self.num_model,
                           num_used)
        start_model = start_iteration * self.num_model
        tree_strs = []
        for i in range(start_model, num_used):
            tree_strs.append(f"Tree={i - start_model}\n"
                             + self.models[i].to_string())
        sizes = [len(s) + 1 for s in tree_strs]
        lines.append("tree_sizes=" + " ".join(str(s) for s in sizes))
        lines.append("")
        body = "\n".join(lines)
        for s in tree_strs:
            body += s + "\n"
        body += "end of trees\n"
        # feature importance block
        imps = self.feature_importance("split")
        counts = imps.astype(np.int64)   # one conversion, not one per pair
        pairs = [(counts[i], self.feature_names[i])
                 for i in np.argsort(-imps, kind="stable") if imps[i] > 0]
        body += "\nfeature importances:\n"
        for cnt, name in pairs:
            body += f"{name}={cnt}\n"
        body += "\nparameters:\n"
        body += self._params_string()
        body += "\nend of parameters\n"
        return body

    def _params_string(self) -> str:
        from ..params import PARAM_BY_NAME
        out = []
        for p in PARAM_BY_NAME.values():
            v = getattr(self.config, p.name, p.default)
            if isinstance(v, list):
                v = ",".join(str(x) for x in v)
            out.append(f"[{p.name}: {v}]")
        return "\n".join(out)

    def save_model_to_file(self, filename, start_iteration=0,
                           num_iteration=-1):
        with open(filename, "w") as fh:
            fh.write(self.model_to_string(start_iteration, num_iteration))
        log_info(f"Finished saving model to file {filename}")

    # ------------------------------------------------------------------
    # training checkpoints (docs/Robustness.md)
    def save_checkpoint(self, path: str) -> None:
        """Atomic training checkpoint: the full model text at ``path``
        plus a ``.state.npz`` sidecar carrying the EXACT float32
        training scores and the iteration counter.  Both land via
        write-temp-then-rename, so a crash mid-save leaves the previous
        checkpoint intact.  Under ``data_sharding=multi_controller``
        this becomes the pod-slice commit protocol
        (robust/checkpoint.py): every host acks its state digest, host
        0 writes the payload and the commit marker only after ALL acks
        land, peers block on the marker — a host killed mid-window
        leaves the snapshot uncommitted."""
        self._flush_pending()
        if (self._grower is not None
                and getattr(self._grower, "_multihost", False)):
            self._save_checkpoint_pod(path)
            return
        _checkpoint.atomic_write_text(path, self.model_to_string())
        # the host learner's feature_fraction stream is the one draw
        # that is NOT (seed, iteration)-derived; snapshot it too
        rng = getattr(getattr(self, "learner", None), "_rng", None)
        _checkpoint.save_train_state(
            path + ".state.npz",
            np.asarray(self.train_score, np.float32), self.iter,
            rng_state=rng.get_state() if rng is not None else None)
        log_info(f"Saved training checkpoint to {path}")

    def _save_checkpoint_pod(self, path: str) -> None:
        """Pod-slice commit protocol (see :meth:`save_checkpoint`)."""
        import jax as _jax
        from ..parallel.network import network_policy_from_config
        rank = int(_jax.process_index())
        hosts = int(_jax.process_count())
        model_str = self.model_to_string()
        score = np.asarray(self.train_score, np.float32)
        # digest over the TREES only: the parameters echo legitimately
        # differs per host (host_rank), the trees must not
        digest = _checkpoint.pod_state_digest(
            model_str.split("\nparameters:", 1)[0], score, self.iter)
        attempts, timeout_s = network_policy_from_config(self.config)
        deadline = max(10.0, float(attempts) * float(timeout_s))
        _checkpoint.write_pod_ack(path, rank, digest)
        if rank == 0:
            _checkpoint.await_pod_acks(path, hosts, digest,
                                       timeout_s=deadline)
            # clear BEFORE the commit marker: a peer starts its next
            # ack only after seeing this commit, so post-commit
            # clearing could race and delete the peer's fresh ack
            _checkpoint.clear_pod_acks(path, hosts)
            _checkpoint.atomic_write_text(path, model_str)
            rng = getattr(getattr(self, "learner", None), "_rng", None)
            _checkpoint.save_train_state(
                path + ".state.npz", score, self.iter,
                rng_state=rng.get_state() if rng is not None else None)
            _checkpoint.commit_pod(path, digest)
            log_info(f"Committed pod checkpoint {path} "
                     f"({hosts} host acks)")
        else:
            _checkpoint.await_pod_commit(path, digest,
                                         timeout_s=deadline)

    def resume_from_checkpoint(self, path: str) -> "GBDT":
        """Adopt a :meth:`save_checkpoint` snapshot AFTER
        ``init_train``: the snapshot's trees replace the (empty) model
        list, the sidecar restores the exact training scores, and the
        bagging draw of the last redraw boundary is re-materialized —
        continued boosting is then byte-identical to the uninterrupted
        run (bagging / feature_fraction / quantization draws are all
        (seed, iteration)-derived, so no RNG state needs saving)."""
        if self.train_set is None:
            raise LightGBMError(
                "resume_from_checkpoint requires init_train first "
                "(the training scores are sized by the dataset)")
        if (getattr(self._grower, "_multihost", False)
                and not _checkpoint.has_pod_commit(path)):
            # a snapshot some host never acked may be mid-write or
            # inconsistent across the slice — resuming from it would
            # diverge the pod on the first collective
            raise LightGBMError(
                f"snapshot {path} has no pod commit marker "
                f"({_checkpoint.pod_commit_path(path)}); refusing to "
                f"resume a pod slice from an uncommitted snapshot")
        state = _checkpoint.load_train_state(path + ".state.npz")
        if state is None:
            raise LightGBMError(
                f"snapshot {path} has no state sidecar "
                f"({path}.state.npz); cannot resume exactly — "
                f"use input_model-style warm start instead")
        score, it, rng_state = state
        if score.shape != (self.num_model, self.num_data):
            raise LightGBMError(
                f"snapshot scores have shape {score.shape}, this "
                f"dataset needs {(self.num_model, self.num_data)} — "
                f"resume must use the SAME training data")
        loaded = GBDT.load_model_from_file(path)
        if len(loaded.models) != it * max(self.num_model, 1):
            raise LightGBMError(
                f"snapshot {path} holds {len(loaded.models)} trees but "
                f"claims iteration {it}")
        self.models = list(loaded.models)
        self.iter = int(it)
        self.train_score = jnp.asarray(score, jnp.float32)
        self._device_stop = False
        self._nl_queue.clear()
        self._last_chunk_stack = None
        rng = getattr(self.learner, "_rng", None)
        if rng_state is not None and rng is not None:
            rng.set_state(rng_state)
        # per-iteration paths continue mid-stride: rebuild the bagging
        # draw active after iteration (iter - 1)
        self._sync_fused_bagging()
        log_info(f"Resumed training from {path} (iteration {self.iter})")
        return self

    # ------------------------------------------------------------------
    @classmethod
    def load_model_from_string(cls, text: str, config=None) -> "GBDT":
        config = config or Config({})
        booster = cls(config)
        header, _, rest = text.partition("Tree=")
        kv: Dict[str, str] = {}
        for line in header.splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v
            elif line.strip() == "average_output":
                booster.average_output = True
        booster.num_model = int(kv.get("num_tree_per_iteration", 1))
        booster.max_feature_idx = int(kv.get("max_feature_idx", 0))
        booster.feature_names = kv.get("feature_names", "").split()
        booster.feature_infos = kv.get("feature_infos", "").split()
        booster.loaded_objective_str = kv.get("objective", "")
        num_class = int(kv.get("num_class", 1))
        config.num_class = num_class
        # tree blocks
        if rest:
            blocks = ("Tree=" + rest).split("end of trees")[0]
            for block in blocks.split("Tree=")[1:]:
                booster.models.append(Tree.from_string(block))
        booster.iter = len(booster.models) // max(booster.num_model, 1)
        booster.num_init_iteration = booster.iter
        # loaded parameters
        if "\nparameters:" in text:
            booster.loaded_parameters = (
                text.split("\nparameters:\n", 1)[1]
                .split("\nend of parameters", 1)[0])
        return booster

    @classmethod
    def load_model_from_file(cls, filename, config=None) -> "GBDT":
        with open(filename) as fh:
            return cls.load_model_from_string(fh.read(), config)


def _refit_leaf_optimum(sum_grad: np.ndarray, sum_hess: np.ndarray,
                        config) -> np.ndarray:
    """Vectorized regularized leaf output (the reference's
    ``FeatureHistogram::CalculateSplittedLeafOutput``):
    ``-ThresholdL1(sum_grad, l1) / (sum_hess + l2)``, clipped to
    ``+-max_delta_step`` when that is set."""
    l1 = float(config.lambda_l1)
    l2 = float(config.lambda_l2)
    thr = np.sign(sum_grad) * np.maximum(np.abs(sum_grad) - l1, 0.0)
    denom = sum_hess + l2
    safe = denom > 0.0
    out = np.where(safe, -thr / np.where(safe, denom, 1.0), 0.0)
    mds = float(getattr(config, "max_delta_step", 0.0))
    if mds > 0.0:
        out = np.clip(out, -mds, mds)
    return out


def refit_tree_leaves(tree: Tree, leaf_ids, grad: np.ndarray,
                      hess: np.ndarray, config, decay_rate: float,
                      shrinkage: float) -> None:
    """Refit one tree's leaf values in place from new-data gradients
    (one ``np.bincount`` per statistic instead of the old
    O(leaves x rows) masked-sum walk).  ``leaf_ids`` is the per-row leaf
    assignment, or ``None`` for a stump (every row in leaf 0).  Empty
    leaves keep their old value; routing arrays are untouched."""
    n_leaves = max(int(tree.num_leaves), 1)
    if leaf_ids is None:
        cnt = np.array([len(grad)], np.int64)
        sg = np.array([float(np.sum(grad))])
        sh = np.array([float(np.sum(hess))])
    else:
        leaf_ids = np.asarray(leaf_ids)
        cnt = np.bincount(leaf_ids, minlength=n_leaves)[:n_leaves]
        sg = np.bincount(leaf_ids, weights=grad,
                         minlength=n_leaves)[:n_leaves]
        sh = np.bincount(leaf_ids, weights=hess,
                         minlength=n_leaves)[:n_leaves]
    optimal = _refit_leaf_optimum(sg, sh, config) * shrinkage
    old = tree.leaf_value[:n_leaves]
    tree.leaf_value[:n_leaves] = np.where(
        cnt > 0, decay_rate * old + (1.0 - decay_rate) * optimal, old)


def _convert_by_name(objective_str: str, raw: np.ndarray) -> np.ndarray:
    """Output transform for models loaded from file (no live objective)."""
    name = objective_str.split()[0] if objective_str else ""
    params = dict(p.split(":", 1) for p in objective_str.split()[1:]
                  if ":" in p)
    if name in ("binary", "multiclassova", "cross_entropy"):
        sigmoid = float(params.get("sigmoid", 1.0))
        return 1.0 / (1.0 + np.exp(-sigmoid * raw))
    if name in ("poisson", "gamma", "tweedie"):
        return np.exp(raw)
    if name == "multiclass":
        e = np.exp(raw - raw.max(axis=0, keepdims=True))
        return e / e.sum(axis=0, keepdims=True)
    if name == "cross_entropy_lambda":
        return np.log1p(np.exp(raw))
    return raw
