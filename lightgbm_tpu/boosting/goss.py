"""GOSS boosting (reference ``src/boosting/goss.hpp``)."""

from __future__ import annotations

import jax.numpy as jnp

from ..utils.log import LightGBMError
from .gbdt import GBDT


class GOSS(GBDT):
    """Gradient one-side sampling: keep top |g*h|, sample + up-weight the
    rest.  No sampling during the warm-up (iter < 1/learning_rate,
    goss.hpp:138)."""

    def init_train(self, train_set, objective=None):
        super().init_train(train_set, objective)
        cfg = self.config
        if cfg.top_rate + cfg.other_rate > 1.0:
            raise LightGBMError("top_rate + other_rate <= 1.0 in GOSS")
        self.need_bagging = False      # GOSS replaces bagging
        self._goss_multiplier = None
        self.is_constant_hessian = False

    def bagging(self, it: int):
        """GOSS selection through the learner's ``goss_state`` hook: the
        serial/feature learners select over the full permutation buffer,
        the row-sharded learners (data/voting) per shard - matching the
        reference's rank-local GOSS (goss.hpp:88-133)."""
        self.bag_buffer = None
        self.bag_count = self.num_data
        self._goss_multiplier = None
        if it < int(1.0 / max(self.config.learning_rate, 1e-12)):
            return
        grad, hess = self._cur_grad
        score = jnp.abs(grad * hess).sum(axis=0)
        seed = (self.config.bagging_seed + it) & 0x7FFFFFFF
        buf, cnt, mult = self.learner.goss_state(
            seed, score, self.config.top_rate, self.config.other_rate)
        self.bag_buffer = buf
        self.bag_count = cnt
        self._goss_multiplier = mult

    def _adjust_gradients(self, grad, hess):
        # stash for bagging(); multiplier applied after selection
        self._cur_grad = (grad, hess)
        return grad, hess

    def _post_bagging_adjust(self, grad, hess):
        del self._cur_grad
        if self._goss_multiplier is None:
            return grad, hess
        m = self._goss_multiplier[None, :]
        return grad * m, hess * m
