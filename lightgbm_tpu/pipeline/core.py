"""Async windowed-retrain pipeline: prep || train || serve.

The fork exists to retrain a cache-admission model every sliding trace
window (PAPER.md, ``src/test.cpp``): label, featurize, bin, train,
predict — and the reference runs those phases strictly serially.  This
module overlaps them into the production shape (docs/Pipeline.md):

* **host prep** (labeling, featurization, CSR/dense -> binned via the
  :class:`~lightgbm_tpu.pipeline.bins.BinMapperCache`) for window N+1
  runs on ONE background thread, double-buffered (a bounded queue of
  depth 1) against
* **device training** of window N on the main thread — shapes held
  stable by ``train_row_bucketing`` and the persistent mappers, so
  cross-window retraces stay at zero and the grower re-dispatches into
  cached programs, while
* **serving** answers continuously from a
  :class:`~lightgbm_tpu.serve.engine.PredictionServer`: the freshly
  trained model lands via an atomic ``swap()`` (never a rebuild), and
  the window is scored against the PREVIOUS model before training — the
  reference's evaluateModel-then-trainModel order.

Window policies (``window_policy``, selectable per window by passing a
callable): ``fresh`` retrains from scratch (the reference's behaviour,
byte-identical to a serial loop — see the determinism contract in
docs/Pipeline.md); ``refit`` keeps the previous ensemble's routing
structure and re-fits leaf values against the new labels with
``refit_decay_rate`` (no new trees — the cheapest window); ``warm``
refits, then continues boosting ``pipeline_warm_iterations`` new trees
on top.  Both warm-start policies assign rows to leaves with the
on-device binned traversal (``ops/traverse.py``) — exact because the
mappers are the SAME objects across windows.

Telemetry (``pipeline.*``, docs/Observability.md): per-window
``pipeline.prep`` / ``pipeline.train`` / ``pipeline.eval`` /
``pipeline.stall`` / ``pipeline.refit`` timings, the cumulative
``pipeline.overlap_fraction`` gauge (overlapped prep seconds over total
prep seconds, steady-state windows), ``pipeline.drift`` gauge, and
``pipeline.windows`` / ``pipeline.rebinds`` counters.
"""

from __future__ import annotations

import copy
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..obs import tracing
from ..boosting import create_boosting
from ..boosting.gbdt import GBDT
from ..config import Config
from ..robust import checkpoint as _checkpoint
from ..robust import faults
from ..utils.log import LightGBMError, log_info, log_warning
from .bins import BinMapperCache

POLICIES = ("fresh", "refit", "warm")


class PipelineError(LightGBMError):
    """A prep-stage failure, re-raised on the caller's thread.  Serving
    is NOT torn down: the server keeps answering from the last good
    model.  ``window`` is the failing window index; ``results`` holds
    the windows completed before the failure."""

    def __init__(self, window: int, results: List["WindowResult"],
                 cause: BaseException):
        super().__init__(f"pipeline prep failed at window {window}: "
                         f"{cause!r}")
        self.window = window
        self.results = results
        self.__cause__ = cause


@dataclass
class PreppedWindow:
    """Everything host prep produces for one window.  Training features
    are either ``dense`` (rows, features) or ``csr``
    ``(indptr, indices, values, num_col)``; ``eval_*`` optionally carry
    the rows the PREVIOUS model should be scored on before this
    window's retrain (the reference's evaluateModel)."""

    label: np.ndarray
    dense: Optional[np.ndarray] = None
    csr: Optional[Tuple] = None
    eval_label: Optional[np.ndarray] = None
    eval_dense: Optional[np.ndarray] = None
    eval_csr: Optional[Tuple] = None
    meta: dict = field(default_factory=dict)

    @property
    def num_rows(self) -> int:
        if self.dense is not None:
            return int(np.asarray(self.dense).shape[0])
        return len(self.csr[0]) - 1

    def has_eval(self) -> bool:
        return self.eval_dense is not None or self.eval_csr is not None


@dataclass
class WindowResult:
    window: int
    policy: str
    rebinned: bool
    drift: Optional[float]
    rows: int
    num_trees: int
    prep_s: float
    stall_s: float
    train_s: float
    eval_s: float
    swap_s: float
    swap_same_shape: Optional[bool]
    train_span: Tuple[float, float]
    eval_metrics: Optional[dict]
    meta: dict
    booster: Optional[GBDT]

    def to_json(self) -> dict:
        """Per-window JSON line (booster and eval arrays omitted)."""
        out = {
            "window": self.window, "policy": self.policy,
            "rebinned": self.rebinned,
            "drift": None if self.drift is None else round(self.drift, 5),
            "rows_trained": self.rows, "num_trees": self.num_trees,
            "prep_s": round(self.prep_s, 3),
            "stall_s": round(self.stall_s, 3),
            "train_s": round(self.train_s, 3),
            "eval_s": round(self.eval_s, 3),
            "swap_s": round(self.swap_s, 4),
            "swap_same_shape": self.swap_same_shape,
        }
        if self.eval_metrics:
            out.update(self.eval_metrics)
        out.update(self.meta)
        return out


def densify_csr_rows(csr: Tuple, lo: int, hi: int) -> np.ndarray:
    """Dense (hi-lo, num_col) float64 block of CSR rows [lo, hi)."""
    indptr, indices, values, num_col = csr
    out = np.zeros((hi - lo, int(num_col)), np.float64)
    p0, p1 = int(indptr[lo]), int(indptr[hi])
    rows = np.repeat(np.arange(lo, hi),
                     np.diff(np.asarray(indptr[lo:hi + 1])))
    out[rows - lo, np.asarray(indices[p0:p1])] = values[p0:p1]
    return out


class RetrainPipeline:
    """The windowed-retrain orchestrator (see module docstring).

    ``params`` is a dict / ``key=value`` string / :class:`Config` with
    the training configuration; pipeline knobs default from it
    (``window_policy``, ``pipeline_rebin``,
    ``pipeline_drift_threshold``, ``pipeline_warm_iterations``,
    ``refit_decay_rate``, ``num_iterations``, ``fused_chunk``) and can
    be overridden by keyword.  ``window_policy`` may be a callable
    ``(window_index) -> str`` for per-window selection.
    """

    def __init__(self, params=None, *,
                 num_iterations: Optional[int] = None,
                 chunk: Optional[int] = None,
                 window_policy=None,
                 refit_decay_rate: Optional[float] = None,
                 warm_iterations: Optional[int] = None,
                 rebin_on_drift: Optional[bool] = None,
                 drift_threshold: Optional[float] = None,
                 categorical: Sequence[int] = (),
                 pipelined: bool = True,
                 serve: bool = True,
                 server=None,
                 tenant_id: Optional[int] = None,
                 eval_chunk_rows: int = 65536,
                 warmup_rows="auto",
                 keep_boosters: bool = True,
                 checkpoint_dir: Optional[str] = None):
        if isinstance(params, Config):
            cfg = params
        elif isinstance(params, str):
            # accept both the C API's space-separated key=value string
            # and the CLI config-file line format
            from ..c_api import _tokenize_params
            from ..config import parse_config_str
            kv = parse_config_str(params)
            kv.update(_tokenize_params(params))
            cfg = Config(kv)
        else:
            cfg = Config(params or {})
        self.config = cfg
        self.num_iterations = int(num_iterations
                                  if num_iterations is not None
                                  else cfg.num_iterations)
        self.chunk = int(chunk if chunk is not None
                         else max(int(getattr(cfg, "fused_chunk", 20)), 1))
        policy = (window_policy if window_policy is not None
                  else getattr(cfg, "window_policy", "fresh"))
        if not callable(policy):
            if str(policy) not in POLICIES:
                raise LightGBMError(f"unknown window_policy {policy!r}; "
                                    f"expected one of {POLICIES}")
            policy = str(policy)
        self.window_policy = policy
        self.refit_decay_rate = float(
            refit_decay_rate if refit_decay_rate is not None
            else getattr(cfg, "refit_decay_rate", 0.9))
        warm = (warm_iterations if warm_iterations is not None
                else int(getattr(cfg, "pipeline_warm_iterations", 0)))
        self.warm_iterations = int(warm) if warm else self.num_iterations
        self.bins = BinMapperCache(
            drift_threshold=float(
                drift_threshold if drift_threshold is not None
                else getattr(cfg, "pipeline_drift_threshold", 0.1)),
            rebin_on_drift=bool(
                rebin_on_drift if rebin_on_drift is not None
                else getattr(cfg, "pipeline_rebin", True)))
        self.categorical = tuple(int(c) for c in categorical)
        self.pipelined = bool(pipelined)
        self.eval_chunk_rows = int(eval_chunk_rows)
        self.server = server
        if serve and self.server is None:
            from ..serve.engine import PredictionServer
            self.server = PredictionServer()
        if tenant_id is not None:
            # tenant-aware swap target (docs/Serving.md): the pipeline
            # retrains ONE tenant of a FleetServer — every swap/eval
            # lands on that tenant while the fleet's other tenants keep
            # serving from the same compiled programs
            if self.server is None:
                raise LightGBMError(
                    "tenant_id= needs a serving target; pass server= "
                    "(a FleetServer) and keep serve=True")
            if not hasattr(self.server, "tenant"):
                raise LightGBMError(
                    "tenant_id= needs a multi-tenant server (a "
                    "FleetServer or anything exposing .tenant())")
            self.server = self.server.tenant(int(tenant_id))
        self.warmup_rows = warmup_rows
        # False = drop each WindowResult's booster reference after
        # on_window fires (long service loops would otherwise pin every
        # window's device scores + binned matrix for the life of run();
        # only the last model — final_booster() — and the served packed
        # copy are needed at steady state)
        self.keep_boosters = bool(keep_boosters)
        # fault tolerance (docs/Robustness.md): after every completed
        # window the model + bin mappers + a manifest land atomically in
        # checkpoint_dir; resume(dir) continues at the next window
        self.checkpoint_dir = str(
            checkpoint_dir if checkpoint_dir is not None
            else getattr(cfg, "pipeline_checkpoint_dir", "") or "") or None
        self._start_window = 0
        # causal chain id for this pipeline's windows (obs/tracing.py):
        # minted lazily at the first traced run(), restored from the
        # checkpoint manifest on resume() so a resumed window keeps the
        # originating trace
        self._trace_id: Optional[str] = None
        self._prev: Optional[GBDT] = None
        self._warmed = False
        self._policy_fallback_logged = False
        self._prep_thread: Optional[threading.Thread] = None
        self._prep_queue: Optional[queue.Queue] = None
        # overlap accounting (steady-state windows only)
        self._prep_total_s = 0.0
        self._overlap_s = 0.0

    # -- checkpoint / resume ------------------------------------------
    @classmethod
    def resume(cls, checkpoint_dir: str, params=None, **kwargs
               ) -> "RetrainPipeline":
        """Rebuild a pipeline from a checkpoint directory: the last
        completed window's model becomes ``_prev`` (so serving and the
        warm-start policies continue from it), the bin-mapper cache is
        restored (so later windows stay shape-stable against the SAME
        reference mappers), and ``run()`` skips every window the
        checkpoint already covers — under a deterministic config
        (``pipeline_rebin=false``, ``window_policy=fresh``) the resumed
        run's final model is byte-identical to an uninterrupted one."""
        cp = _checkpoint.load_pipeline_checkpoint(checkpoint_dir)
        if cp is None:
            raise LightGBMError(
                f"no pipeline checkpoint manifest in {checkpoint_dir}")
        kwargs.setdefault("checkpoint_dir", checkpoint_dir)
        pipe = cls(params, **kwargs)
        if cp.bins_path:
            loaded = BinMapperCache.load(
                cp.bins_path, rebin_on_drift=pipe.bins.rebin_on_drift)
            loaded.drift_threshold = pipe.bins.drift_threshold
            pipe.bins = loaded
        # checkpoint -> resume propagation: the resumed windows join the
        # originating run's causal chain instead of minting a new one
        pipe._trace_id = cp.trace_id
        model_str = cp.model_string()
        if model_str:
            pipe._prev = GBDT.load_model_from_string(
                model_str, pipe.config.clone())
            if pipe.server is not None:
                # serving restarts WITH the last good model: the first
                # resumed window is test-then-train scored against it,
                # exactly as if the process had never died
                pipe._swap(pipe._prev)
        pipe._start_window = cp.window + 1
        log_info(f"Resuming pipeline at window {pipe._start_window} "
                 f"(checkpoint {checkpoint_dir})")
        return pipe

    def _save_checkpoint(self, idx: int, bst: GBDT, policy: str,
                         rows: int) -> None:
        t0 = time.perf_counter()
        _checkpoint.save_pipeline_checkpoint(
            self.checkpoint_dir, window=idx,
            model_str=bst.model_to_string(),
            bins=self.bins,
            meta={"policy": policy, "rows": int(rows),
                  "num_trees": len(bst.models),
                  "num_iterations": self.num_iterations,
                  "trace_id": self._trace_id})
        obs.observe("pipeline.checkpoint", time.perf_counter() - t0)
        obs.inc("pipeline.checkpoints")

    # -- prep stage ---------------------------------------------------
    def _prep_window(self, payload, idx: int, prep_fn):
        t0 = time.perf_counter()
        with obs.span("pipeline.prep_window", cat="pipeline", window=idx):
            faults.check("pipeline.prep")
            pw = prep_fn(payload)
            if not isinstance(pw, PreppedWindow):
                raise LightGBMError(
                    "prep_fn must return a PreppedWindow")
            ds, info = self.bins.dataset_for(
                self.config, dense=pw.dense, csr=pw.csr,
                categorical=self.categorical, label=pw.label)
            # captured INSIDE the span: the prep_window span becomes the
            # parent of everything the main thread does with this
            # window (train -> swap -> the serve requests its model
            # answers); None while tracing is off
            prep_ctx = tracing.capture()
        prep_s = time.perf_counter() - t0
        obs.observe("pipeline.prep", prep_s)
        return pw, ds, info, prep_s, prep_ctx

    def _window_stream(self, payloads, prep_fn, stop: threading.Event,
                       root_ctx=None):
        """Yield ``("window", idx, pw, ds, info, prep_s, prep_ctx)``
        items, then ``("done",)`` — from a background thread when
        pipelined (queue depth 1 = double buffering), inline otherwise.
        Prep failures travel as ``("error", idx, exc)``.  ``root_ctx``
        is the pipeline's trace root, activated on the prep thread
        (threads start with an empty contextvars context)."""
        start = self._start_window
        if not self.pipelined:
            def inline():
                idx = -1
                try:
                    for idx, payload in enumerate(payloads):
                        if idx < start:    # resumed: already completed
                            continue
                        yield ("window", idx) + self._prep_window(
                            payload, idx, prep_fn)
                except Exception as e:   # noqa: BLE001 — surfaced below
                    yield ("error", idx, e)
                    return
                yield ("done",)
            return inline()

        q: "queue.Queue" = queue.Queue(maxsize=1)

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            idx = -1
            tracing.set_current(root_ctx)   # thread-local; dies with us
            try:
                for idx, payload in enumerate(payloads):
                    if stop.is_set():
                        return
                    if idx < start:        # resumed: already completed
                        continue
                    item = ("window", idx) + self._prep_window(
                        payload, idx, prep_fn)
                    if not put(item):
                        return
            except Exception as e:   # noqa: BLE001 — surfaced on main
                put(("error", idx, e))
                return
            put(("done",))

        t = threading.Thread(target=worker, name="lgbm-pipeline-prep",
                             daemon=True)
        t.start()
        self._prep_thread = t
        self._prep_queue = q

        def drain():
            # timed get + liveness check: a prep thread killed without
            # running its except/put (interpreter teardown, os._exit in
            # a prep_fn) must surface as an error on the training
            # thread, not hang the window loop forever
            while True:
                try:
                    yield q.get(timeout=0.5)
                    continue
                except queue.Empty:
                    pass
                if t.is_alive():
                    continue
                try:
                    # the worker may have delivered its final item
                    # between the timeout and the death check
                    yield q.get_nowait()
                except queue.Empty:
                    raise LightGBMError(
                        "pipeline prep thread died without delivering "
                        "a result") from None

        return drain()

    # -- policies -----------------------------------------------------
    def _policy_for(self, idx: int, rebinned: bool) -> str:
        pol = (self.window_policy(idx) if callable(self.window_policy)
               else self.window_policy)
        if pol not in POLICIES:
            raise LightGBMError(f"unknown window_policy {pol!r}")
        if pol == "fresh":
            return pol
        fallback = None
        if self._prev is None:
            fallback = "no previous model"
        elif rebinned:
            fallback = "window was re-binned (leaf assignment needs the "
            fallback += "previous mappers)"
        elif type(self._prev) is not GBDT:
            fallback = "previous booster is not plain gbdt"
        if fallback is not None:
            if not self._policy_fallback_logged:
                log_warning(f"window_policy={pol}: falling back to "
                            f"fresh ({fallback})")
                self._policy_fallback_logged = True
            return "fresh"
        return pol

    # -- training -----------------------------------------------------
    def _train_fresh(self, ds) -> GBDT:
        bst = create_boosting(self.config)
        bst.init_train(ds)
        bst.train_chunked(self.num_iterations,
                          chunk=min(self.chunk, self.num_iterations))
        return bst

    def _leaf_assignments(self, trees, ds, learner):
        """Per-tree leaf ids of ``ds``'s rows via the on-device binned
        traversal — exact, because the mappers are shared objects
        across windows (BinMapperCache)."""
        from ..ops.traverse import device_tree, traverse
        out = []
        for tree in trees:
            if tree.num_leaves <= 1:
                out.append(None)
                continue
            dt = device_tree(tree, ds, self.config.num_leaves)
            out.append(np.asarray(traverse(learner.traverse_binned, dt)))
        return out

    def _train_warm_start(self, ds, policy: str) -> GBDT:
        """``refit``/``warm``: adopt DEEP COPIES of the previous
        ensemble's trees, refit their leaf values against this window's
        labels with decay, and (``warm``) continue boosting new trees
        from the refit scores."""
        prev = self._prev
        prev._flush_pending()
        bst = create_boosting(self.config)
        bst.init_train(ds)
        trees = [copy.deepcopy(t) for t in prev.models]
        bst.models = trees
        bst.iter = len(trees) // max(bst.num_model, 1)
        with obs.span("pipeline.refit", cat="pipeline",
                      trees=len(trees)) as sp:
            leaf_ids = self._leaf_assignments(trees, ds, bst.learner)
            label = np.asarray(ds.metadata.label, np.float64)
            # the ONE refit implementation (GBDT.refit_leaves): with
            # precomputed leaf assignments it rebuilds raw scores from
            # leaf values and never touches raw features
            bst.refit_leaves(None, label,
                             decay_rate=self.refit_decay_rate,
                             leaf_ids=leaf_ids)
            sp.set(rows=len(label))
        if policy == "warm":
            # training scores of the REFIT model on this window (f64
            # host accumulation, cast once — continued boosting corrects
            # any representation difference on the next gradient step)
            score = np.zeros((bst.num_model, ds.num_data), np.float64)
            for idx, tree in enumerate(trees):
                k = idx % bst.num_model
                if leaf_ids[idx] is None:
                    score[k] += float(tree.leaf_value[0])
                else:
                    score[k] += tree.leaf_value[leaf_ids[idx]]
            import jax.numpy as jnp
            bst.train_score = jnp.asarray(score, jnp.float32)
            bst.train_chunked(self.warm_iterations,
                              chunk=min(self.chunk, self.warm_iterations))
        return bst

    def _train_window(self, ds, policy: str) -> GBDT:
        faults.check("pipeline.train")
        if policy == "fresh":
            bst = self._train_fresh(ds)
        else:
            bst = self._train_warm_start(ds, policy)
        bst._flush_pending()
        obs.inc(f"pipeline.policy_{policy}")
        self._prev = bst
        return bst

    def _emit_feature_telemetry(self, bst, idx: int, policy: str) -> None:
        """Per-window split-gain/importance event (ROADMAP item 4's
        observability half): the trained window's top feature gains
        stream as one instant event next to the ``pipeline.drift``
        bin-occupancy gauge, so feature drift across retrain windows is
        observable and explainable from the same dashboard."""
        if not obs.enabled():
            return
        try:
            gain = np.asarray(bst.feature_importance("gain"), np.float64)
            splits = np.asarray(bst.feature_importance("split"),
                                np.float64)
        except Exception:   # noqa: BLE001 — non-gbdt boosters
            return
        total = float(gain.sum())
        order = np.argsort(gain)[::-1][:16]
        top = [[int(f), round(float(gain[f]), 5), int(splits[f])]
               for f in order if gain[f] > 0.0]
        obs.instant("pipeline.window_features", cat="pipeline",
                    window=idx, policy=policy, features=int(gain.size),
                    total_gain=round(total, 5), top=top)
        obs.inc("pipeline.feature_events")
        if total > 0.0 and top:
            # share of total gain held by the strongest feature: a
            # cheap scalar drift indicator next to pipeline.drift
            obs.set_gauge("pipeline.gain_top_share",
                          round(top[0][1] / total, 5))

    # -- serving ------------------------------------------------------
    def _swap(self, bst) -> Tuple[float, Optional[bool]]:
        if self.server is None:
            return 0.0, None
        t0 = time.perf_counter()
        first = self.server._model is None
        same = self.server.swap(bst)
        swap_s = time.perf_counter() - t0
        obs.observe("pipeline.swap", swap_s)
        # model-freshness anchor for the SLO engine (obs/slo.py
        # ``freshness_s<=D``): age of the served model = now minus this
        obs.set_gauge("pipeline.last_swap_unix", time.time())
        # a fleet TenantHandle always has a model (the fleet seeds every
        # tenant), so warm on the first swap of THIS pipeline, not only
        # when the server was empty
        if not self._warmed:
            self._warmed = True
            rows = self.warmup_rows
            if rows == "auto":
                rows = [min(self.eval_chunk_rows, 8192)]
            if rows:
                # precompile the eval buckets while window 1's prep is
                # still running — the first real eval then re-dispatches
                self.server.warmup(list(rows))
        return swap_s, (None if first else same)

    def _eval_window(self, pw: PreppedWindow, eval_fn):
        """Score the CURRENTLY SERVED model (the previous window's) on
        this window's eval rows — chunked through the server so serving
        telemetry and row bucketing apply."""
        if self.server is None or self.server._model is None \
                or not pw.has_eval():
            return None, 0.0
        t0 = time.perf_counter()
        with obs.span("pipeline.eval", cat="pipeline"):
            if pw.eval_dense is not None:
                n = int(np.asarray(pw.eval_dense).shape[0])
                fetch = lambda lo, hi: np.asarray(  # noqa: E731
                    pw.eval_dense[lo:hi], np.float64)
            else:
                n = len(pw.eval_csr[0]) - 1
                fetch = lambda lo, hi: densify_csr_rows(  # noqa: E731
                    pw.eval_csr, lo, hi)
            preds = []
            step = self.eval_chunk_rows
            for lo in range(0, n, step):
                hi = min(lo + step, n)
                preds.append(np.asarray(self.server.predict(
                    fetch(lo, hi))))
            pred = np.concatenate(preds, axis=0) if preds \
                else np.zeros(0)
            metrics = eval_fn(pred, pw) if eval_fn is not None else None
        eval_s = time.perf_counter() - t0
        return metrics, eval_s

    # -- the loop ------------------------------------------------------
    def run(self, payloads, prep_fn: Callable,
            eval_fn: Optional[Callable] = None,
            on_window: Optional[Callable] = None) -> List[WindowResult]:
        """Drive the pipeline over ``payloads`` (any iterable; each item
        is handed to ``prep_fn(payload) -> PreppedWindow`` on the prep
        thread).  ``eval_fn(pred, prepped) -> dict`` turns the served
        model's predictions on a window's eval rows into metrics;
        ``on_window(result)`` fires after every completed window.
        Returns the list of :class:`WindowResult`.  A prep failure
        raises :class:`PipelineError` — completed results ride on the
        exception and the server keeps serving the last good model."""
        if self._prep_thread is not None and self._prep_thread.is_alive():
            # a previous run's worker is still mid-prep; letting a new
            # one start would race it on the shared BinMapperCache
            raise LightGBMError(
                "a previous run()'s prep thread is still active; wait "
                "for it to finish before starting another run")
        obs.configure_from_config(self.config)
        faults.configure_from_config(self.config)
        from .. import compile_cache
        compile_cache.configure_from_config(self.config)
        # one causal chain per pipeline (kept across resume via the
        # checkpoint manifest); both the prep thread and the main loop
        # root their spans under it
        if tracing.enabled() and self._trace_id is None:
            self._trace_id = tracing.new_id()
        root_ctx = (tracing.SpanContext(self._trace_id)
                    if tracing.enabled() else None)
        root_tok = tracing.set_current(root_ctx)
        results: List[WindowResult] = []
        stop = threading.Event()
        stream = self._window_stream(payloads, prep_fn, stop, root_ctx)
        try:
            while True:
                t_wait = time.perf_counter()
                item = next(stream)
                stall_s = time.perf_counter() - t_wait
                if item[0] == "done":
                    break
                if item[0] == "error":
                    _, idx, exc = item
                    obs.inc("pipeline.prep_errors")
                    raise PipelineError(idx, results, exc)
                _, idx, pw, ds, info, prep_s, prep_ctx = item
                obs.observe("pipeline.stall", stall_s)
                if idx > 0:
                    self._prep_total_s += prep_s
                    self._overlap_s += max(prep_s - stall_s, 0.0)
                    if self._prep_total_s > 0:
                        obs.set_gauge(
                            "pipeline.overlap_fraction",
                            self._overlap_s / self._prep_total_s)
                # cross-thread handoff: the window span (and everything
                # under it — train, swap, checkpoint) parents under the
                # prep thread's prep_window span
                ctx_tok = tracing.set_current(prep_ctx)
                try:
                    with obs.span("pipeline.window", cat="pipeline",
                                  window=idx, rows=int(ds.num_data)):
                        eval_metrics, eval_s = self._eval_window(
                            pw, eval_fn)
                        policy = self._policy_for(idx, info["rebinned"])
                        t0 = time.perf_counter()
                        # the span exit records the pipeline.train timing
                        with obs.span("pipeline.train", cat="pipeline",
                                      window=idx, policy=policy):
                            bst = self._train_window(ds, policy)
                        t1 = time.perf_counter()
                        self._emit_feature_telemetry(bst, idx, policy)
                        swap_s, same = self._swap(bst)
                        if self.checkpoint_dir:
                            # commit the completed window AFTER serving
                            # has it: a crash from here on resumes at
                            # idx + 1
                            self._save_checkpoint(idx, bst, policy,
                                                  int(ds.num_data))
                finally:
                    tracing.reset(ctx_tok)
                res = WindowResult(
                    window=idx, policy=policy,
                    rebinned=info["rebinned"], drift=info["drift"],
                    rows=int(ds.num_data), num_trees=len(bst.models),
                    prep_s=prep_s, stall_s=stall_s, train_s=t1 - t0,
                    eval_s=eval_s, swap_s=swap_s, swap_same_shape=same,
                    train_span=(t0, t1), eval_metrics=eval_metrics,
                    meta=dict(pw.meta), booster=bst)
                results.append(res)
                obs.inc("pipeline.windows")
                if on_window is not None:
                    on_window(res)
                if not self.keep_boosters:
                    res.booster = None
        finally:
            tracing.reset(root_tok)
            stop.set()
            self._shutdown_prep()
        return results

    def _shutdown_prep(self, timeout_s: float = 30.0) -> None:
        """Wait for the prep worker to exit (its ``put`` loop notices
        ``stop`` within 0.1 s; draining the queue unparks it).  A worker
        deep inside a long ``prep_fn`` finishes that window first —
        best effort, bounded; if it is somehow still alive afterwards
        the thread reference is kept so the next ``run()`` refuses to
        race it."""
        worker = self._prep_thread
        if worker is None:
            return
        deadline = time.perf_counter() + timeout_s
        while worker.is_alive() and time.perf_counter() < deadline:
            try:
                self._prep_queue.get_nowait()
            except queue.Empty:
                pass
            worker.join(timeout=0.2)
        if worker.is_alive():
            log_warning("pipeline prep thread did not stop within "
                        f"{timeout_s:.0f} s; a new run() will refuse "
                        "until it exits")
        else:
            self._prep_thread = None
            self._prep_queue = None

    @property
    def overlap_fraction(self) -> Optional[float]:
        """Overlapped prep seconds / total prep seconds across
        steady-state windows (window 0 is inherently serial)."""
        if self._prep_total_s <= 0:
            return None
        return self._overlap_s / self._prep_total_s

    def final_booster(self) -> Optional[GBDT]:
        return self._prev
