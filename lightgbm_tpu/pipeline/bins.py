"""Persistent bin mappers across retrain windows, with drift detection.

The fork's harness (``src/test.cpp``) re-runs find-bin from scratch for
every sliding window even though feature distributions drift slowly —
and in this runtime a fresh set of mappers is worse than wasted host
time: a different bin count or feature grouping changes the device
program SIGNATURE, so the grower and the serving kernel re-trace and
the compile caches (in-process ``GrowerPrograms`` and the persistent
XLA store, docs/ColdStart.md) stop paying.

:class:`BinMapperCache` fixes both: the first window's mappers become
the reference, every later window's dataset is constructed AGAINST them
(``reference=``-style, ``Dataset::CreateValid`` semantics — no find-bin,
no re-bundling, identical group layout), and a cheap per-group drift
statistic decides when a re-find-bin is actually warranted:

    occ_w[g, s]  = P(slot s in group g)        for window w's binned rows
    tv_g         = 0.5 * sum_s | occ_w[g, s] - occ_ref[g, s] |
    drift        = mean_g  max(tv_g - noise_g, 0)

i.e. the MEAN per-group total-variation distance between this window's
bin-occupancy histogram and the occupancy recorded when the cached
mappers were found, each group's TV first reduced by its expected null
TV ``noise_g`` — what two same-distribution samples of these sizes
would measure from sampling noise alone (per-slot binomial std,
``E|N(0, s)| = s * sqrt(2/pi)``):

    noise_g = 0.5 * sqrt(2/pi) * sqrt(1/n_w + 1/n_ref)
                  * sum_s sqrt(occ_ref[g, s] * (1 - occ_ref[g, s]))

Without the noise correction, small windows read a constant
~O(bins/sqrt(n)) pseudo-drift and rebin forever; the MEAN (not the max)
across groups makes the decision about global mapper staleness — a
single inherently non-stationary feature (the cache-admission trace's
running ``cacheAvailBytes`` state drifts ~0.2 TV every window, all
other groups ~0.003) must not force a rebin that would not help it and
would retrace every program for the 51 features whose mappers are
fine.  The statistic costs one ``np.bincount`` per group over the
(N, G) uint8 matrix that window construction produces anyway, and it
is exactly the quantity that degrades when mappers go stale:
probability mass piling into few slots means splits lose resolution.
When ``drift > threshold`` (and rebinding is enabled) the window
re-runs find-bin, becomes the new reference, and the rebind is
counted — callers see ``rebinned=True`` and should expect a one-off
retrace.
"""

from __future__ import annotations

import pickle
from typing import Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..data.binning import BinMapper
from ..data.dataset import MAX_GROUP_BIN, BinnedDataset, FeatureGroupInfo
from ..utils.log import LightGBMError, log_info

CACHE_MAGIC = b"LIGHTGBM_TPU_BINCACHE_V1\n"


class BinMapperCache:
    """Owns the reference mappers of a windowed-retrain loop.

    ``dataset_for(...)`` is the single construction entry point: it
    builds the window's :class:`BinnedDataset` (dense or CSR) against
    the cached mappers, measures drift, optionally rebins, and reports
    what it did.  The cache is NOT thread-safe by itself — the pipeline
    calls it from its single prep thread.
    """

    def __init__(self, drift_threshold: float = 0.1,
                 rebin_on_drift: bool = True):
        self.drift_threshold = float(drift_threshold)
        self.rebin_on_drift = bool(rebin_on_drift)
        self.reference: Optional[BinnedDataset] = None
        self._ref_occ: Optional[np.ndarray] = None   # (G, 256) float64
        self._ref_n = 0          # rows behind _ref_occ (noise floor)
        self.windows = 0
        self.rebinds = 0
        self.last_drift: Optional[float] = None

    # -- construction ------------------------------------------------
    def dataset_for(self, config, *, dense: Optional[np.ndarray] = None,
                    csr: Optional[Tuple] = None,
                    categorical: Sequence[int] = (),
                    label=None) -> Tuple[BinnedDataset, dict]:
        """Build one window's dataset; returns ``(dataset, info)`` with
        ``info = {"rebinned": bool, "drift": float | None}``.  ``csr``
        is ``(indptr, indices, values, num_col)``; exactly one of
        ``dense``/``csr`` must be given."""
        if (dense is None) == (csr is None):
            raise LightGBMError(
                "dataset_for needs exactly one of dense= or csr=")
        self.windows += 1
        drift: Optional[float] = None
        if self.reference is None:
            # the initial find-bin is not a REBIND — `rebinds` counts
            # only drift-triggered re-runs (each of those retraces)
            ds = self._construct(config, dense, csr, categorical, None)
            self._adopt(ds)
            rebinned = True
        else:
            ds = self._construct(config, dense, csr, categorical,
                                 self.reference)
            drift = self._drift(ds)
            self.last_drift = drift
            obs.set_gauge("pipeline.drift", drift)
            if self.rebin_on_drift and drift > self.drift_threshold:
                log_info(f"bin drift {drift:.4f} > "
                         f"{self.drift_threshold:.4f}: re-running "
                         f"find-bin (window {self.windows - 1})")
                ds = self._construct(config, dense, csr, categorical,
                                     None)
                self._adopt(ds)
                rebinned = True
                self.rebinds += 1
                obs.inc("pipeline.rebinds")
            else:
                rebinned = False
        if label is not None:
            ds.metadata.set_label(label)
        return ds, {"rebinned": rebinned, "drift": drift}

    @staticmethod
    def _construct(config, dense, csr, categorical, reference):
        if dense is not None:
            return BinnedDataset.construct_from_matrix(
                np.asarray(dense), config, categorical,
                reference=reference)
        indptr, indices, values, num_col = csr
        return BinnedDataset.construct_from_csr(
            indptr, indices, values, num_col, config, categorical,
            reference=reference)

    # -- drift statistic ---------------------------------------------
    @staticmethod
    def _occupancy(ds: BinnedDataset) -> np.ndarray:
        """(G, 256) normalized slot-occupancy of the binned matrix."""
        binned = np.asarray(ds.binned)
        g_count = max(ds.num_groups, 1)
        occ = np.zeros((g_count, MAX_GROUP_BIN), np.float64)
        n = max(ds.num_data, 1)
        for g in range(ds.num_groups):
            occ[g] = np.bincount(binned[:, g],
                                 minlength=MAX_GROUP_BIN) / n
        return occ

    def _drift(self, ds: BinnedDataset) -> float:
        occ = self._occupancy(ds)
        tv = 0.5 * np.abs(occ - self._ref_occ).sum(axis=1)
        if not tv.size:
            return 0.0
        # expected null TV from sampling noise alone (module docstring)
        scale = np.sqrt(1.0 / max(ds.num_data, 1)
                        + 1.0 / max(self._ref_n, 1))
        noise = (0.5 * np.sqrt(2.0 / np.pi) * scale
                 * np.sqrt(self._ref_occ * (1.0 - self._ref_occ))
                 .sum(axis=1))
        return float(np.maximum(tv - noise, 0.0).mean())

    def _adopt(self, ds: BinnedDataset) -> None:
        self.reference = ds
        self._ref_occ = self._occupancy(ds)
        self._ref_n = int(ds.num_data)

    # -- persistence ---------------------------------------------------
    # Mappers survive process restarts the same way compiled programs do
    # (docs/ColdStart.md): a restarted pipeline re-loads its reference
    # and the first window of the new process is already shape-stable.
    def save(self, path: str) -> None:
        if self.reference is None:
            raise LightGBMError("BinMapperCache has no reference to save")
        state = _reference_state(self.reference)
        state.update(occ=self._ref_occ, occ_n=self._ref_n,
                     drift_threshold=self.drift_threshold)
        with open(path, "wb") as fh:
            fh.write(CACHE_MAGIC)
            pickle.dump(state, fh, protocol=4)
        log_info(f"Saved bin-mapper cache to {path}")

    @classmethod
    def load(cls, path: str, rebin_on_drift: bool = True
             ) -> "BinMapperCache":
        with open(path, "rb") as fh:
            if fh.read(len(CACHE_MAGIC)) != CACHE_MAGIC:
                raise LightGBMError(
                    f"{path} is not a lightgbm_tpu bin-mapper cache")
            state = pickle.load(fh)
        cache = cls(drift_threshold=float(state["drift_threshold"]),
                    rebin_on_drift=rebin_on_drift)
        cache.reference = _skeleton_from_state(state)
        cache._ref_occ = np.asarray(state["occ"], np.float64)
        cache._ref_n = int(state["occ_n"])
        return cache


# ---------------------------------------------------------------------------
# reference serialization + the pod-slice mapper broadcast
# ---------------------------------------------------------------------------
# A multi-controller pod host must bin its row shard against EXACTLY
# the layout host 0's find-bin produced — a peer running its own
# find-bin over a different sample would disagree on bin boundaries
# AND on feature bundling, changing the program signature and the
# trees.  So the layout travels as a self-contained blob (the same
# state dict BinMapperCache persists, minus the drift bookkeeping)
# over the network.py broadcast plane, and peers rebuild a data-free
# skeleton that construct_streaming_begin adopts ``reference=``-style.

def _reference_state(ref: BinnedDataset) -> dict:
    """The picklable mapper/group/constraint layout of a dataset (no
    row data) — the unit both the on-disk cache and the pod broadcast
    serialize."""
    return {
        "num_total_features": ref.num_total_features,
        "feature_names": ref.feature_names,
        "used_features": ref.used_features,
        "mappers": [m.to_state() if m else None
                    for m in ref.bin_mappers],
        "groups": [g.feature_indices for g in ref.groups],
        # adopted verbatim by reference-constructed datasets —
        # a restarted pipeline must keep training constrained
        "monotone": np.asarray(ref.monotone_constraints),
        "penalty": np.asarray(ref.feature_penalty),
    }


def _skeleton_from_state(state: dict) -> BinnedDataset:
    """A data-free skeleton dataset carrying the mappers/groups; only
    ever used as a ``reference=``, which reads exactly these."""
    ref = BinnedDataset()
    ref.num_total_features = int(state["num_total_features"])
    ref.feature_names = list(state["feature_names"])
    ref.used_features = list(state["used_features"])
    ref.bin_mappers = [BinMapper.from_state(s) if s else None
                       for s in state["mappers"]]
    ref.groups = [FeatureGroupInfo(g, [ref.bin_mappers[f] for f in g])
                  for g in state["groups"]]
    ref._build_feature_lookups(None)
    # restore what _build_feature_lookups(None) cannot know
    ref.monotone_constraints = np.asarray(state["monotone"], np.int32)
    ref.feature_penalty = np.asarray(state["penalty"], np.float64)
    return ref


def reference_to_bytes(ref: BinnedDataset,
                       extra: Optional[dict] = None) -> bytes:
    """Serialize a dataset's mapper/group layout (plus a small
    picklable ``extra`` dict of handshake facts — global row count,
    column count) to a self-contained blob."""
    state = _reference_state(ref)
    state["extra"] = dict(extra or {})
    return CACHE_MAGIC + pickle.dumps(state, protocol=4)


def reference_from_bytes(blob: bytes
                         ) -> Tuple[BinnedDataset, dict]:
    """Rebuild ``(skeleton, extra)`` from :func:`reference_to_bytes`
    output."""
    if not blob.startswith(CACHE_MAGIC):
        raise LightGBMError(
            "broadcast blob is not a lightgbm_tpu mapper reference "
            "(magic mismatch) — coordinator/broadcast port collision?")
    state = pickle.loads(blob[len(CACHE_MAGIC):])
    return _skeleton_from_state(state), dict(state.get("extra") or {})


def reference_layout_digest(ref: BinnedDataset) -> str:
    """Digest of the mapper/group layout — equal across pod hosts iff
    they will trace identical program signatures and bin rows
    identically (tests/test_multihost.py pins this)."""
    import hashlib
    state = _reference_state(ref)
    state.pop("penalty", None)
    h = hashlib.sha256()
    h.update(pickle.dumps(
        [state["num_total_features"], state["used_features"],
         state["groups"],
         [s if s is None else sorted(s.items())
          for s in state["mappers"]]], protocol=4))
    return h.hexdigest()


def broadcast_reference(reference: Optional[BinnedDataset], *,
                        address: str, num_hosts: int, rank: int,
                        config=None, extra: Optional[dict] = None
                        ) -> Tuple[BinnedDataset, dict]:
    """The pod ingest handshake: host 0 broadcasts its freshly-found
    reference layout (+ ``extra`` handshake facts), peers return the
    reconstructed skeleton.  Every host comes back with an equal
    layout digest or construction would diverge."""
    from ..parallel.network import broadcast_blob
    payload = None
    if int(rank) == 0:
        if reference is None:
            raise LightGBMError(
                "broadcast_reference: host 0 must supply the reference")
        payload = reference_to_bytes(reference, extra)
    blob = broadcast_blob(payload, address=address,
                          num_hosts=num_hosts, rank=rank, config=config)
    if int(rank) == 0:
        return reference, dict(extra or {})
    return reference_from_bytes(blob)
