"""Async windowed-retrain pipeline (docs/Pipeline.md).

``RetrainPipeline`` overlaps host prep (labeling, featurization,
binning) of window N+1 with device training of window N while a
``PredictionServer`` keeps answering through atomic model swaps;
``BinMapperCache`` persists bin boundaries across windows and re-runs
find-bin only when the bin-occupancy drift statistic crosses its
threshold, keeping program signatures — and therefore every compile
cache — stable.
"""

from .bins import BinMapperCache
from .core import (PipelineError, PreppedWindow, RetrainPipeline,
                   WindowResult, densify_csr_rows)

__all__ = ["BinMapperCache", "PipelineError", "PreppedWindow",
           "RetrainPipeline", "WindowResult", "densify_csr_rows"]
