"""Metrics registry: counters, gauges, timing histograms.

The registry is the canonical store behind every number the telemetry
subsystem emits: monotonically increasing **counters** (recompiles,
retrain windows, dispatches), last/peak **gauges** (device memory,
profile results) and **timings** — per-name duration accumulators that
keep total/count plus a bounded reservoir of samples so snapshots can
report p50/p95/max without unbounded memory.

Everything is thread-safe behind one lock per registry: callbacks, the
process-global ``TRAIN_TIMER`` sink and the C-API embed path may all
record from different threads.  The reservoir uses a deterministic
seeded RNG so repeated runs produce identical percentile estimates.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

#: samples kept per timing name; beyond this, reservoir sampling keeps an
#: unbiased subset (percentiles become estimates, exact below the cap)
RESERVOIR_SIZE = 2048


class TimingStat:
    """Total/count/max plus a bounded sample reservoir for percentiles."""

    __slots__ = ("count", "total", "max", "samples", "_rng")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.samples: List[float] = []
        self._rng = random.Random(0)

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        if seconds > self.max:
            self.max = seconds
        if len(self.samples) < RESERVOIR_SIZE:
            self.samples.append(seconds)
        else:
            j = self._rng.randrange(self.count)
            if j < RESERVOIR_SIZE:
                self.samples[j] = seconds

    def _percentile(self, ordered: List[float], q: float) -> float:
        if not ordered:
            return 0.0
        idx = min(int(q * len(ordered)), len(ordered) - 1)
        return ordered[idx]

    def to_dict(self) -> Dict[str, float]:
        ordered = sorted(self.samples)
        mean = self.total / self.count if self.count else 0.0
        return {
            "count": self.count,
            "total_s": round(self.total, 6),
            "mean_s": round(mean, 6),
            "p50_s": round(self._percentile(ordered, 0.50), 6),
            "p95_s": round(self._percentile(ordered, 0.95), 6),
            "max_s": round(self.max, 6),
        }


class MetricsRegistry:
    """Thread-safe counters / gauges / timing histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._timings: Dict[str, TimingStat] = {}
        # jit compile attribution: name -> {"compiles": n,
        # "signatures": {sig: count}} (fed by obs.jit_track)
        self._jit: Dict[str, Dict] = {}
        self.created_unix = time.time()

    # -- counters ---------------------------------------------------------
    def inc(self, name: str, value: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    # -- gauges -----------------------------------------------------------
    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def max_gauge(self, name: str, value: float) -> None:
        """Keep the maximum ever observed (peak memory style)."""
        with self._lock:
            cur = self._gauges.get(name)
            if cur is None or value > cur:
                self._gauges[name] = value

    def gauge(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    # -- timings ----------------------------------------------------------
    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            stat = self._timings.get(name)
            if stat is None:
                stat = self._timings[name] = TimingStat()
            stat.observe(seconds)

    def timing(self, name: str) -> Optional[TimingStat]:
        with self._lock:
            return self._timings.get(name)

    # -- jit attribution --------------------------------------------------
    def record_compile(self, name: str, signature: str) -> None:
        with self._lock:
            ent = self._jit.setdefault(name,
                                       {"compiles": 0, "signatures": {}})
            ent["compiles"] += 1
            sigs = ent["signatures"]
            sigs[signature] = sigs.get(signature, 0) + 1

    def jit_compiles(self, name: str) -> int:
        with self._lock:
            ent = self._jit.get(name)
            return ent["compiles"] if ent else 0

    # -- snapshot ---------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": {k: v for k, v in self._gauges.items()},
                "timings": {k: s.to_dict()
                            for k, s in self._timings.items()},
                "jit": {k: {"compiles": v["compiles"],
                            "signatures": dict(v["signatures"])}
                        for k, v in self._jit.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timings.clear()
            self._jit.clear()
            self.created_unix = time.time()
