"""Live telemetry export: JSONL time series, Prometheus exposition,
optional localhost scrape endpoint.

The cumulative registry dumps once at process exit; a soak run needs
the numbers *while it runs*.  :class:`StreamExporter` is a background
flusher that, every ``interval_s``:

* appends one schema-versioned JSON line (the rolling-window snapshot
  plus compact cumulative counters and the latest SLO digest) to
  ``stream_path`` — a time series ``jq``/pandas can plot live;
* atomically rewrites ``prom_path`` in the Prometheus text-exposition
  format (counters as ``_total``, gauges, timings as summaries whose
  quantiles come from the ROLLING window — the sliding-window
  semantics Prometheus client summaries have natively);
* serves the same exposition text at ``http://127.0.0.1:<port>/metrics``
  when a port is configured (opt-in; never binds by default).

**The export path can never stall training or serving.**  The hot path
does not know the exporter exists: snapshots are *pulled* by the ticker
thread, handed to the writer thread through a bounded queue with
``put_nowait`` — a jammed writer (dead disk, wedged NFS) drops the
snapshot and counts it (``export.dropped``), it never blocks.  Write
failures are counted (``export.write_errors``) and never raise into
the ticker.
"""

from __future__ import annotations

import json
import os
import queue as _queue
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from .state import STATE

STREAM_SCHEMA_NAME = "lightgbm-tpu-stream"
STREAM_SCHEMA_VERSION = 1

PROM_PREFIX = "lgbm_"
_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_SUB = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """Legal Prometheus metric name for a dotted registry name."""
    out = PROM_PREFIX + _NAME_SUB.sub("_", name)
    if not _NAME_OK.match(out):
        out = "_" + out
    return out


def prometheus_text(cumulative: Dict,
                    rolling: Optional[Dict] = None) -> Tuple[str, int]:
    """Render a cumulative registry snapshot (plus optional rolling
    window) as Prometheus text exposition.  Returns ``(text,
    collisions)`` — collisions are raw names whose sanitized form was
    already emitted (skipped, so the exposition never carries duplicate
    samples)."""
    lines: List[str] = []
    seen = set()
    collisions = 0

    def fmt(v) -> str:
        return f"{float(v):.9g}"

    def emit(family: str, kind: str, samples) -> None:
        nonlocal collisions
        if family in seen:
            collisions += 1
            return
        seen.add(family)
        lines.append(f"# TYPE {family} {kind}")
        for suffix, labels, value in samples:
            lines.append(f"{family}{suffix}{labels} {fmt(value)}")

    roll_t = (rolling or {}).get("timings", {})
    for name, v in sorted(cumulative.get("counters", {}).items()):
        emit(sanitize_metric_name(name) + "_total", "counter",
             [("", "", v)])
    for name, v in sorted(cumulative.get("gauges", {}).items()):
        emit(sanitize_metric_name(name), "gauge", [("", "", v)])
    for name, stat in sorted(cumulative.get("timings", {}).items()):
        family = sanitize_metric_name(name) + "_seconds"
        roll = roll_t.get(name)
        # quantiles over the rolling window when it has samples (the
        # live SLO view); the process-lifetime reservoir otherwise
        src = roll if roll else stat
        samples = [("", '{quantile="0.5"}', src["p50_s"]),
                   ("", '{quantile="0.95"}', src["p95_s"])]
        if "p99_s" in src:
            samples.append(("", '{quantile="0.99"}', src["p99_s"]))
        samples += [("_sum", "", stat["total_s"]),
                    ("_count", "", stat["count"])]
        emit(family, "summary", samples)
    return "\n".join(lines) + "\n", collisions


def _inc(name: str, value: int = 1) -> None:
    """Counter bump through the same enabled gate as ``obs.inc`` (local
    to avoid an import cycle with ``obs/__init__``)."""
    if STATE.enabled:
        STATE.registry.inc(name, value)
        r = STATE.rolling
        if r is not None:
            r.inc(name, value)


class StreamExporter:
    """Background flusher (see module docstring).  ``slo_spec`` (a
    string or parsed :class:`~.slo.SloSpec`) makes every snapshot line
    carry a fresh SLO evaluation; without it, lines carry the last
    report something else evaluated (``bench.py --slo``, CI gates)."""

    def __init__(self, *, stream_path: Optional[str] = None,
                 prom_path: Optional[str] = None,
                 interval_s: float = 5.0, queue_max: int = 8,
                 http_port: Optional[int] = None,
                 slo_spec=None, window_s: Optional[float] = None):
        self.stream_path = stream_path or None
        self.prom_path = prom_path or None
        self.interval_s = max(float(interval_s), 0.05)
        # 0 is meaningful (bind an ephemeral port, resolved on start);
        # the REQUESTED port is kept for matches() so re-configuring
        # with port 0 after resolution stays idempotent
        self.http_port = None if http_port is None else int(http_port)
        self._http_port_requested = self.http_port
        self.window_s = window_s
        self._slo_spec = None
        if slo_spec is not None:
            self.set_slo_spec(slo_spec)
        self._lock = threading.Lock()
        # serializes _write(): flush_now() runs on the CALLER's thread
        # and may race the writer thread on the same tmp/stream files
        self._write_lock = threading.Lock()
        self._queue: _queue.Queue = _queue.Queue(maxsize=max(queue_max, 1))
        self._stop = threading.Event()
        self._ticker: Optional[threading.Thread] = None
        self._writer: Optional[threading.Thread] = None
        self._httpd = None
        self._http_thread: Optional[threading.Thread] = None
        self._latest_prom = "# no snapshot yet\n"
        self._dropped = 0
        self._write_errors = 0
        self._flushes = 0
        self._slo_error_logged = False

    # -- introspection (lock-guarded: ticker/writer/callers race) -------
    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    @property
    def write_errors(self) -> int:
        with self._lock:
            return self._write_errors

    @property
    def flushes(self) -> int:
        with self._lock:
            return self._flushes

    def latest_prom_text(self) -> str:
        with self._lock:
            return self._latest_prom

    def matches(self, stream_path, prom_path, http_port) -> bool:
        return (self.stream_path == (stream_path or None)
                and self.prom_path == (prom_path or None)
                and self._http_port_requested
                == (None if http_port is None else int(http_port)))

    def set_slo_spec(self, spec) -> None:
        """Install the per-flush SLO spec.  A string is parsed HERE so
        a typo raises at configure time instead of being silently
        swallowed on every tick."""
        from .slo import SloSpec
        if isinstance(spec, str):
            spec = SloSpec.parse(spec)
        self._slo_spec = spec

    # -- snapshot assembly ----------------------------------------------
    def collect(self, now: Optional[float] = None) -> Dict:
        """One stream line: rolling window + compact cumulative tallies
        + the latest SLO digest.  Pure read — safe from any thread."""
        from . import slo as _slo
        now = time.time() if now is None else now
        rolling = STATE.rolling
        doc = {
            "schema": STREAM_SCHEMA_NAME,
            "schema_version": STREAM_SCHEMA_VERSION,
            "t_unix": round(now, 3),
        }
        if rolling is not None:
            doc.update(rolling.window(self.window_s, now))
        else:
            doc.update({"window_s": None, "counters": {},
                        "gauges": {}, "timings": {}})
        if self._slo_spec is not None:
            try:
                # the spec was parsed at set_slo_spec time; only the
                # evaluation itself is guarded (e.g. rolling opted out,
                # or window_s beyond the ring capacity)
                STATE.last_slo = self._slo_spec.evaluate(
                    rolling=rolling, now=now)
            except _slo.SloSpecError as e:
                # never silent: a spec that can NEVER evaluate would
                # otherwise just produce slo-less stream lines forever
                _inc("export.slo_errors")
                # and never stale: re-stamping the last successful
                # digest onto fresh lines would show a frozen "ok"
                # while the evaluation is failing
                STATE.last_slo = None
                with self._lock:
                    first = not self._slo_error_logged
                    self._slo_error_logged = True
                if first:
                    from ..utils.log import log_warning
                    log_warning(f"obs export: SLO spec cannot be "
                                f"evaluated ({e}); stream lines will "
                                f"carry no slo digest")
        if STATE.last_slo is not None:
            doc["slo"] = STATE.last_slo.digest()
        return doc

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "StreamExporter":
        with self._lock:
            if self._writer is not None and self._writer.is_alive():
                return self
            self._stop.clear()
            self._writer = threading.Thread(
                target=self._write_loop, name="lgbm-obs-writer",
                daemon=True)
            self._writer.start()
            self._ticker = threading.Thread(
                target=self._tick_loop, name="lgbm-obs-ticker",
                daemon=True)
            self._ticker.start()
        if self.http_port is not None:
            self._start_http()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        """Stop the threads; a final snapshot is written synchronously
        so the files always end on the freshest state."""
        self._stop.set()
        with self._lock:
            ticker, self._ticker = self._ticker, None
            writer, self._writer = self._writer, None
            httpd, self._httpd = self._httpd, None
            ht, self._http_thread = self._http_thread, None
        for t in (ticker, writer):
            if t is not None:
                t.join(timeout=timeout_s)
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
            if ht is not None:
                ht.join(timeout=timeout_s)
        self.flush_now()

    def __enter__(self) -> "StreamExporter":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- hot-path-safe handoff -------------------------------------------
    def emit(self, now: Optional[float] = None) -> bool:
        """Snapshot and offer to the writer queue — NON-BLOCKING.  A
        full queue drops the snapshot (counted), it never waits.
        Chaos-armed at ``obs.export``: an injected fault here behaves
        exactly like a full queue (dropped + counted, never raised)."""
        from ..robust import faults
        try:
            faults.check("obs.export")
            doc = self.collect(now)
            self._queue.put_nowait(doc)
            return True
        except (_queue.Full, faults.InjectedFault):
            with self._lock:
                self._dropped += 1
            _inc("export.dropped")
            return False

    def flush_now(self, now: Optional[float] = None) -> Dict:
        """Synchronous snapshot + write on the CALLER's thread (used by
        ``obs.flush()`` and at exit; bypasses the queue so it cannot be
        dropped)."""
        doc = self.collect(now)
        self._write(doc)
        return doc

    # -- threads -----------------------------------------------------------
    def _tick_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.emit()

    def _write_loop(self) -> None:
        while True:
            try:
                doc = self._queue.get(timeout=0.2)
            except _queue.Empty:
                if self._stop.is_set():
                    return
                continue
            self._write(doc)

    def _write(self, doc: Dict) -> None:
        with self._write_lock:
            self._write_locked(doc)

    def _write_locked(self, doc: Dict) -> None:
        from ..robust import faults
        try:
            # chaos-armed: an injected fault on the writer thread takes
            # the same path as a real disk failure (counted, not raised)
            faults.check("obs.export")
            if self.stream_path:
                with open(self.stream_path, "a") as fh:
                    fh.write(json.dumps(doc) + "\n")
            if self.prom_path or self.http_port is not None:
                text, collisions = prometheus_text(
                    STATE.registry.snapshot(),
                    {"timings": doc.get("timings", {})})
                if collisions:
                    _inc("export.name_collisions", collisions)
                with self._lock:
                    self._latest_prom = text
                if self.prom_path:
                    tmp = f"{self.prom_path}.tmp.{os.getpid()}"
                    with open(tmp, "w") as fh:
                        fh.write(text)
                    os.replace(tmp, self.prom_path)
            with self._lock:
                self._flushes += 1
            _inc("export.flushes")
        except Exception:   # noqa: BLE001 — export never raises upward
            with self._lock:
                self._write_errors += 1
            _inc("export.write_errors")

    # -- scrape endpoint ---------------------------------------------------
    def _start_http(self) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 — stdlib API name
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                body = exporter.latest_prom_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):   # silence per-scrape stderr
                pass

        httpd = ThreadingHTTPServer(("127.0.0.1", int(self.http_port)),
                                    Handler)
        self.http_port = httpd.server_address[1]    # resolve port 0
        thread = threading.Thread(target=httpd.serve_forever,
                                  name="lgbm-obs-http", daemon=True)
        with self._lock:
            self._httpd = httpd
            self._http_thread = thread
        thread.start()
