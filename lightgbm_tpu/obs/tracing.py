"""Causal trace context: trace_id / span_id / parent_id propagation.

Spans recorded while trace context is enabled
(``trace_context_enabled=true`` / ``LGBM_TPU_TRACE_CTX=1`` /
``obs.configure(trace_context=True)``) carry three extra args —
``trace_id`` (the whole causal chain), ``span_id`` (this span) and
``parent_id`` (the enclosing span) — so one JSONL/Perfetto export shows
a serve request's full ancestry back to the pipeline window that
trained the model answering it.

Within one thread the current context lives in a ``contextvars``
variable and nesting is automatic: every ``obs.span`` opened while
another is active becomes its child.  Across thread boundaries the
context must travel explicitly, because worker threads start with an
empty contextvars context:

* ``capture()`` snapshots the sender's current context (``None`` while
  tracing is off — the disabled path allocates nothing);
* the snapshot rides the queue item / model generation / checkpoint
  manifest to the receiver;
* ``set_current(ctx)`` / ``reset(token)`` activate it around the
  receiver's work (both no-ops on ``None``, so call sites need no flag
  checks of their own).

The repo's propagation edges (docs/Observability.md "Tracing &
attribution"): pipeline prep thread -> train -> swap -> the serve
requests answered by that model, micro-batch ``submit`` -> worker
flush, FleetServer replica dispatch, and checkpoint/resume (the
resumed pipeline reuses the originating ``trace_id`` from the
manifest).
"""

from __future__ import annotations

import contextvars
import uuid
from typing import Optional

from .state import STATE

__all__ = ["SpanContext", "enabled", "new_id", "current", "capture",
           "set_current", "reset", "new_root", "link_args"]

#: the active span's context on THIS thread (threads start empty —
#: cross-thread handoff is explicit via capture()/set_current())
_CURRENT: "contextvars.ContextVar[Optional[SpanContext]]" = \
    contextvars.ContextVar("lgbm_tpu_trace_ctx", default=None)


class SpanContext:
    """An immutable (trace_id, span_id) position in a trace tree.

    ``span_id`` may be ``None`` for a root context (a trace id restored
    from a checkpoint manifest, or a fresh pipeline root before any
    span opened): children inherit the trace_id and record no
    parent_id."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:   # pragma: no cover - debugging aid
        return f"SpanContext({self.trace_id!r}, {self.span_id!r})"


def new_id() -> str:
    """16-hex-char random id (process-unique is all the exports need)."""
    return uuid.uuid4().hex[:16]


def enabled() -> bool:
    """True when spans record/propagate trace context."""
    return STATE.enabled and STATE.trace_context


def current() -> Optional["SpanContext"]:
    """The active context on this thread (None while tracing is off)."""
    if not (STATE.enabled and STATE.trace_context):
        return None
    return _CURRENT.get()


def capture() -> Optional["SpanContext"]:
    """Snapshot the current context for a cross-thread handoff.

    Returns ``None`` while tracing is disabled — the queue tuples and
    model generations that carry the snapshot pay a single flag check
    and allocate no context objects on the disabled path."""
    if not (STATE.enabled and STATE.trace_context):
        return None
    return _CURRENT.get()


def set_current(ctx: Optional["SpanContext"]):
    """Activate ``ctx`` on this thread; returns a reset token (or
    ``None`` when there is nothing to activate — pass it straight to
    :func:`reset`, which ignores ``None``)."""
    if ctx is None or not (STATE.enabled and STATE.trace_context):
        return None
    return _CURRENT.set(ctx)


def reset(token) -> None:
    """Undo a :func:`set_current` (no-op on a ``None`` token)."""
    if token is not None:
        _CURRENT.reset(token)


def new_root(trace_id: Optional[str] = None) -> Optional["SpanContext"]:
    """A root context for a new causal chain (e.g. one pipeline run).

    ``trace_id`` restores an existing chain — the checkpoint/resume
    edge: the resumed pipeline's windows keep the originating trace_id.
    Returns ``None`` while tracing is disabled."""
    if not (STATE.enabled and STATE.trace_context):
        return None
    return SpanContext(trace_id or new_id(), None)


def link_args(ctx: Optional["SpanContext"], prefix: str = "") -> dict:
    """Span args linking to another trace position (empty when no
    context): ``{<prefix>trace_id, <prefix>span_id}``.  Used for
    cross-chain references that are NOT parent/child edges — e.g. a
    serve span linking to the training window whose model answered
    it."""
    if ctx is None:
        return {}
    out = {f"{prefix}trace_id": ctx.trace_id}
    if ctx.span_id is not None:
        out[f"{prefix}span_id"] = ctx.span_id
    return out
