"""Structured trace events: spans, instants, counter samples.

Events accumulate in a bounded in-memory buffer and export in two
formats:

* **JSONL** — one JSON object per line, schema-stable, for ad-hoc
  ``jq``/pandas analysis of a run;
* **Chrome trace** (the ``chrome://tracing`` / Perfetto JSON array
  format) — complete ``"ph": "X"`` events with microsecond timestamps
  relative to the buffer's epoch, so a whole training run or a
  windowed-retrain session renders as a timeline.

The buffer is capped (no unbounded growth inside a long retrain loop);
overflow drops the newest events and the drop count is reported in the
metrics snapshot — a truncated trace is never silently complete.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

#: hard cap on buffered events; beyond it events are dropped and counted
MAX_EVENTS = 200_000


class Event:
    __slots__ = ("name", "cat", "kind", "t0", "dur", "tid", "args")

    def __init__(self, name, cat, kind, t0, dur, tid, args):
        self.name = name
        self.cat = cat
        self.kind = kind          # "span" | "instant" | "counter"
        self.t0 = t0              # perf_counter seconds
        self.dur = dur            # seconds (spans only)
        self.tid = tid
        self.args = args


class TraceBuffer:
    """Bounded, thread-safe event buffer with two exporters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[Event] = []
        self.dropped = 0
        # tid -> thread name at first sight, so exports can label the
        # prep/serve/fleet worker lanes (Perfetto reads thread_name
        # metadata; raw tids interleave unreadably)
        self._thread_names: Dict[int, str] = {}
        # perf_counter origin and the wall-clock it corresponds to, so
        # JSONL lines carry absolute times while chrome ts stay relative
        self.epoch_perf = time.perf_counter()
        self.epoch_unix = time.time()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def add(self, name: str, *, cat: str = "train", kind: str = "span",
            t0: Optional[float] = None, dur: float = 0.0,
            args: Optional[Dict] = None) -> None:
        tid = threading.get_ident()
        ev = Event(name, cat, kind,
                   time.perf_counter() if t0 is None else t0,
                   dur, tid, args or {})
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            if len(self._events) >= MAX_EVENTS:
                self.dropped += 1
                return
            self._events.append(ev)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._thread_names.clear()
            self.epoch_perf = time.perf_counter()
            self.epoch_unix = time.time()

    def thread_names(self) -> Dict[int, str]:
        """tid -> thread name for every thread that recorded an event."""
        with self._lock:
            return dict(self._thread_names)

    def _copy(self) -> List[Event]:
        with self._lock:
            return list(self._events)

    # -- exporters --------------------------------------------------------
    def to_jsonl(self, path: str) -> int:
        """One JSON object per line; returns the number written."""
        events = self._copy()
        names = self.thread_names()
        with open(path, "w") as fh:
            for ev in events:
                rec = {
                    "t_unix": round(self.epoch_unix
                                    + (ev.t0 - self.epoch_perf), 6),
                    "name": ev.name,
                    "cat": ev.cat,
                    "kind": ev.kind,
                    "tid": ev.tid,
                    "thread": names.get(ev.tid, ""),
                }
                if ev.kind == "span":
                    rec["dur_s"] = round(ev.dur, 6)
                if ev.args:
                    rec["args"] = ev.args
                fh.write(json.dumps(rec) + "\n")
        return len(events)

    def to_chrome(self, path: str) -> int:
        """Chrome-trace JSON object; loads in Perfetto / chrome://tracing.

        Spans become complete events (``ph: "X"``), instants ``ph: "i"``
        (thread-scoped), counter samples ``ph: "C"``.  Timestamps are
        microseconds since the buffer epoch.
        """
        events = self._copy()
        out = [{
            "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
            "args": {"name": "lightgbm_tpu"},
        }]
        # one thread_name metadata event per recording thread: Perfetto
        # labels the lanes (prep / serve / fleet workers) instead of
        # showing raw interleaved tids
        for tid, tname in sorted(self.thread_names().items()):
            out.append({"name": "thread_name", "ph": "M", "pid": 0,
                        "tid": tid, "args": {"name": tname}})
        for ev in events:
            ts = (ev.t0 - self.epoch_perf) * 1e6
            base = {"name": ev.name, "cat": ev.cat, "pid": 0,
                    "tid": ev.tid, "ts": round(ts, 3)}
            if ev.kind == "span":
                base["ph"] = "X"
                base["dur"] = round(ev.dur * 1e6, 3)
                if ev.args:
                    base["args"] = ev.args
            elif ev.kind == "counter":
                base["ph"] = "C"
                base["args"] = ev.args
            else:
                base["ph"] = "i"
                base["s"] = "t"
                if ev.args:
                    base["args"] = ev.args
            out.append(base)
        with open(path, "w") as fh:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, fh)
        return len(events)
