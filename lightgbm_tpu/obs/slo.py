"""Declarative SLOs evaluated from the rolling telemetry windows.

A production serving stack is judged on *objectives over a live
window* — "availability >= 99.9% through retrains", "p95 latency under
50 ms over the last minute" — not on process-lifetime averages.  This
module turns a compact spec string into those objectives and evaluates
them against :class:`~.rolling.RollingRegistry` state into a
:class:`SloReport` that CI gates, ``bench.py --slo`` and the soak
harness (ROADMAP item 5) can assert on.

Spec grammar — comma/semicolon-separated ``key<op>value`` tokens::

    availability>=0.999,p95_ms<=50,burn<=14,freshness_s<=30
    source=serve.fleet;window_s=60;p99_ms<=200
    metric=serve.request_latency,p95_ms<=5

* ``availability>=T`` — request availability over the window.  Valid
  requests are successes + degraded-to-host fallbacks + hard failures
  (client **input errors are excluded** — a malformed query is not the
  service's unavailability).  Breaker dark time counts against it:
  ``availability = answered/valid x (1 - dark_fraction)``, where
  ``dark_fraction`` is the time-weighted mean of the ``<source>.degraded``
  gauge over the window (or ``degraded_replicas / replicas`` for the
  fleet), so a service answering 100% of requests from the host
  fallback while the device is dead still fails a 99.9% target.
* ``p50_ms<=B`` / ``p95_ms<=B`` / ``p99_ms<=B`` — rolling latency
  percentile bound (milliseconds) on ``metric=`` (default
  ``<source>.predict``).
* ``burn<=B`` — error-budget burn rate: ``(1 - availability) /
  (1 - availability_target)``; requires an ``availability`` objective.
* ``freshness_s<=D`` — model freshness: seconds since the last
  completed retrain swap (``pipeline.last_swap_unix`` gauge, written by
  ``RetrainPipeline._swap``), i.e. the per-window retrain deadline.
* ``window_p95_s<=B`` — end-to-end retrain window (prep||train+swap)
  p95 bound from the ``pipeline.window`` span timings.
* ``source=PFX`` (default ``serve``), ``window_s=N`` (default 60),
  ``metric=NAME`` — evaluation knobs, not objectives.

Comparisons carry a 1e-12 tolerance so an objective met exactly at its
boundary passes deterministically.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .state import STATE

_EPS = 1e-12

#: objective keys -> (kind, payload) parsed below
_LAT_KEYS = {"p50_ms": 0.50, "p95_ms": 0.95, "p99_ms": 0.99}


class SloSpecError(ValueError):
    """Malformed SLO spec string."""


@dataclass
class SloResult:
    """One evaluated objective."""

    name: str
    comparator: str          # ">=" | "<="
    target: float
    observed: Optional[float]
    ok: bool
    detail: str = ""

    def to_json(self) -> Dict:
        out = {"name": self.name, "comparator": self.comparator,
               "target": self.target,
               "observed": (None if self.observed is None
                            else round(self.observed, 6)),
               "ok": self.ok}
        if self.detail:
            out["detail"] = self.detail
        return out


@dataclass
class SloReport:
    """Evaluation of one spec at one instant over one rolling window."""

    spec: str
    source: str
    window_s: float
    evaluated_unix: float
    objectives: List[SloResult] = field(default_factory=list)
    counts: Dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return all(o.ok for o in self.objectives)

    def objective(self, name: str) -> Optional[SloResult]:
        for o in self.objectives:
            if o.name == name:
                return o
        return None

    def to_json(self) -> Dict:
        return {"spec": self.spec, "source": self.source,
                "window_s": self.window_s,
                "evaluated_unix": round(self.evaluated_unix, 3),
                "ok": self.ok,
                "objectives": [o.to_json() for o in self.objectives],
                "counts": dict(self.counts)}

    def digest(self) -> Dict:
        """Compact form for ``obs.summary()`` / bench JSON lines."""
        return {"ok": self.ok, "window_s": self.window_s,
                "objectives": {
                    o.name: {"target": o.target,
                             "observed": (None if o.observed is None
                                          else round(o.observed, 6)),
                             "ok": o.ok}
                    for o in self.objectives},
                "counts": dict(self.counts)}


class SloSpec:
    """Parsed spec: evaluation knobs plus the ordered objectives."""

    def __init__(self, *, availability: Optional[float] = None,
                 latency: Optional[List] = None,
                 burn_rate: Optional[float] = None,
                 freshness_s: Optional[float] = None,
                 window_p95_s: Optional[float] = None,
                 window_s: float = 60.0, source: str = "serve",
                 latency_metric: Optional[str] = None,
                 text: str = ""):
        self.availability = availability
        self.latency = list(latency or ())    # [(q, bound_seconds), ...]
        self.burn_rate = burn_rate
        self.freshness_s = freshness_s
        self.window_p95_s = window_p95_s
        self.window_s = float(window_s)
        self.source = source
        self.latency_metric = latency_metric
        self.text = text or self._render()
        if self.burn_rate is not None and self.availability is None:
            raise SloSpecError(
                "burn<= needs an availability>= objective (the burn "
                "rate is relative to that error budget)")
        if not (self.latency or self.availability is not None
                or self.freshness_s is not None
                or self.window_p95_s is not None):
            raise SloSpecError("spec has no objectives")

    def _render(self) -> str:
        parts = []
        if self.availability is not None:
            parts.append(f"availability>={self.availability:g}")
        for q, b in self.latency:
            parts.append(f"p{int(q * 100)}_ms<={b * 1e3:g}")
        if self.burn_rate is not None:
            parts.append(f"burn<={self.burn_rate:g}")
        if self.freshness_s is not None:
            parts.append(f"freshness_s<={self.freshness_s:g}")
        if self.window_p95_s is not None:
            parts.append(f"window_p95_s<={self.window_p95_s:g}")
        return ",".join(parts)

    @classmethod
    def parse(cls, text: str) -> "SloSpec":
        kw = {"latency": [], "text": text.strip()}
        for raw in text.replace(";", ",").split(","):
            tok = raw.strip()
            if not tok:
                continue
            for op in (">=", "<=", "="):
                if op in tok:
                    key, _, val = tok.partition(op)
                    break
            else:
                raise SloSpecError(f"cannot parse SLO token {tok!r} "
                                   f"(expected key>=v, key<=v or key=v)")
            key = key.strip().lower()
            val = val.strip()
            if key == "source":
                kw["source"] = val
                continue
            if key == "metric":
                kw["latency_metric"] = val
                continue
            try:
                num = float(val)
            except ValueError:
                raise SloSpecError(
                    f"SLO token {tok!r}: {val!r} is not a number") \
                    from None
            if key == "availability":
                if op != ">=":
                    raise SloSpecError("availability takes >=")
                if not 0.0 < num <= 1.0:
                    raise SloSpecError(
                        f"availability target {num} not in (0, 1]")
                kw["availability"] = num
            elif key in _LAT_KEYS:
                if op != "<=":
                    raise SloSpecError(f"{key} takes <=")
                kw["latency"].append((_LAT_KEYS[key], num / 1e3))
            elif key == "burn":
                if op != "<=":
                    raise SloSpecError("burn takes <=")
                kw["burn_rate"] = num
            elif key == "freshness_s":
                if op != "<=":
                    raise SloSpecError("freshness_s takes <=")
                kw["freshness_s"] = num
            elif key == "window_p95_s":
                if op != "<=":
                    raise SloSpecError("window_p95_s takes <=")
                kw["window_p95_s"] = num
            elif key == "window_s":
                if num <= 0:
                    raise SloSpecError("window_s must be > 0")
                kw["window_s"] = num
            else:
                raise SloSpecError(f"unknown SLO key {key!r}")
        return cls(**kw)

    # -- evaluation ---------------------------------------------------
    def _dark_fraction(self, rolling, registry, now) -> float:
        dark = rolling.gauge_mean(f"{self.source}.degraded",
                                  self.window_s, now)
        if dark is None:
            # fleet shape: degraded replica count over replica count
            dr = rolling.gauge_mean(f"{self.source}.degraded_replicas",
                                    self.window_s, now)
            reps = rolling.gauge_last(f"{self.source}.replicas")
            if reps is None and registry is not None:
                reps = registry.gauge(f"{self.source}.replicas")
            dark = (dr / reps) if (dr is not None and reps) else 0.0
        return min(max(float(dark), 0.0), 1.0)

    def evaluate(self, rolling=None, registry=None,
                 now: Optional[float] = None) -> SloReport:
        rolling = rolling if rolling is not None else STATE.rolling
        if rolling is None:
            raise SloSpecError(
                "no rolling telemetry to evaluate against; enable "
                "telemetry first (obs.configure(enabled=True))")
        capacity = rolling.bucket_seconds * rolling.num_buckets
        if self.window_s > capacity + _EPS:
            # the ring would silently clamp the window and a failure
            # older than the ring would produce a FALSE PASS — a gate
            # must error loudly instead
            raise SloSpecError(
                f"window_s={self.window_s:g} exceeds the rolling "
                f"registry's capacity ({capacity:g} s = bucket_seconds "
                f"x num_buckets); evaluate a smaller window or build "
                f"the registry with a larger ring")
        registry = registry if registry is not None else STATE.registry
        now = time.time() if now is None else now
        w = self.window_s
        src = self.source

        def delta(suffix):
            return rolling.counter_delta(f"{src}.{suffix}", w, now)

        n_ok = delta("ok")
        n_fb = delta("fallback_requests")
        n_fail = delta("failed")
        n_input = delta("input_errors")
        answered = n_ok + n_fb
        valid = answered + n_fail
        request_avail = (answered / valid) if valid else 1.0
        dark = self._dark_fraction(rolling, registry, now)
        availability = request_avail * (1.0 - dark)

        report = SloReport(
            spec=self.text, source=src, window_s=w, evaluated_unix=now,
            counts={"ok": n_ok, "fallback": n_fb, "failed": n_fail,
                    "input_errors": n_input,
                    "dark_fraction": round(dark, 6),
                    "availability": round(availability, 6)})
        res = report.objectives.append

        if self.availability is not None:
            res(SloResult(
                "availability", ">=", self.availability, availability,
                availability >= self.availability - _EPS,
                detail="" if valid or dark else "no requests in window"))
        metric = self.latency_metric or f"{src}.predict"
        for q, bound in self.latency:
            p = rolling.percentile(metric, q, w, now)
            res(SloResult(
                f"p{int(q * 100)}_ms", "<=", bound * 1e3,
                None if p is None else p * 1e3,
                p is not None and p <= bound + _EPS,
                detail="" if p is not None
                else f"no {metric} samples in window"))
        if self.burn_rate is not None:
            budget = 1.0 - self.availability
            burn = ((1.0 - availability) / budget) if budget > 0 \
                else (0.0 if availability >= 1.0 - _EPS else float("inf"))
            res(SloResult("burn", "<=", self.burn_rate, burn,
                          burn <= self.burn_rate + _EPS))
        if self.freshness_s is not None:
            last = rolling.gauge_last("pipeline.last_swap_unix")
            if last is None and registry is not None:
                last = registry.gauge("pipeline.last_swap_unix")
            age = None if last is None else max(now - float(last), 0.0)
            res(SloResult(
                "freshness_s", "<=", self.freshness_s, age,
                age is not None and age <= self.freshness_s + _EPS,
                detail="" if age is not None else "no retrain swap "
                "recorded (pipeline.last_swap_unix unset)"))
        if self.window_p95_s is not None:
            p = rolling.percentile("pipeline.window", 0.95, w, now)
            res(SloResult(
                "window_p95_s", "<=", self.window_p95_s, p,
                p is not None and p <= self.window_p95_s + _EPS,
                detail="" if p is not None
                else "no pipeline.window spans in window"))
        return report


def evaluate(spec, rolling=None, registry=None,
             now: Optional[float] = None, record: bool = True
             ) -> SloReport:
    """Parse-if-needed and evaluate ``spec``.  With ``record`` (the
    default) the report is remembered on the obs state so
    ``obs.summary()`` embeds its digest and the stream exporter tags
    subsequent snapshot lines."""
    if isinstance(spec, str):
        spec = SloSpec.parse(spec)
    report = spec.evaluate(rolling=rolling, registry=registry, now=now)
    if record:
        STATE.last_slo = report
    return report
