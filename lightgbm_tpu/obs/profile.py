"""Device-time attribution: XLA cost analysis, profiler traces, and the
phase-attribution report behind ``bench.py --explain``.

Three layers, all dependency-light and failure-tolerant (every JAX
surface here has shifted across releases, and a missing backend
counter must degrade to ``None``, never to an exception):

* :func:`cost_of` lowers + compiles a jitted callable on concrete
  operands and normalizes ``Compiled.cost_analysis()`` into a flat
  ``{flops, bytes_accessed, transcendentals}`` dict — the static
  FLOPs/bytes estimate per program that turns a measured stage time
  into an achieved-FLOPs / achieved-bandwidth number;
* :func:`device_trace` wraps ``jax.profiler.trace`` as a context
  manager that no-ops cleanly when the profiler is unavailable, so a
  ``--explain`` run can drop a Perfetto-compatible device profile next
  to the report;
* :func:`attribution_report` folds measured wall time + per-phase
  estimates into the report shape ``bench.py --explain`` emits: named
  phases, their share of the measured training wall time, and the
  coverage fraction (the acceptance bar is >= 0.9 — below that the
  report says so instead of pretending).

The per-phase *measurements* live with the probes themselves
(``DeviceGrower.profile_stage_plan`` / ``profile_phases`` /
``profile_psum`` in ops/grow.py); with ``profile_attribution`` on they
attach :func:`cost_of` estimates to each probe program.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

from .state import STATE

__all__ = ["enabled", "normalize_cost", "cost_of", "device_trace",
           "attribution_report"]

#: cost_analysis key aliases across jax/XLA versions
_FLOPS_KEYS = ("flops",)
_BYTES_KEYS = ("bytes accessed", "bytes_accessed")
_TRANS_KEYS = ("transcendentals",)


def enabled() -> bool:
    """True when probes should attach cost-analysis estimates."""
    return STATE.enabled and STATE.profile_attribution


def _pick(d: Dict, keys) -> Optional[float]:
    for k in keys:
        v = d.get(k)
        if v is not None:
            try:
                return float(v)
            except (TypeError, ValueError):
                return None
    return None


def normalize_cost(ca) -> Optional[Dict]:
    """Flatten a ``Compiled.cost_analysis()`` result.

    Handles both historical shapes — a list with one dict per device
    program and a plain dict — and returns ``{"flops", "bytes_accessed",
    "transcendentals"}`` (values ``None`` when the backend does not
    report them), or ``None`` for an empty/unusable analysis."""
    if ca is None:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    return {
        "flops": _pick(ca, _FLOPS_KEYS),
        "bytes_accessed": _pick(ca, _BYTES_KEYS),
        "transcendentals": _pick(ca, _TRANS_KEYS),
    }


def cost_of(fn, *args) -> Optional[Dict]:
    """Static per-program cost estimate for a jitted callable on the
    given concrete operands: lower, compile (a cache hit when the
    program already ran), normalize the XLA cost analysis.  Returns
    ``None`` when any step is unsupported on this backend — callers
    treat the estimate as optional garnish, never as a gate."""
    try:
        lowered = fn.lower(*args)
        return normalize_cost(lowered.compile().cost_analysis())
    except Exception:   # noqa: BLE001 — version/backend dependent
        return None


@contextlib.contextmanager
def device_trace(path: Optional[str]):
    """``jax.profiler.trace`` as a tolerant context manager: profiles
    into ``path`` when the profiler works here, silently does nothing
    when ``path`` is falsy or the profiler is unavailable (some CPU
    builds, nested-trace errors)."""
    if not path:
        yield False
        return
    try:
        import jax.profiler as _prof
        cm = _prof.trace(path)
    except Exception:   # noqa: BLE001 — profiler optional by design
        yield False
        return
    try:
        with cm:
            yield True
    except Exception:   # noqa: BLE001
        yield False


def attribution_report(measured_ms: float, phases_ms: Dict[str, float],
                       costs: Optional[Dict[str, Optional[Dict]]] = None,
                       ) -> Dict:
    """Fold per-phase estimates into the ``--explain`` report.

    ``measured_ms`` is the ground truth (the timed training region);
    ``phases_ms`` maps phase name -> estimated ms over that same
    region.  The report carries each phase's ms and share, the
    unattributed residual, and ``coverage`` = attributed/measured
    (clamped to 1.0 — probes measured hotter than the run overshoot,
    which is misattribution of a different kind and is reported
    verbatim in ``attributed_ratio``).  ``costs`` optionally maps phase
    name -> :func:`cost_of` dict; phases with both a time and a FLOPs
    estimate gain an achieved-GFLOP/s figure."""
    measured_ms = float(measured_ms)
    total = sum(float(v) for v in phases_ms.values())
    phases = {}
    for name in sorted(phases_ms, key=lambda k: -float(phases_ms[k])):
        ms = float(phases_ms[name])
        entry = {
            "ms": round(ms, 3),
            "share": round(ms / measured_ms, 4) if measured_ms > 0
            else None,
        }
        cost = (costs or {}).get(name)
        if cost:
            entry["cost"] = {k: v for k, v in cost.items()
                             if v is not None}
            flops = cost.get("flops")
            if flops and ms > 0:
                entry["achieved_gflops"] = round(flops / (ms * 1e6), 2)
        phases[name] = entry
    ratio = total / measured_ms if measured_ms > 0 else 0.0
    return {
        "measured_ms": round(measured_ms, 3),
        "attributed_ms": round(total, 3),
        "attributed_ratio": round(ratio, 4),
        "coverage": round(min(ratio, 1.0), 4),
        "unattributed_ms": round(max(measured_ms - total, 0.0), 3),
        "phases": phases,
    }
