"""Rolling-window aggregation: live rates and percentiles, not lifetime.

The cumulative :class:`~.registry.MetricsRegistry` answers "what did
this process do since it started" — the right question for a bench
digest, the wrong one for a live SLO: a p95 reservoir that mixes
window 0's cold compiles with window 40's steady state cannot express
"p95 latency over the last 60 seconds".  :class:`RollingRegistry`
keeps the same three metric kinds time-bucketed into a fixed ring:

* **counters** — one integer cell per time bucket; a window query sums
  the in-window cells into a delta and a per-second rate;
* **gauges** — a bounded list of (timestamp, value) *transitions*, so a
  window query can reconstruct the time-weighted mean (the fraction of
  the window a 0/1 gauge like ``serve.degraded`` spent at 1 — breaker
  dark time — falls out of this);
* **timings** — per-bucket histograms over **fixed log-spaced bounds**
  (:data:`HIST_BOUNDS`), so p50/p95/p99 over "the last N seconds" are
  computed by merging in-window bucket counts.  Reported percentiles
  are always one of the fixed bound values (clamped to the window max),
  which makes them deterministic under replayed timestamps: the same
  (timestamp, value) sequence always yields the same snapshot.

Everything is wall-clock driven (``clock`` injectable for tests),
thread-safe behind one lock, and bounded: memory is
O(names x num_buckets x len(HIST_BOUNDS)) regardless of run length.
The registry records nothing by itself — ``lightgbm_tpu.obs`` mirrors
its ``inc``/``set_gauge``/``observe`` calls here while telemetry is
enabled, so the disabled hot path stays a single flag check.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, List, Optional

#: fixed log-spaced timing-histogram bounds (seconds): 1 µs .. ~80 s at
#: ratio 10^(1/12) ≈ 1.21 per bucket, so a percentile estimate is at
#: most ~21% above the true value.  Observations past the last bound
#: land in an overflow cell and report as the window max.
HIST_BOUNDS: tuple = tuple(1e-6 * 10 ** (i / 12) for i in range(96))

#: transitions kept per gauge; beyond this the oldest are discarded
#: (older than any realistic window anyway)
MAX_GAUGE_TRANSITIONS = 512


class _Cells:
    """A ring of per-bucket cells addressed by absolute bucket epoch."""

    __slots__ = ("epochs",)

    def __init__(self, n: int):
        self.epochs = [-1] * n

    def slot(self, epoch: int) -> Optional[int]:
        """(ring index) for ``epoch``; None when ``epoch`` is older
        than the slot's current tenant (a late out-of-order record —
        dropped, never double-counted into a newer bucket).  Callers
        compare ``epochs[i] != epoch`` to detect a stale slot, reset
        its payload, then stamp ``epochs[i] = epoch``."""
        i = epoch % len(self.epochs)
        if self.epochs[i] > epoch:
            return None
        return i


class _RollCounter(_Cells):
    __slots__ = ("values",)

    def __init__(self, n: int):
        super().__init__(n)
        self.values = [0] * n

    def add(self, epoch: int, value: int) -> None:
        i = self.slot(epoch)
        if i is None:
            return
        if self.epochs[i] != epoch:
            self.values[i] = 0
            self.epochs[i] = epoch
        self.values[i] += value


class _RollTiming(_Cells):
    __slots__ = ("counts", "totals", "maxes", "hists")

    def __init__(self, n: int):
        super().__init__(n)
        self.counts = [0] * n
        self.totals = [0.0] * n
        self.maxes = [0.0] * n
        self.hists: List[Optional[List[int]]] = [None] * n

    def add(self, epoch: int, seconds: float) -> None:
        i = self.slot(epoch)
        if i is None:
            return
        if self.epochs[i] != epoch or self.hists[i] is None:
            self.hists[i] = [0] * (len(HIST_BOUNDS) + 1)
            self.counts[i] = 0
            self.totals[i] = 0.0
            self.maxes[i] = 0.0
            self.epochs[i] = epoch
        self.counts[i] += 1
        self.totals[i] += seconds
        if seconds > self.maxes[i]:
            self.maxes[i] = seconds
        self.hists[i][_bound_index(seconds)] += 1


def _bound_index(seconds: float) -> int:
    """Index of the smallest bound >= seconds (len(HIST_BOUNDS) =
    overflow).  Closed-form from the log spacing, then nudged for
    float edge cases so the invariant holds exactly."""
    if seconds <= HIST_BOUNDS[0]:
        return 0
    k = int(math.ceil(12.0 * math.log10(seconds / 1e-6)))
    k = max(0, min(k, len(HIST_BOUNDS)))
    while k > 0 and HIST_BOUNDS[k - 1] >= seconds:
        k -= 1
    while k < len(HIST_BOUNDS) and HIST_BOUNDS[k] < seconds:
        k += 1
    return k


def _merged_percentile(merged: List[int], total: int, q: float,
                       wmax: float) -> float:
    """q-quantile of a merged histogram: the fixed upper bound of the
    bucket where the cumulative count crosses q, clamped to the window
    max (overflow bucket reports the max)."""
    rank = max(1, int(math.ceil(q * total)))
    cum = 0
    for j, c in enumerate(merged):
        cum += c
        if cum >= rank:
            bound = HIST_BOUNDS[j] if j < len(HIST_BOUNDS) else wmax
            return min(bound, wmax)
    return wmax


class RollingRegistry:
    """Time-bucketed counters / gauges / timing histograms (see module
    docstring).  ``bucket_seconds`` x ``num_buckets`` is the maximum
    queryable window (default 1 s x 120 = 2 minutes); queries may ask
    for any smaller ``window_s``."""

    def __init__(self, bucket_seconds: float = 1.0,
                 num_buckets: int = 120,
                 clock: Callable[[], float] = time.time):
        if bucket_seconds <= 0 or num_buckets < 2:
            raise ValueError("bucket_seconds must be > 0 and "
                             "num_buckets >= 2")
        self.bucket_seconds = float(bucket_seconds)
        self.num_buckets = int(num_buckets)
        self._clock = clock
        self._lock = threading.Lock()
        self._counters: Dict[str, _RollCounter] = {}
        self._gauges: Dict[str, List] = {}     # name -> [(t, value), ...]
        self._timings: Dict[str, _RollTiming] = {}

    # -- recording --------------------------------------------------------
    def _epoch(self, now: Optional[float]) -> int:
        return int((self._clock() if now is None else now)
                   // self.bucket_seconds)

    def inc(self, name: str, value: int = 1,
            now: Optional[float] = None) -> None:
        e = self._epoch(now)
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = _RollCounter(self.num_buckets)
            c.add(e, value)

    def set_gauge(self, name: str, value: float,
                  now: Optional[float] = None) -> None:
        with self._lock:
            # clock read INSIDE the lock: concurrent writers must not
            # interleave into a non-monotone transition list
            t = self._clock() if now is None else now
            trans = self._gauges.get(name)
            if trans is None:
                trans = self._gauges[name] = []
            if trans and t < trans[-1][0]:
                # late out-of-order write: dropped, matching the
                # counter/timing ring contract — gauge_last stays the
                # newest value and gauge_mean never integrates a
                # negative segment
                return
            # only CHANGES are transitions; a re-set of the same value
            # costs nothing, so per-request gauge writes stay bounded
            if not trans or trans[-1][1] != value:
                trans.append((t, value))
                if len(trans) > MAX_GAUGE_TRANSITIONS:
                    del trans[:len(trans) - MAX_GAUGE_TRANSITIONS]

    def observe(self, name: str, seconds: float,
                now: Optional[float] = None) -> None:
        e = self._epoch(now)
        with self._lock:
            t = self._timings.get(name)
            if t is None:
                t = self._timings[name] = _RollTiming(self.num_buckets)
            t.add(e, seconds)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timings.clear()

    # -- window queries ---------------------------------------------------
    def _window_epochs(self, window_s: Optional[float],
                       now: Optional[float]):
        now = self._clock() if now is None else now
        w = (self.bucket_seconds * self.num_buckets
             if window_s is None else float(window_s))
        nb = min(self.num_buckets,
                 max(1, int(math.ceil(w / self.bucket_seconds))))
        e_hi = int(now // self.bucket_seconds)
        return now, nb * self.bucket_seconds, e_hi - nb + 1, e_hi

    def counter_delta(self, name: str, window_s: Optional[float] = None,
                      now: Optional[float] = None) -> int:
        _, _, lo, hi = self._window_epochs(window_s, now)
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                return 0
            return sum(v for e, v in zip(c.epochs, c.values)
                       if lo <= e <= hi)

    def gauge_last(self, name: str) -> Optional[float]:
        with self._lock:
            trans = self._gauges.get(name)
            return trans[-1][1] if trans else None

    def gauge_mean(self, name: str, window_s: Optional[float] = None,
                   now: Optional[float] = None) -> Optional[float]:
        """Time-weighted mean over the window.  The value holds from
        each transition until the next; before the first known
        transition the value is unknown, so integration starts there
        (None when the gauge has no transition at or before ``now``)."""
        now, w, _, _ = self._window_epochs(window_s, now)
        ws = now - w
        with self._lock:
            trans = list(self._gauges.get(name) or ())
        if not trans or trans[0][0] > now:
            return None
        # value at window start = last transition at or before ws
        start_t, start_v = ws, None
        segs = []
        for t, v in trans:
            if t > now:
                break
            if t <= ws:
                start_v = v
            else:
                segs.append((t, v))
        t0 = ws if start_v is not None else segs[0][0]
        cur = start_v if start_v is not None else None
        total = 0.0
        weighted = 0.0
        prev_t = t0
        for t, v in segs:
            if cur is not None:
                weighted += cur * (t - prev_t)
                total += t - prev_t
            cur = v
            prev_t = t
        if cur is None:
            return None
        weighted += cur * (now - prev_t)
        total += now - prev_t
        if total <= 0:
            return float(cur)
        return weighted / total

    def percentile(self, name: str, q: float,
                   window_s: Optional[float] = None,
                   now: Optional[float] = None) -> Optional[float]:
        """q-quantile (0 < q <= 1) of the merged in-window histogram:
        the fixed upper bound of the bucket where the cumulative count
        crosses q, clamped to the window max.  None with no samples."""
        _, _, lo, hi = self._window_epochs(window_s, now)
        with self._lock:
            t = self._timings.get(name)
            if t is None:
                return None
            merged = [0] * (len(HIST_BOUNDS) + 1)
            total = 0
            wmax = 0.0
            for i, e in enumerate(t.epochs):
                if lo <= e <= hi and t.counts[i]:
                    total += t.counts[i]
                    if t.maxes[i] > wmax:
                        wmax = t.maxes[i]
                    h = t.hists[i]
                    for j, c in enumerate(h):
                        merged[j] += c
        if total == 0:
            return None
        return _merged_percentile(merged, total, q, wmax)

    def timing_stats(self, name: str, window_s: Optional[float] = None,
                     now: Optional[float] = None) -> Optional[Dict]:
        # one locked pass merges the in-window histogram; all three
        # quantiles read from the merged counts (same resolved clock,
        # so count/max/percentiles always describe ONE window)
        _, w, lo, hi = self._window_epochs(window_s, now)
        with self._lock:
            t = self._timings.get(name)
            if t is None:
                return None
            count = 0
            total = 0.0
            wmax = 0.0
            merged = [0] * (len(HIST_BOUNDS) + 1)
            for i, e in enumerate(t.epochs):
                if lo <= e <= hi and t.counts[i]:
                    count += t.counts[i]
                    total += t.totals[i]
                    if t.maxes[i] > wmax:
                        wmax = t.maxes[i]
                    for j, c in enumerate(t.hists[i]):
                        merged[j] += c
        if count == 0:
            return None
        out = {"count": count, "total_s": round(total, 6),
               "mean_s": round(total / count, 6), "max_s": round(wmax, 6)}
        for tag, q in (("p50_s", 0.50), ("p95_s", 0.95), ("p99_s", 0.99)):
            out[tag] = round(_merged_percentile(merged, count, q, wmax), 6)
        return out

    def window(self, window_s: Optional[float] = None,
               now: Optional[float] = None) -> Dict:
        """Full rolling snapshot over the window: counter deltas+rates,
        gauge last/time-weighted mean, timing count/percentiles.  Only
        names with in-window activity appear (gauges: any transition at
        or before now)."""
        now, w, lo, hi = self._window_epochs(window_s, now)
        with self._lock:
            counter_names = list(self._counters)
            gauge_names = list(self._gauges)
            timing_names = list(self._timings)
        counters = {}
        for name in counter_names:
            delta = self.counter_delta(name, window_s, now)
            if delta:
                counters[name] = {"delta": delta,
                                  "rate_per_s": round(delta / w, 6)}
        gauges = {}
        for name in gauge_names:
            mean = self.gauge_mean(name, window_s, now)
            last = self.gauge_last(name)
            if last is not None:
                gauges[name] = {
                    "last": last,
                    "mean": None if mean is None else round(mean, 6)}
        timings = {}
        for name in timing_names:
            stat = self.timing_stats(name, window_s, now)
            if stat is not None:
                timings[name] = stat
        return {"bucket_s": self.bucket_seconds,
                "window_s": round(w, 3),
                "now_unix": round(now, 3),
                "counters": counters, "gauges": gauges,
                "timings": timings}
