"""JIT recompile tracking: count and attribute compiles per shape signature.

In the retrain-every-window pattern (PAPER.md's LRB harness) recompiles
are the silent killer: every fresh ``DeviceGrower`` owns fresh
``jax.jit`` objects, so a window whose padded shape differs — or merely
a new grower instance without a warm persistent XLA cache — pays a full
trace+compile that the wall-clock numbers otherwise attribute to
"training".  ``track_jit`` wraps a jitted callable and detects the
first call per abstract signature (shapes/dtypes of array leaves,
qualnames for callables, ``repr`` for the rest): that call is the one
that traces and compiles, so its duration is recorded as a
``jit_compile:<name>`` span and counted per signature in the registry.

When observability is disabled the wrapper is a single flag check plus
one indirect call — no signature computation, no allocation.
"""

from __future__ import annotations

import itertools
import re
import time
import weakref
from typing import Tuple

from .state import STATE

# plain objects (e.g. a static `self` of a jitted method) default to an
# address-bearing repr; addresses get reused, so two distinct instances
# could alias one signature and a real recompile would go unrecorded.
# A weak per-object sequence number is collision-free and dies with the
# object.
_ADDR_REPR_RE = re.compile(r" at 0x[0-9a-fA-F]+>")
_obj_seq = weakref.WeakKeyDictionary()
_obj_counter = itertools.count()


def _obj_token(leaf) -> str:
    try:
        seq = _obj_seq.get(leaf)
        if seq is None:
            seq = next(_obj_counter)
            _obj_seq[leaf] = seq
        return f"{type(leaf).__name__}#{seq}"
    except TypeError:            # unhashable / not weak-referenceable
        return repr(leaf)


def _leaf_sig(leaf) -> str:
    sig = getattr(leaf, "obs_signature", None)
    if sig is not None:
        return str(sig)
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    if callable(leaf):
        return getattr(leaf, "__qualname__", None) \
            or getattr(leaf, "__name__", "<callable>")
    r = repr(leaf)
    return _obj_token(leaf) if _ADDR_REPR_RE.search(r) else r


def signature_of(args, kwargs, static_info: Tuple = ()) -> str:
    import jax
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    parts = [str(v) for v in static_info]
    parts.extend(_leaf_sig(l) for l in leaves)
    return "(" + ", ".join(parts) + ")"


class TrackedJit:
    """Callable wrapper around a jitted function.

    Each instance keeps its own seen-signature set because each
    underlying ``jax.jit`` object owns its own compile cache: a new
    instance recompiles even signatures an older instance already
    compiled, and that per-instance cost is precisely what windowed
    retraining needs surfaced.  Counts accumulate into the shared
    registry under ``name``, so cross-window totals survive grower
    churn.
    """

    __slots__ = ("name", "fn", "static_info", "_seen")

    def __init__(self, name, fn, static_info=()):
        self.name = name
        self.fn = fn
        self.static_info = tuple(static_info)
        self._seen = set()

    def _cache_size(self) -> int:
        try:
            return self.fn._cache_size()
        except Exception:
            return -1

    def __call__(self, *args, **kwargs):
        st = STATE
        if not st.enabled:
            return self.fn(*args, **kwargs)
        sig = signature_of(args, kwargs, self.static_info)
        if sig in self._seen:
            return self.fn(*args, **kwargs)
        # first tracked call for this signature on this instance: it
        # traces + compiles synchronously (dispatch stays async), so its
        # wall time is the compile cost.  The jit cache size confirms a
        # trace really happened — a cache warmed before tracking was
        # enabled (e.g. a disabled warm-up run on the same module-level
        # jit) must not count as a compile.
        if len(self._seen) > 4096:
            # unbounded instance churn (fresh static-self objectives per
            # retrain window) must not grow this set forever; a clear
            # costs at most one redundant recount per signature
            self._seen.clear()
        self._seen.add(sig)
        before = self._cache_size()
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        dur = time.perf_counter() - t0
        if before < 0 or self._cache_size() > before:
            st.registry.record_compile(self.name, sig)
            st.registry.inc("jit.compiles_total")
            st.registry.observe(f"jit_compile.{self.name}", dur)
            st.trace.add(f"jit_compile:{self.name}", cat="jit", t0=t0,
                         dur=dur, args={"signature": sig})
        return out

    # pass through jit-object attributes (lower, clear_cache, ...)
    def __getattr__(self, item):
        return getattr(self.fn, item)

    # descriptor protocol: a TrackedJit wrapping a static-self jitted
    # METHOD (`@functools.partial(jax.jit, static_argnums=0)`) must bind
    # like the jit object it replaced, or `self._grad(...)` would drop
    # the receiver.  Per-instance signatures are correct telemetry here:
    # a static self really does recompile per instance.
    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        import functools
        return functools.partial(self, obj)


def track_jit(name: str, fn, static_info: Tuple = ()) -> TrackedJit:
    """Wrap ``fn`` (typically a ``jax.jit`` result) with compile tracking."""
    return TrackedJit(name, fn, static_info)
