"""Process-global observability state.

One module-level singleton keeps the enabled flag, the registry and the
trace buffer, so every instrumentation site shares the same fast-path
check: ``if not STATE.enabled: return``.  Kept in its own module (not
``obs/__init__``) so instrumented modules can import it without pulling
the exporters, and so there is exactly one import direction:
``jit_track``/``hooks``/``__init__`` -> ``state`` -> ``registry``/``events``.
"""

from __future__ import annotations

from typing import Optional

from .events import TraceBuffer
from .registry import MetricsRegistry


class ObsState:
    __slots__ = ("enabled", "sync", "trace_context",
                 "profile_attribution", "registry", "trace", "rolling",
                 "rolling_opt_out", "exporter", "last_slo",
                 "pending_slo_spec",
                 "metrics_path", "trace_path", "events_path",
                 "_atexit_registered", "_mem_unavailable",
                 "_trace_flushed")

    def __init__(self):
        self.enabled = False
        # when True, iteration instrumentation blocks on the device value
        # before stopping the clock (honest attribution; serialises the
        # pipeline — leave off for production runs)
        self.sync = False
        # causal trace-context propagation (obs/tracing.py): spans gain
        # trace_id/span_id/parent_id and contexts flow across the
        # pipeline/serve thread boundaries; off = zero context objects
        self.trace_context = False
        # attach XLA cost-analysis (FLOPs / bytes) to the profile
        # probes (obs/profile.py; bench.py --explain turns it on)
        self.profile_attribution = False
        self.registry = MetricsRegistry()
        self.trace = TraceBuffer()
        # rolling-window mirror of the registry (obs/rolling.py) —
        # created when telemetry is enabled, None while disabled so the
        # hot path stays a single flag check; rolling_opt_out persists
        # an explicit configure(rolling=False) across the per-window
        # configure_from_config calls
        self.rolling = None
        self.rolling_opt_out = False
        # background StreamExporter (obs/export.py), None until a
        # stream/prom path or scrape port is configured
        self.exporter = None
        # most recent SloReport (obs/slo.py), embedded in summary() and
        # stream lines
        self.last_slo = None
        # a parsed SloSpec configured before any exporter exists —
        # adopted by the next exporter start instead of being dropped
        self.pending_slo_spec = None
        self.metrics_path: Optional[str] = None
        self.trace_path: Optional[str] = None
        self.events_path: Optional[str] = None
        self._atexit_registered = False
        self._mem_unavailable = False
        # (path, event_count, dropped) of the last trace write, so
        # repeated flushes (one per train() in a windowed loop) skip
        # re-serializing an unchanged buffer
        self._trace_flushed = None


STATE = ObsState()
