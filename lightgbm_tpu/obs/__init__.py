"""Structured telemetry: metrics registry, trace events, recompile and
device-memory tracking.

One process-global :class:`~.state.ObsState` backs the whole subsystem.
Everything is **off by default** and every instrumentation site reduces
to a single flag check when disabled, so the hot path pays nothing.

Enable it three ways (any one suffices):

* config params: ``metrics_enabled=true`` and/or any output path —
  ``metrics_path`` / ``trace_path`` / ``events_path`` / the streaming
  exporter's ``stream_path`` / ``prom_path`` / ``obs_http_port``
  (picked up by ``GBDT.init_train``, so ``engine.train``, the sklearn
  wrapper, the C API and the embedded windowed harness all inherit it);
* env vars: ``LGBM_TPU_METRICS=<path|1>`` / ``LGBM_TPU_TRACE=<path>``
  / ``LGBM_TPU_EVENTS=<path.jsonl>`` / ``LGBM_TPU_STREAM`` /
  ``LGBM_TPU_PROM`` / ``LGBM_TPU_OBS_HTTP`` — snapshot files are
  written at process exit (the stream/exposition files refresh live),
  which is how the ``src/capi`` harness gets per-window retrain
  telemetry without a code change;
* programmatically: ``obs.configure(enabled=True, ...)`` (what
  ``bench.py --metrics/--trace`` does).

The registry subsumes the legacy ``TRAIN_TIMER``: while enabled, every
``Timer.stop`` also lands in the registry as a ``phase.<tag>`` timing,
so phase totals/counts/percentiles appear in the metrics snapshot next
to iteration timings, recompile counts and memory peaks.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

from . import profile  # noqa: F401  (re-export)
from . import tracing  # noqa: F401  (re-export)
from .jit_track import track_jit  # noqa: F401  (re-export)
from .registry import MetricsRegistry  # noqa: F401  (re-export)
from .rolling import RollingRegistry
from .state import STATE

SCHEMA_NAME = "lightgbm-tpu-metrics"
SCHEMA_VERSION = 2

__all__ = [
    "enabled", "configure", "configure_from_config", "reset", "registry",
    "rolling", "rolling_snapshot", "tracing", "profile",
    "inc", "set_gauge", "max_gauge", "observe", "span", "span_event",
    "instant", "counter_sample", "track_jit", "sample_device_memory",
    "device_memory_stats", "snapshot", "summary", "dump_metrics",
    "dump_trace", "dump_events_jsonl", "flush", "iteration_hooks",
]


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def enabled() -> bool:
    return STATE.enabled


def registry() -> MetricsRegistry:
    return STATE.registry


def rolling() -> Optional[RollingRegistry]:
    """The rolling-window mirror (None while telemetry is disabled)."""
    return STATE.rolling


def configure(enabled: Optional[bool] = None,
              metrics_path: Optional[str] = None,
              trace_path: Optional[str] = None,
              events_path: Optional[str] = None,
              sync: Optional[bool] = None,
              rolling=None,
              stream_path: Optional[str] = None,
              prom_path: Optional[str] = None,
              export_interval_s: Optional[float] = None,
              http_port: Optional[int] = None,
              slo_spec=None,
              trace_context: Optional[bool] = None,
              profile_attribution: Optional[bool] = None) -> None:
    """Update the global observability state.

    Additive: ``None`` leaves a setting untouched, and enabling twice
    keeps the accumulated registry/trace (windowed retraining wants
    cross-window totals).  Use :func:`reset` for a clean slate.

    Enabling also installs the rolling-window mirror (``rolling=False``
    opts out; a :class:`~.rolling.RollingRegistry` instance replaces
    it).  ``stream_path`` (JSONL time series) / ``prom_path``
    (Prometheus exposition file) / ``http_port`` (localhost scrape
    endpoint; 0 picks a free port) start the background
    :class:`~.export.StreamExporter`, flushing every
    ``export_interval_s`` seconds (default 5); ``slo_spec`` makes each
    flush carry a fresh SLO evaluation (docs/Observability.md).
    ``trace_context`` turns causal span propagation on/off
    (obs/tracing.py); ``profile_attribution`` attaches XLA
    cost-analysis FLOPs/bytes to the profile probes (obs/profile.py).
    """
    if metrics_path:
        STATE.metrics_path = metrics_path
    if trace_path:
        STATE.trace_path = trace_path
    if events_path:
        STATE.events_path = events_path
    if sync is not None:
        STATE.sync = bool(sync)
    if trace_context is not None:
        STATE.trace_context = bool(trace_context)
    if profile_attribution is not None:
        STATE.profile_attribution = bool(profile_attribution)
    if enabled is not None:
        was = STATE.enabled
        STATE.enabled = bool(enabled)
        if STATE.enabled and not was:
            _install_timer_sink()
        elif was and not STATE.enabled:
            _remove_timer_sink()
    if rolling is False:
        # sticky: the per-window configure_from_config calls pass
        # rolling=None and must not silently undo an explicit opt-out
        STATE.rolling = None
        STATE.rolling_opt_out = True
    elif isinstance(rolling, RollingRegistry):
        STATE.rolling = rolling
        STATE.rolling_opt_out = False
    elif rolling is True:
        STATE.rolling_opt_out = False
    if (STATE.enabled and STATE.rolling is None
            and not STATE.rolling_opt_out):
        STATE.rolling = RollingRegistry()
    if slo_spec is not None:
        # parse HERE so a typo'd spec raises at configure time even
        # when no exporter exists yet; an exporter started later (or
        # already running) adopts it
        from .slo import SloSpec
        if isinstance(slo_spec, str):
            slo_spec = SloSpec.parse(slo_spec)
        STATE.pending_slo_spec = slo_spec
        if STATE.exporter is not None and not (
                stream_path or prom_path or http_port is not None):
            STATE.exporter.set_slo_spec(slo_spec)
    if stream_path or prom_path or http_port is not None:
        _ensure_exporter(stream_path, prom_path, export_interval_s,
                         http_port, slo_spec)
    if STATE.enabled and (STATE.metrics_path or STATE.trace_path
                          or STATE.events_path
                          or STATE.exporter is not None):
        _register_atexit()


def _ensure_exporter(stream_path, prom_path, export_interval_s,
                     http_port, slo_spec) -> None:
    """Start (or retarget) the background exporter.  Idempotent for the
    per-window ``configure_from_config`` call: matching paths only
    update interval/spec, they never restart the threads.  ADDITIVE
    like the rest of configure(): an unspecified target inherits the
    running exporter's (env-started stream + param-added prom file
    coexist), so a partial reconfigure never silently drops an
    export."""
    from .export import StreamExporter
    if slo_spec is None:
        slo_spec = STATE.pending_slo_spec
    exp = STATE.exporter
    if exp is not None:
        stream_path = stream_path or exp.stream_path
        prom_path = prom_path or exp.prom_path
        if http_port is None:
            http_port = exp._http_port_requested
        if exp.matches(stream_path, prom_path, http_port):
            if export_interval_s:
                exp.interval_s = max(float(export_interval_s), 0.05)
            if slo_spec is not None:
                exp.set_slo_spec(slo_spec)
            return
        exp.stop()
    STATE.exporter = StreamExporter(
        stream_path=stream_path, prom_path=prom_path,
        interval_s=export_interval_s or 5.0,
        http_port=http_port, slo_spec=slo_spec).start()


def configure_from_config(cfg) -> None:
    """Pick up ``metrics_enabled`` / the telemetry paths from a Config.

    Called on every ``GBDT.init_train`` — i.e. once per booster, which
    in the windowed harness means once per retrain window — so it must
    be cheap and must never *disable* telemetry another component turned
    on (first window enables, later windows accumulate).
    """
    want = bool(getattr(cfg, "metrics_enabled", False))
    trace_path = str(getattr(cfg, "trace_path", "") or "")
    metrics_path = str(getattr(cfg, "metrics_path", "") or "")
    events_path = str(getattr(cfg, "events_path", "") or "")
    stream_path = str(getattr(cfg, "stream_path", "") or "")
    prom_path = str(getattr(cfg, "prom_path", "") or "")
    http_port = int(getattr(cfg, "obs_http_port", 0) or 0)
    trace_ctx = bool(getattr(cfg, "trace_context_enabled", False))
    profile_attr = bool(getattr(cfg, "profile_attribution", False))
    if not (want or trace_path or metrics_path or events_path
            or stream_path or prom_path or http_port or trace_ctx
            or profile_attr):
        return
    configure(enabled=True, metrics_path=metrics_path or None,
              trace_path=trace_path or None,
              events_path=events_path or None,
              stream_path=stream_path or None,
              prom_path=prom_path or None,
              export_interval_s=float(getattr(
                  cfg, "obs_export_interval", 0) or 0) or None,
              http_port=http_port if http_port > 0 else None,
              # additive like every other setting: a later window's
              # config without the flag must not disable propagation
              trace_context=True if trace_ctx else None,
              profile_attribution=True if profile_attr else None)


def reset() -> None:
    """Clear all accumulated metrics and events (keeps enabled/paths)."""
    STATE.registry.reset()
    STATE.trace.reset()
    if STATE.rolling is not None:
        STATE.rolling.reset()
    STATE.last_slo = None
    STATE._mem_unavailable = False
    STATE._trace_flushed = None


# ---------------------------------------------------------------------------
# recording primitives
# ---------------------------------------------------------------------------

def inc(name: str, value: int = 1) -> None:
    if STATE.enabled:
        STATE.registry.inc(name, value)
        r = STATE.rolling
        if r is not None:
            r.inc(name, value)


def set_gauge(name: str, value: float) -> None:
    if STATE.enabled:
        STATE.registry.set_gauge(name, value)
        r = STATE.rolling
        if r is not None:
            r.set_gauge(name, value)


def max_gauge(name: str, value: float) -> None:
    if STATE.enabled:
        STATE.registry.max_gauge(name, value)


def observe(name: str, seconds: float) -> None:
    if STATE.enabled:
        STATE.registry.observe(name, seconds)
        r = STATE.rolling
        if r is not None:
            r.observe(name, seconds)


class _NullSpan:
    """Shared no-op context manager: the disabled fast path allocates
    nothing.  ``sync_value`` accepts and discards writes, so the
    documented ``sp.sync_value = arr`` pattern is safe whether or not
    telemetry is on — without the shared singleton retaining a
    reference to a (possibly multi-MB) device array."""

    __slots__ = ()

    @property
    def sync_value(self):
        return None

    @sync_value.setter
    def sync_value(self, value):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args):
        pass


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "t0", "sync_value",
                 "trace_id", "span_id", "parent_id", "_ctx_token")

    def __init__(self, name, cat, args):
        self.name = name
        self.cat = cat
        self.args = args
        self.sync_value = None
        if STATE.trace_context:
            # becomes the current context for everything opened inside
            # this span on this thread (obs/tracing.py); a cross-thread
            # parent arrives via tracing.set_current before the span
            parent = tracing._CURRENT.get()
            self.trace_id = (parent.trace_id if parent is not None
                             else tracing.new_id())
            self.span_id = tracing.new_id()
            self.parent_id = (parent.span_id if parent is not None
                              else None)
            self._ctx_token = tracing._CURRENT.set(
                tracing.SpanContext(self.trace_id, self.span_id))
        else:
            self.trace_id = self.span_id = self.parent_id = None
            self._ctx_token = None
        self.t0 = time.perf_counter()

    def set(self, **args):
        """Attach attributes after the span opened."""
        self.args.update(args)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._ctx_token is not None:
            tracing._CURRENT.reset(self._ctx_token)
            self._ctx_token = None
        if STATE.sync and self.sync_value is not None:
            import jax
            jax.block_until_ready(self.sync_value)
        dur = time.perf_counter() - self.t0
        STATE.registry.observe(self.name, dur)
        r = STATE.rolling
        if r is not None:
            r.observe(self.name, dur)
        if self.span_id is not None:
            self.args["trace_id"] = self.trace_id
            self.args["span_id"] = self.span_id
            if self.parent_id is not None:
                self.args["parent_id"] = self.parent_id
        STATE.trace.add(self.name, cat=self.cat, t0=self.t0, dur=dur,
                        args=self.args or None)
        return False


def span(name: str, cat: str = "train", **args):
    """Timed span: ``with obs.span("grow_tree", iter=k): ...``.

    Records a timing observation under ``name`` and a trace event.  Set
    ``span.sync_value = device_array`` inside the block to make the exit
    block on the device value when sync profiling is on (honest device
    attribution; guarded so production runs never block).
    """
    if not STATE.enabled:
        return _NULL_SPAN
    return _Span(name, cat, dict(args) if args else {})


def span_event(name: str, t0: float, dur: float, cat: str = "serve",
               **args) -> None:
    """Record a completed span from explicit timestamps — for work
    whose start/end were observed on different threads (a micro-batch
    request: submit on the caller, flush on the worker).  Pass
    ``trace_id``/``parent_id`` args (``tracing.link_args``) to place it
    in a causal chain."""
    if STATE.enabled:
        STATE.trace.add(name, cat=cat, t0=t0, dur=dur, args=args or None)


def instant(name: str, cat: str = "train", **args) -> None:
    """Zero-duration marker event."""
    if STATE.enabled:
        STATE.trace.add(name, cat=cat, kind="instant", args=args or None)


def counter_sample(name: str, cat: str = "mem", **values) -> None:
    """Chrome-trace counter track sample (renders as a stacked area)."""
    if STATE.enabled:
        STATE.trace.add(name, cat=cat, kind="counter", args=values)


# ---------------------------------------------------------------------------
# device memory
# ---------------------------------------------------------------------------

def device_memory_stats() -> Optional[Dict[str, int]]:
    """Raw ``Device.memory_stats()`` of the first device, or None when
    the backend does not expose it (CPU does not)."""
    if STATE._mem_unavailable:
        return None
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        stats = None
    if not stats:
        STATE._mem_unavailable = True
        return None
    return stats


def sample_device_memory() -> None:
    """Record bytes-in-use / peak gauges and a trace counter sample."""
    if not STATE.enabled or STATE._mem_unavailable:
        return
    stats = device_memory_stats()
    if stats is None:
        return
    in_use = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use")
    if in_use is not None:
        STATE.registry.set_gauge("device.bytes_in_use", int(in_use))
        counter_sample("device_memory", bytes_in_use=int(in_use))
    if peak is not None:
        STATE.registry.max_gauge("device.peak_bytes_in_use", int(peak))


# ---------------------------------------------------------------------------
# snapshot / export
# ---------------------------------------------------------------------------

def snapshot() -> Dict:
    """Full schema-versioned metrics document (see docs/Observability.md)."""
    doc = {
        "schema": SCHEMA_NAME,
        "schema_version": SCHEMA_VERSION,
        "created_unix": round(STATE.registry.created_unix, 3),
        "snapshot_unix": round(time.time(), 3),
        "enabled": STATE.enabled,
    }
    doc.update(STATE.registry.snapshot())
    mem = device_memory_stats()
    doc["device_memory"] = (
        {"bytes_in_use": int(mem.get("bytes_in_use", 0)),
         "peak_bytes_in_use": int(mem.get("peak_bytes_in_use", 0))}
        if mem else None)
    doc["events"] = {"recorded": len(STATE.trace),
                     "dropped": STATE.trace.dropped}
    doc["rolling"] = (STATE.rolling.window()
                      if STATE.rolling is not None else None)
    doc["slo"] = (STATE.last_slo.digest()
                  if STATE.last_slo is not None else None)
    return doc


def rolling_snapshot(window_s: Optional[float] = None) -> Optional[Dict]:
    """The rolling-window document alone (None while disabled)."""
    if STATE.rolling is None:
        return None
    return STATE.rolling.window(window_s)


def summary() -> Dict:
    """Compact digest for embedding in bench JSON lines: recompile
    counts per jitted fn, iteration p95, peak device memory, and —
    when the serving path ran — predict-latency percentiles + swap
    counts."""
    snap = STATE.registry.snapshot()
    iter_stat = snap["timings"].get("train.iter")
    compile_total = sum(v["compiles"] for v in snap["jit"].values())
    out = {
        "jit_compiles": {k: v["compiles"] for k, v in snap["jit"].items()},
        "jit_compiles_total": compile_total,
        "iter_p95_ms": round(iter_stat["p95_s"] * 1e3, 2)
        if iter_stat else None,
        "iter_p50_ms": round(iter_stat["p50_s"] * 1e3, 2)
        if iter_stat else None,
        "peak_device_bytes": STATE.registry.gauge(
            "device.peak_bytes_in_use"),
        "events_recorded": len(STATE.trace),
    }
    cc_req = snap["counters"].get("compile_cache.requests", 0)
    if cc_req:
        saved = snap["timings"].get("compile_cache.time_saved")
        out["compile_cache"] = {
            "requests": cc_req,
            "hits": snap["counters"].get("compile_cache.hits", 0),
            "misses": snap["counters"].get("compile_cache.misses", 0),
            "time_saved_s": round(saved["total_s"], 2) if saved else 0.0,
        }
    serve_stat = snap["timings"].get("serve.predict")
    if serve_stat:
        out["serve"] = {
            "predicts": serve_stat["count"],
            "predict_p50_ms": round(serve_stat["p50_s"] * 1e3, 3),
            "predict_p95_ms": round(serve_stat["p95_s"] * 1e3, 3),
            "swaps": snap["counters"].get("serve.swaps", 0),
            "rows": snap["counters"].get("serve.rows", 0),
        }
    fleet_stat = snap["timings"].get("serve.fleet.predict")
    if fleet_stat:
        out["fleet"] = {
            "predicts": fleet_stat["count"],
            "predict_p50_ms": round(fleet_stat["p50_s"] * 1e3, 3),
            "predict_p95_ms": round(fleet_stat["p95_s"] * 1e3, 3),
            "tenants": snap["gauges"].get("serve.fleet.tenants"),
            "replicas": snap["gauges"].get("serve.fleet.replicas"),
            "swaps": snap["counters"].get("serve.fleet.swaps", 0),
            "swap_shape_changes": snap["counters"].get(
                "serve.fleet.swap_shape_changes", 0),
            "rows": snap["counters"].get("serve.fleet.rows", 0),
            "fallback_requests": snap["counters"].get(
                "serve.fleet.fallback_requests", 0),
            "degraded_replicas": snap["gauges"].get(
                "serve.fleet.degraded_replicas"),
        }
    shard_devices = snap["gauges"].get("shard.devices")
    if shard_devices:
        # single-controller sharded training ran: attribute collective
        # time the way grow.hist.* attributes kernel routing — BENCH_r06
        # reads this digest to separate psum cost from histogram compute
        psum = snap["timings"].get("shard.psum")
        out["shard"] = {
            "devices": int(shard_devices),
            "local_rows": snap["gauges"].get("shard.local_rows"),
            "sharded_dispatches": snap["counters"].get(
                "grow.sharded_dispatches", 0),
            "psum_ms": round(psum["p50_s"] * 1e3, 3) if psum else None,
            "psum_probes": psum["count"] if psum else 0,
        }
        hosts = snap["gauges"].get("shard.hosts")
        if hosts and int(hosts) > 1:
            # pod-slice training: per-host ingest throughput and the
            # mapper-broadcast traffic join the shard digest so a
            # multi-controller run is distinguishable from a local
            # mesh at a glance (docs/Observability.md)
            out["shard"]["hosts"] = int(hosts)
            out["shard"]["ingest_rows_per_s"] = snap["gauges"].get(
                "ingest.rows_per_s")
            out["shard"]["broadcast_bytes"] = snap["counters"].get(
                "net.broadcast_bytes", 0)
    injected = sum(v for k, v in snap["counters"].items()
                   if k.startswith("fault."))
    retries = snap["counters"].get("retry.attempts", 0)
    fallback = snap["counters"].get("serve.fallback_requests", 0)
    if injected or retries or fallback:
        degraded = snap["timings"].get("serve.degraded_time")
        out["robust"] = {
            "faults_injected": injected,
            "retry_attempts": retries,
            "fallback_requests": fallback,
            "device_failures": snap["counters"].get(
                "serve.device_failures", 0),
            "degraded": snap["gauges"].get("serve.degraded"),
            "degraded_time_s": round(degraded["total_s"], 3)
            if degraded else 0.0,
            "checkpoints": snap["counters"].get(
                "pipeline.checkpoints", 0),
        }
    if any(k.startswith("soak.") for k in snap["counters"]):
        # a chaos soak ran (lightgbm_tpu/soak/): surface the injected
        # chaos alongside the serving digest so a SOAK_r* bench line is
        # self-describing without opening the full verdict
        out["soak"] = {
            "kills": snap["counters"].get("soak.kills", 0),
            "resumes": snap["counters"].get("soak.resumes", 0),
            "poison_sent": snap["counters"].get("soak.poison_sent", 0),
            "dead_peer_timeouts": snap["counters"].get(
                "soak.dead_peer_timeouts", 0),
            "clock_skews": snap["counters"].get("soak.clock_skews", 0),
        }
    if STATE.last_slo is not None:
        out["slo"] = STATE.last_slo.digest()
    exp = STATE.exporter
    if exp is not None:
        out["export"] = {"flushes": exp.flushes, "dropped": exp.dropped,
                         "write_errors": exp.write_errors}
    windows = snap["counters"].get("pipeline.windows", 0)
    if windows:
        prep = snap["timings"].get("pipeline.prep")
        train = snap["timings"].get("pipeline.train")
        stall = snap["timings"].get("pipeline.stall")
        out["pipeline"] = {
            "windows": windows,
            "rebinds": snap["counters"].get("pipeline.rebinds", 0),
            "overlap_fraction": STATE.registry.gauge(
                "pipeline.overlap_fraction"),
            "prep_p50_s": round(prep["p50_s"], 3) if prep else None,
            "train_p50_s": round(train["p50_s"], 3) if train else None,
            "stall_total_s": round(stall["total_s"], 3) if stall
            else 0.0,
        }
    return out


def dump_metrics(path: Optional[str] = None) -> Optional[str]:
    path = path or STATE.metrics_path
    if not path:
        return None
    with open(path, "w") as fh:
        json.dump(snapshot(), fh, indent=1)
    return path


def dump_trace(path: Optional[str] = None) -> Optional[str]:
    path = path or STATE.trace_path
    if not path:
        return None
    # the buffer is cumulative and each write serializes all of it, so a
    # per-window flush loop skips writes when nothing new was recorded
    key = (path, len(STATE.trace), STATE.trace.dropped)
    if STATE._trace_flushed == key and os.path.exists(path):
        return path
    STATE.trace.to_chrome(path)
    STATE._trace_flushed = key
    return path


def dump_events_jsonl(path: Optional[str] = None) -> Optional[str]:
    path = path or STATE.events_path
    if not path:
        return None
    STATE.trace.to_jsonl(path)
    return path


def flush() -> None:
    """Write every configured output file (idempotent; cheap when no
    paths are configured)."""
    if not STATE.enabled:
        return
    dump_metrics()
    dump_trace()
    dump_events_jsonl()
    if STATE.exporter is not None:
        STATE.exporter.flush_now()


def _atexit_flush() -> None:
    # stop() already performs a final synchronous exporter flush, so
    # only the snapshot files are written here (no duplicated final
    # stream line)
    exp = STATE.exporter
    if exp is not None:
        exp.stop()
    if STATE.enabled:
        dump_metrics()
        dump_trace()
        dump_events_jsonl()


def _register_atexit() -> None:
    if STATE._atexit_registered:
        return
    import atexit
    atexit.register(_atexit_flush)
    STATE._atexit_registered = True


# ---------------------------------------------------------------------------
# TRAIN_TIMER bridge
# ---------------------------------------------------------------------------

def _timer_sink(tag: str, seconds: float) -> None:
    STATE.registry.observe(f"phase.{tag}", seconds)
    r = STATE.rolling
    if r is not None:
        r.observe(f"phase.{tag}", seconds)


def _install_timer_sink() -> None:
    from ..utils import log
    log.set_timer_sink(_timer_sink)


def _remove_timer_sink() -> None:
    from ..utils import log
    log.set_timer_sink(None)


# ---------------------------------------------------------------------------
# engine callback hook (CallbackEnv-compatible)
# ---------------------------------------------------------------------------

def iteration_hooks() -> Tuple:
    """(before, after) callbacks for ``engine.train``'s callback list.

    Both take the standard :class:`~lightgbm_tpu.callback.CallbackEnv`.
    The pair times each boosting iteration end to end (update + eval +
    other callbacks), samples device memory, and emits eval results as
    instant events, so a plain ``train(params, ds)`` call with
    ``metrics_enabled`` produces a full timeline with no user code.
    """
    state = {}

    def _before(env):
        if STATE.enabled:
            state["t0"] = time.perf_counter()
    _before.before_iteration = True
    _before.order = -1000
    # pure telemetry: the fused engine driver may invoke the pair once
    # per chunk instead of once per iteration (engine.train)
    _before.obs_hook = True

    def _after(env):
        t0 = state.pop("t0", None)
        if t0 is None or not STATE.enabled:
            return
        dur = time.perf_counter() - t0
        STATE.registry.observe("engine.iter", dur)
        r = STATE.rolling
        if r is not None:
            r.observe("engine.iter", dur)
        STATE.trace.add("engine_iter", cat="engine", t0=t0, dur=dur,
                        args={"iteration": env.iteration})
        for rec in (env.evaluation_result_list or []):
            instant(f"eval:{rec[0]}:{rec[1]}", cat="eval",
                    iteration=env.iteration, value=float(rec[2]))
        sample_device_memory()
    _after.order = 1000
    _after.obs_hook = True

    return _before, _after


# ---------------------------------------------------------------------------
# env-var activation (no code change needed in embedding hosts)
# ---------------------------------------------------------------------------

def _configure_from_env() -> None:
    metrics = os.environ.get("LGBM_TPU_METRICS", "")
    trace = os.environ.get("LGBM_TPU_TRACE", "")
    events = os.environ.get("LGBM_TPU_EVENTS", "")
    stream = os.environ.get("LGBM_TPU_STREAM", "")
    prom = os.environ.get("LGBM_TPU_PROM", "")
    try:
        http_port = int(os.environ.get("LGBM_TPU_OBS_HTTP", "") or 0)
    except ValueError:
        http_port = 0
    trace_ctx = os.environ.get("LGBM_TPU_TRACE_CTX", "").lower() \
        in ("1", "true", "yes")
    if metrics.lower() in ("0", "false", "no"):
        metrics = ""
    if not (metrics or trace or events or stream or prom or http_port
            or trace_ctx):
        return
    configure(
        enabled=True,
        metrics_path=metrics if metrics.lower() not in ("1", "true", "yes")
        else None,
        trace_path=trace or None,
        events_path=events or None,
        stream_path=stream or None,
        prom_path=prom or None,
        http_port=http_port if http_port > 0 else None,
        sync=os.environ.get("LGBM_TPU_OBS_SYNC", "") in ("1", "true"),
        trace_context=True if trace_ctx else None,
    )


_configure_from_env()
