"""Single-source-of-truth parameter schema.

The reference keeps every parameter as an annotated field of a C++ ``Config``
struct (``include/LightGBM/config.h:27-873``) and generates the alias table,
typed getters and the docs from those doc-comments via
``helper/parameter_generator.py``.  We keep the same "one annotated schema
generates parser + aliases + docs" design: every parameter is a ``Param``
entry in ``PARAM_SCHEMA`` below; ``lightgbm_tpu.config.Config`` consumes the
schema for alias resolution / type coercion / validation, and
``python -m lightgbm_tpu.utils.gen_docs`` renders ``docs/Parameters.md``.

No code is copied from the reference; parameter names, aliases, defaults and
semantics follow the documented public LightGBM v2.2.2 parameter surface so
that user configs written for the reference keep working.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Param:
    name: str
    type: type
    default: Any
    aliases: tuple = ()
    check: Optional[str] = None      # human-readable constraint, e.g. ">= 0.0"
    desc: str = ""
    section: str = "core"

    def coerce(self, value):
        """Coerce a raw (possibly string) value to this param's type."""
        if self.type is bool:
            if isinstance(value, str):
                v = value.strip().lower()
                if v in ("true", "1", "yes", "+"):
                    return True
                if v in ("false", "0", "no", "-"):
                    return False
                raise ValueError(f"cannot parse bool from {value!r} for {self.name}")
            return bool(value)
        if self.type is int:
            if isinstance(value, str):
                return int(float(value.strip()))
            if isinstance(value, float) and value != int(value):
                raise ValueError(f"{self.name} expects an int, got {value}")
            return int(value)
        if self.type is float:
            if isinstance(value, str):
                value = value.strip()
            return float(value)
        if self.type is str:
            return str(value).strip() if isinstance(value, str) else str(value)
        if self.type is list:
            return _coerce_list(value)
        return value


def _coerce_list(value):
    if value is None:
        return []
    if isinstance(value, (list, tuple)):
        return list(value)
    if isinstance(value, str):
        value = value.strip()
        if not value:
            return []
        return [v for v in value.replace(" ", ",").split(",") if v != ""]
    return [value]


def _p(name, type_, default, aliases=(), check=None, desc="", section="core"):
    return Param(name, type_, default, tuple(aliases), check, desc, section)


# ---------------------------------------------------------------------------
# The schema.  Sections mirror the reference's config.h ordering:
# core, learning control, IO, objective, metric, network, device.
# ---------------------------------------------------------------------------

PARAM_SCHEMA: Sequence[Param] = (
    # -- core -------------------------------------------------------------
    _p("config", str, "", ("config_file",),
       desc="path to a key=value config file (CLI)", section="core"),
    _p("task", str, "train", ("task_type",),
       desc="train, predict (prediction), convert_model, refit "
            "(refit_tree), warmup (AOT compile warmup into the "
            "persistent cache, docs/ColdStart.md), pipeline (windowed-"
            "retrain pipeline over the data file, docs/Pipeline.md), "
            "soak (composed fleet chaos soak to an SLO-gated verdict, "
            "docs/Soak.md)",
       section="core"),
    _p("objective", str, "regression",
       ("objective_type", "app", "application"),
       desc="regression, regression_l1, huber, fair, poisson, quantile, mape, "
            "gamma, tweedie, binary, multiclass, multiclassova, cross_entropy, "
            "cross_entropy_lambda, lambdarank",
       section="core"),
    _p("boosting", str, "gbdt", ("boosting_type", "boost"),
       desc="gbdt, rf (random_forest), dart, goss", section="core"),
    _p("data", str, "", ("train", "train_data", "train_data_file", "data_filename"),
       desc="path of training data (CLI)", section="core"),
    _p("valid", list, [], ("test", "valid_data", "valid_data_file",
                           "test_data", "test_data_file", "valid_filenames"),
       desc="paths of validation data, comma separated (CLI)", section="core"),
    _p("num_iterations", int, 100,
       ("num_iteration", "n_iter", "num_tree", "num_trees", "num_round",
        "num_rounds", "num_boost_round", "n_estimators"),
       check=">= 0", desc="number of boosting iterations", section="core"),
    _p("learning_rate", float, 0.1, ("shrinkage_rate", "eta"),
       check="> 0.0", desc="shrinkage rate", section="core"),
    _p("num_leaves", int, 31, ("num_leaf", "max_leaves", "max_leaf"),
       check="> 1", desc="max number of leaves in one tree", section="core"),
    _p("tree_learner", str, "serial",
       ("tree", "tree_type", "tree_learner_type"),
       desc="serial, feature (feature_parallel), data (data_parallel), "
            "voting (voting_parallel)", section="core"),
    _p("num_threads", int, 0, ("num_thread", "nthread", "nthreads", "n_jobs"),
       desc="number of host threads (0 = default)", section="core"),
    _p("device_type", str, "tpu", ("device",),
       desc="device for tree learning: tpu (default here), cpu. The reference's "
            "cpu/gpu map to cpu/tpu in this framework", section="core"),
    _p("seed", int, 0, ("random_seed", "random_state"),
       desc="master seed; deterministically derives data/feature/bagging/drop "
            "seeds like the reference", section="core"),

    # -- learning control -------------------------------------------------
    _p("max_depth", int, -1, (),
       desc="limit tree depth, <= 0 means no limit", section="learning"),
    _p("min_data_in_leaf", int, 20,
       ("min_data_per_leaf", "min_data", "min_child_samples"),
       check=">= 0", desc="minimal number of data in one leaf", section="learning"),
    _p("min_sum_hessian_in_leaf", float, 1e-3,
       ("min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian",
        "min_child_weight"),
       check=">= 0.0", desc="minimal sum of hessians in one leaf", section="learning"),
    _p("bagging_fraction", float, 1.0,
       ("sub_row", "subsample", "bagging"),
       check="0.0 < x <= 1.0", desc="row subsample ratio (without replacement)",
       section="learning"),
    _p("pos_bagging_fraction", float, 1.0,
       ("pos_sub_row", "pos_subsample", "pos_bagging"),
       check="0.0 < x <= 1.0", desc="positive-class bagging fraction (binary)",
       section="learning"),
    _p("neg_bagging_fraction", float, 1.0,
       ("neg_sub_row", "neg_subsample", "neg_bagging"),
       check="0.0 < x <= 1.0", desc="negative-class bagging fraction (binary)",
       section="learning"),
    _p("bagging_freq", int, 0, ("subsample_freq",),
       desc="bagging frequency; 0 disables bagging", section="learning"),
    _p("bagging_seed", int, 3, ("bagging_fraction_seed",),
       desc="bagging random seed", section="learning"),
    _p("feature_fraction", float, 1.0,
       ("sub_feature", "colsample_bytree"),
       check="0.0 < x <= 1.0", desc="feature subsample ratio per tree",
       section="learning"),
    _p("feature_fraction_seed", int, 2, (),
       desc="feature_fraction random seed", section="learning"),
    _p("early_stopping_round", int, 0,
       ("early_stopping_rounds", "early_stopping"),
       desc="stop if one validation metric does not improve in this many rounds",
       section="learning"),
    _p("first_metric_only", bool, False, (),
       desc="only use the first metric for early stopping", section="learning"),
    _p("max_delta_step", float, 0.0, ("max_tree_output", "max_leaf_output"),
       desc="limit the max output of tree leaves, <= 0 means no constraint",
       section="learning"),
    _p("lambda_l1", float, 0.0, ("reg_alpha",), check=">= 0.0",
       desc="L1 regularization", section="learning"),
    _p("lambda_l2", float, 0.0, ("reg_lambda", "lambda"), check=">= 0.0",
       desc="L2 regularization", section="learning"),
    _p("min_gain_to_split", float, 0.0, ("min_split_gain",), check=">= 0.0",
       desc="minimal gain to perform split", section="learning"),
    _p("drop_rate", float, 0.1, ("rate_drop",), check="0.0 <= x <= 1.0",
       desc="dart: dropout rate", section="learning"),
    _p("max_drop", int, 50, (),
       desc="dart: max number of dropped trees per iteration, <=0 no limit",
       section="learning"),
    _p("skip_drop", float, 0.5, (), check="0.0 <= x <= 1.0",
       desc="dart: probability of skipping drop", section="learning"),
    _p("xgboost_dart_mode", bool, False, (),
       desc="dart: use xgboost dart normalization", section="learning"),
    _p("uniform_drop", bool, False, (),
       desc="dart: uniform (vs weighted) drop", section="learning"),
    _p("drop_seed", int, 4, (), desc="dart: drop random seed", section="learning"),
    _p("top_rate", float, 0.2, (), check="0.0 <= x <= 1.0",
       desc="goss: retain ratio of large-gradient data", section="learning"),
    _p("other_rate", float, 0.1, (), check="0.0 <= x <= 1.0",
       desc="goss: sample ratio of small-gradient data", section="learning"),
    _p("min_data_per_group", int, 100, (), check="> 0",
       desc="minimal data per categorical group", section="learning"),
    _p("max_cat_threshold", int, 32, (), check="> 0",
       desc="max number of categories on one side of a categorical split",
       section="learning"),
    _p("cat_l2", float, 10.0, (), check=">= 0.0",
       desc="L2 regularization in categorical split", section="learning"),
    _p("cat_smooth", float, 10.0, (), check=">= 0.0",
       desc="smoothing of categorical bin statistics", section="learning"),
    _p("max_cat_to_onehot", int, 4, (), check="> 0",
       desc="use one-vs-other categorical split when #categories <= this",
       section="learning"),
    _p("top_k", int, 20, ("topk",), check="> 0",
       desc="voting parallel: number of top features voted per worker",
       section="learning"),
    _p("monotone_constraints", list, [],
       ("mc", "monotone_constraint"),
       desc="per-feature monotone constraints: 1 increasing, -1 decreasing, 0 none",
       section="learning"),
    _p("feature_contri", list, [],
       ("feature_contrib", "fc", "fp", "feature_penalty"),
       desc="per-feature split-gain multipliers", section="learning"),
    _p("forcedsplits_filename", str, "",
       ("fs", "forced_splits_filename", "forced_splits_file", "forced_splits"),
       desc="path to a JSON file of forced splits", section="learning"),
    _p("refit_decay_rate", float, 0.9, (), check="0.0 <= x <= 1.0",
       desc="decay rate of leaf values in the refit task and in the "
            "pipeline's refit/warm window policies: new leaf value = "
            "decay * old + (1 - decay) * optimal-on-new-data",
       section="learning"),
    _p("window_policy", str, "fresh", (),
       check="fresh/refit/warm",
       desc="how each retrain window of the windowed pipeline "
            "(lightgbm_tpu.pipeline, docs/Pipeline.md) starts: fresh = "
            "train a new booster from scratch (the reference harness's "
            "behaviour; byte-identical to the serial loop); refit = "
            "keep the previous ensemble's routing structure and re-fit "
            "leaf values against the new labels with refit_decay_rate "
            "(no new trees); warm = refit, then continue boosting "
            "pipeline_warm_iterations new trees on top (tree count "
            "grows per window — pad-boundary crossings re-trace the "
            "serving kernel)", section="learning"),
    _p("pipeline_warm_iterations", int, 0, (), check=">= 0",
       desc="extra boosting iterations per window under "
            "window_policy=warm; 0 = num_iterations", section="learning"),
    _p("verbosity", int, 1, ("verbose",),
       desc="<0 fatal only, 0 error/warning, 1 info, >1 debug", section="io"),

    # -- IO / dataset -----------------------------------------------------
    _p("max_bin", int, 255, (), check="> 1",
       desc="max number of bins for feature values", section="io"),
    _p("min_data_in_bin", int, 3, (), check="> 0",
       desc="minimal number of data inside one bin", section="io"),
    _p("bin_construct_sample_cnt", int, 200000, ("subsample_for_bin",),
       check="> 0", desc="number of sampled rows to construct bins", section="io"),
    _p("histogram_pool_size", float, -1.0, ("hist_pool_size",),
       desc="max cache size in MB for historical histograms; < 0 = no limit",
       section="io"),
    _p("data_random_seed", int, 1, ("data_seed",),
       desc="random seed for sampling data rows for bin construction",
       section="io"),
    _p("output_model", str, "LightGBM_model.txt",
       ("model_output", "model_out"),
       desc="filename of output model (CLI)", section="io"),
    _p("snapshot_freq", int, -1, ("save_period",),
       desc="checkpoint frequency in iterations; <=0 disables", section="io"),
    _p("input_model", str, "", ("model_input", "model_in"),
       desc="filename of input model for continued train / predict", section="io"),
    _p("output_result", str, "LightGBM_predict_result.txt",
       ("predict_result", "prediction_result", "predict_name",
        "prediction_name", "pred_name", "name_pred"),
       desc="filename of prediction result (CLI predict task)", section="io"),
    _p("initscore_filename", str, "",
       ("init_score_filename", "init_score_file", "init_score",
        "input_init_score"),
       desc="path of initial-score file; '' means <data>.init if exists",
       section="io"),
    _p("valid_data_initscores", list, [],
       ("valid_data_init_scores", "valid_init_score_file", "valid_init_score"),
       desc="init-score files of validation data", section="io"),
    _p("pre_partition", bool, False, ("is_pre_partition",),
       desc="distributed: data is already partitioned across machines", section="io"),
    _p("enable_bundle", bool, True, ("is_enable_bundle", "bundle"),
       desc="enable exclusive feature bundling (EFB)", section="io"),
    _p("max_conflict_rate", float, 0.0, (), check="0.0 <= x < 1.0",
       desc="max conflict rate for EFB bundling", section="io"),
    _p("is_enable_sparse", bool, True,
       ("is_sparse", "enable_sparse", "sparse"),
       desc="enable sparse optimization (host-side)", section="io"),
    _p("sparse_threshold", float, 0.8, (), check="0.0 < x <= 1.0",
       desc="zero-ratio threshold treating a feature group as sparse", section="io"),
    _p("use_missing", bool, True, (),
       desc="enable special handling of missing values", section="io"),
    _p("zero_as_missing", bool, False, (),
       desc="treat zero as missing (and unrecorded sparse entries)", section="io"),
    _p("two_round", bool, False,
       ("two_round_loading", "use_two_round_loading"),
       desc="two-pass loading for data bigger than memory", section="io"),
    _p("save_binary", bool, False, ("is_save_binary", "is_save_binary_file"),
       desc="save dataset to binary cache file", section="io"),
    _p("header", bool, False, ("has_header",),
       desc="input data has a header line", section="io"),
    _p("label_column", str, "", ("label",),
       desc="label column: index or name: prefix", section="io"),
    _p("weight_column", str, "", ("weight",),
       desc="weight column: index or name: prefix", section="io"),
    _p("group_column", str, "",
       ("group", "group_id", "query_column", "query", "query_id"),
       desc="query/group id column for ranking", section="io"),
    _p("ignore_column", list, [],
       ("ignore_feature", "blacklist"),
       desc="columns to ignore", section="io"),
    _p("categorical_feature", list, [],
       ("cat_feature", "categorical_column", "cat_column"),
       desc="categorical feature indices or name: list", section="io"),
    _p("predict_raw_score", bool, False,
       ("is_predict_raw_score", "predict_rawscore", "raw_score"),
       desc="predict raw scores only", section="io"),
    _p("predict_leaf_index", bool, False,
       ("is_predict_leaf_index", "leaf_index"),
       desc="predict leaf indices", section="io"),
    _p("predict_contrib", bool, False,
       ("is_predict_contrib", "contrib"),
       desc="predict SHAP feature contributions", section="io"),
    _p("num_iteration_predict", int, -1, (),
       desc="number of iterations used in prediction, <=0 all", section="io"),
    _p("pred_early_stop", bool, False, (),
       desc="use early stopping in prediction", section="io"),
    _p("pred_early_stop_freq", int, 10, (),
       desc="frequency of checking prediction early stopping", section="io"),
    _p("pred_early_stop_margin", float, 10.0, (),
       desc="threshold margin for prediction early stopping", section="io"),
    _p("convert_model_language", str, "", (),
       desc="convert_model target language (cpp supported)", section="io"),
    _p("convert_model", str, "gbdt_prediction.cpp",
       ("convert_model_file",),
       desc="output of convert_model task", section="io"),
    _p("metrics_enabled", bool, False, ("telemetry", "obs_enabled"),
       desc="enable the structured telemetry subsystem (lightgbm_tpu.obs): "
            "metrics registry (per-phase/iteration timing histograms with "
            "p50/p95/max), JIT recompile tracking per shape signature, and "
            "device memory peaks; near-zero overhead when false. "
            "Independent of `verbosity` (which only gates stderr logging). "
            "Env override: LGBM_TPU_METRICS=<path|1>. See "
            "docs/Observability.md", section="io"),
    _p("metrics_path", str, "", ("metrics_file",),
       desc="write the telemetry metrics JSON snapshot to this path at the "
            "end of train() (implies metrics_enabled)", section="io"),
    _p("events_path", str, "", ("events_file",),
       desc="write the trace-event buffer as JSONL (one event per line: "
            "t_unix, name, cat, kind, dur_s, args) to this path at process "
            "exit (implies metrics_enabled). The streaming counterpart of "
            "trace_path for jq/pandas post-processing; the per-window "
            "feature-gain events land here. Env override: "
            "LGBM_TPU_EVENTS=<path.jsonl>. See docs/Observability.md",
       section="io"),
    _p("stream_path", str, "", ("stream_file",),
       desc="append a rolling-window telemetry snapshot line (JSONL time "
            "series: counter rates, gauge means, p50/p95/p99 over the "
            "last window, latest SLO digest) every obs_export_interval "
            "seconds via the background exporter (implies "
            "metrics_enabled; docs/Observability.md \"Streaming & "
            "SLOs\"). Export is bounded-queue + drop-counter: it can "
            "never stall training or serving. Env override: "
            "LGBM_TPU_STREAM=<path.jsonl>", section="io"),
    _p("prom_path", str, "", ("prometheus_path",),
       desc="atomically rewrite a Prometheus text-exposition file at this "
            "path every obs_export_interval seconds (implies "
            "metrics_enabled): counters as _total, gauges, timings as "
            "summaries with rolling-window quantiles. Env override: "
            "LGBM_TPU_PROM=<path>", section="io"),
    _p("obs_export_interval", float, 5.0, (), check="> 0.0",
       desc="seconds between background telemetry exporter flushes "
            "(stream_path / prom_path / the scrape endpoint)",
       section="io"),
    _p("obs_http_port", int, 0, (), check=">= 0",
       desc="opt-in localhost Prometheus scrape endpoint: serve the "
            "text exposition at http://127.0.0.1:<port>/metrics "
            "(implies metrics_enabled). 0 disables (default — the "
            "library never binds a socket unasked). Env override: "
            "LGBM_TPU_OBS_HTTP=<port>", section="io"),
    _p("pipeline_windows", int, 4, (), check="> 0",
       desc="task=pipeline (CLI): number of equal row windows the "
            "training file is replayed as through the windowed-retrain "
            "pipeline (docs/Pipeline.md); each window is scored against "
            "the previously served model (test-then-train), then "
            "retrained per window_policy and hot-swapped into serving",
       section="io"),
    _p("pipeline_rebin", bool, True, (),
       desc="windowed pipeline: allow drift-triggered re-find-bin. "
            "When false, every window is constructed against the first "
            "window's bin mappers unconditionally — program signatures "
            "stay frozen (zero retraces) and, with window_policy=fresh, "
            "the pipelined loop is byte-identical to the serial one",
       section="io"),
    _p("pipeline_drift_threshold", float, 0.1, (), check=">= 0.0",
       desc="windowed pipeline: re-run find-bin when a window's "
            "noise-adjusted bin-occupancy drift (mean per-group total-"
            "variation distance vs the cached mappers' occupancy, minus "
            "the expected sampling noise — docs/Pipeline.md) exceeds "
            "this; a rebind changes program signatures, so expect a "
            "one-off retrace on that window", section="io"),
    _p("trace_path", str, "", ("trace_file",),
       desc="write a Chrome-trace / Perfetto timeline of the run to this "
            "path at the end of train() (implies metrics_enabled). Open at "
            "https://ui.perfetto.dev. Env override: LGBM_TPU_TRACE=<path>",
       section="io"),
    _p("trace_context_enabled", bool, False, ("trace_context",),
       desc="causal trace-context propagation (obs/tracing.py, implies "
            "metrics_enabled): spans gain trace_id/span_id/parent_id and "
            "the ids flow across thread boundaries — pipeline prep "
            "thread -> train -> hot-swap -> the serve requests answered "
            "by that model, micro-batch submit -> worker flush, fleet "
            "replica dispatch, checkpoint -> resume — so one exported "
            "trace shows a request's causal chain back to the training "
            "window that produced its model (docs/Observability.md "
            "\"Tracing & attribution\"). Off: zero context objects are "
            "allocated. Env override: LGBM_TPU_TRACE_CTX=1",
       section="io"),
    _p("profile_attribution", bool, False, (),
       desc="attach XLA cost-analysis estimates (FLOPs / bytes accessed "
            "per compiled program) to the device profiling probes "
            "(profile_stage_plan / profile_phases / profile_psum, implies "
            "metrics_enabled); bench.py --explain turns this on to emit "
            "the phase-attribution report with achieved-GFLOP/s figures",
       section="io"),
    _p("pipeline_checkpoint_dir", str, "", (),
       desc="windowed pipeline: directory for per-window fault-tolerance "
            "checkpoints (docs/Robustness.md). After every completed "
            "window the pipeline atomically persists the trained model, "
            "the bin-mapper cache and a manifest (write-temp-then-"
            "rename; the manifest is the commit point), so a killed run "
            "resumes from the last completed window via "
            "resume_training=true / RetrainPipeline.resume(dir). Empty "
            "disables checkpointing", section="io"),
    _p("resume_training", bool, False, ("resume",),
       desc="resume an interrupted run instead of starting over "
            "(docs/Robustness.md). task=train: adopt the highest "
            "<output_model>.snapshot_iter_N whose .state.npz sidecar "
            "exists and continue boosting from it — byte-identical to "
            "the uninterrupted run because the sidecar restores the "
            "exact float32 training scores. task=pipeline: reload "
            "pipeline_checkpoint_dir's manifest and continue at the "
            "first uncheckpointed window. CLI sugar: --resume. Warns "
            "and trains from scratch when nothing resumable exists",
       section="io"),
    _p("fault_spec", str, "", (),
       desc="deterministic fault injection for chaos testing "
            "(docs/Robustness.md): comma-separated "
            "site[:key=value|persist]* entries armed at the named "
            "sites (grow.dispatch, serve.dispatch, pipeline.prep, "
            "net.connect, io.write, ...), e.g. "
            "'serve.dispatch:persist' or 'pipeline.prep:at=2'. Modes: "
            "n= (first N calls), at= (exact invocation), after=, "
            "p=/seed= (seed-keyed probabilistic, reproducible), "
            "persist; error=fault/oserror/timeout picks the raised "
            "flavor. Env override: LGBM_TPU_FAULTS. NEVER set in "
            "production", section="io"),
    _p("soak_scenario", str, "", (),
       desc="task=soak: path to a JSON SoakScenario file (docs/Soak.md) "
            "overriding the individual soak_* params. Env override: "
            "LGBM_TPU_SOAK=<path-or-inline-JSON> takes precedence over "
            "everything", section="io"),
    _p("soak_tenants", int, 2, (), check=">= 1",
       desc="task=soak: cache nodes in the fleet — one FleetServer "
            "tenant per node, each retrained through its own "
            "RetrainPipeline (docs/Soak.md)", section="io"),
    _p("soak_windows", int, 3, (), check=">= 1",
       desc="task=soak: retrain windows per tenant (a tenant's cadence "
            "subsamples these)", section="io"),
    _p("soak_requests_per_window", int, 4096, (), check=">= 256",
       desc="task=soak: synthetic cache-admission requests per window "
            "(must be >= 2*soak_sample_rows so the labelable-row trim "
            "keeps every window shape-stable)", section="io"),
    _p("soak_sample_rows", int, 1024, (), check=">= 64",
       desc="task=soak: training rows per window after the tail trim "
            "(exact, so same-shape swaps stay zero-retrace)",
       section="io"),
    _p("soak_replicas", int, 1, (), check=">= 1",
       desc="task=soak: fleet serving replicas", section="io"),
    _p("soak_seed", int, 7, (),
       desc="task=soak: the chaos seed — the fault timeline, traces and "
            "sampling all derive from it, so the same seed replays the "
            "same soak byte-for-byte (docs/Soak.md)", section="io"),
    _p("soak_kills", int, 1, (), check=">= 0",
       desc="task=soak: scheduled kill-and-resume points (a retrain "
            "window's ingestion dies mid-window; the driver resumes "
            "from the checkpoint and the verdict gates on byte-"
            "identical reconvergence)", section="io"),
    _p("soak_device_deaths", int, 0, (), check=">= 0",
       desc="task=soak: transient device-death bursts injected on the "
            "serving dispatch path (host fallback + breaker recovery; "
            "dark time is charged to the availability objective)",
       section="io"),
    _p("soak_poison_batches", int, 1, (), check=">= 0",
       desc="task=soak: malformed query micro-batches the fleet must "
            "isolate per-request", section="io"),
    _p("soak_dead_peers", int, 1, (), check=">= 0",
       desc="task=soak: dead-ingest-peer timeouts on the query-load "
            "feed (soak.load site)", section="io"),
    _p("soak_clock_skews", int, 1, (), check=">= 0",
       desc="task=soak: clock faults injected at the driver's SLO "
            "clock stamps (soak.clock site; max 2 — run start and "
            "verdict)", section="io"),
    _p("soak_slo", str, "", (),
       desc="task=soak: SLO spec the verdict evaluates (obs/slo.py "
            "grammar); empty uses the scenario default "
            "'availability>=0.999,p95_ms<=250,burn<=14;"
            "source=serve.fleet;window_s=600'", section="io"),
    _p("soak_checkpoint_dir", str, "", (),
       desc="task=soak: working directory for per-tenant pipeline "
            "checkpoints + the telemetry stream; empty uses a fresh "
            "temp dir", section="io"),
    _p("soak_out", str, "", (),
       desc="task=soak: write the verdict JSON here (SOAK_r*.json "
            "rounds wrap it with the bench round envelope); empty "
            "prints to stdout only", section="io"),

    # -- objective --------------------------------------------------------
    _p("num_class", int, 1, ("num_classes",), check="> 0",
       desc="number of classes for multiclass objectives", section="objective"),
    _p("is_unbalance", bool, False, ("unbalance", "unbalanced_sets"),
       desc="binary: auto-reweight unbalanced labels", section="objective"),
    _p("scale_pos_weight", float, 1.0, (), check="> 0.0",
       desc="binary: weight of positive labels", section="objective"),
    _p("sigmoid", float, 1.0, (), check="> 0.0",
       desc="sigmoid steepness for binary/lambdarank", section="objective"),
    _p("boost_from_average", bool, True, (),
       desc="start from the average label instead of 0", section="objective"),
    _p("reg_sqrt", bool, False, (),
       desc="regression on sqrt(label) (undone at prediction)", section="objective"),
    _p("alpha", float, 0.9, (), check="> 0.0",
       desc="parameter of huber/quantile loss", section="objective"),
    _p("fair_c", float, 1.0, (), check="> 0.0",
       desc="parameter of fair loss", section="objective"),
    _p("poisson_max_delta_step", float, 0.7, (), check="> 0.0",
       desc="parameter of poisson hessian safeguard", section="objective"),
    _p("tweedie_variance_power", float, 1.5, (), check="1.0 <= x < 2.0",
       desc="tweedie variance power", section="objective"),
    _p("max_position", int, 20, (), check="> 0",
       desc="lambdarank NDCG optimization position cutoff", section="objective"),
    _p("label_gain", list, [], (),
       desc="lambdarank gain per label level, default 2^l - 1", section="objective"),

    # -- metric -----------------------------------------------------------
    _p("metric", list, [],
       ("metrics", "metric_types"),
       desc="metric names, '' uses objective default, 'None' disables",
       section="metric"),
    _p("metric_freq", int, 1, ("output_freq",), check="> 0",
       desc="metric output frequency", section="metric"),
    _p("is_provide_training_metric", bool, False,
       ("training_metric", "is_training_metric", "train_metric"),
       desc="output metrics on training data", section="metric"),
    _p("eval_at", list, [1, 2, 3, 4, 5],
       ("ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at"),
       desc="evaluation positions for NDCG/MAP", section="metric"),

    # -- network ----------------------------------------------------------
    _p("num_machines", int, 1, ("num_machine",), check="> 0",
       desc="number of workers in the mesh axis (distributed)", section="network"),
    _p("local_listen_port", int, 12400, ("local_port",),
       desc="accepted for reference compatibility; unused on TPU (ICI mesh)",
       section="network"),
    _p("time_out", int, 120, (), desc="socket timeout in minutes (compat; unused)",
       section="network"),
    _p("machine_list_filename", str, "",
       ("machine_list_file", "machine_list", "mlist"),
       desc="machine list file (compat; unused on TPU)", section="network"),
    _p("machines", str, "", ("workers", "nodes"),
       desc="machine list (compat; unused on TPU)", section="network"),
    _p("network_timeout", float, 30.0, (), check="> 0.0",
       desc="per-operation socket timeout in SECONDS for the host-level "
            "point-to-point helpers (parallel/network.py connect/send/"
            "recv and the jax.distributed coordinator probe): a dead "
            "peer fails the operation with context instead of blocking "
            "the worker mesh forever. Distinct from the reference's "
            "time_out (minutes; kept for config compatibility, unused)",
       section="network"),
    _p("network_retries", int, 5, (), check="> 0",
       desc="max connect attempts (first try included) for the "
            "point-to-point helpers, with capped exponential backoff "
            "between attempts; exhausting them raises 'peer unreachable "
            "after N attempts' instead of hanging", section="network"),
    _p("coordinator_address", str, "", (),
       desc="host:port of the jax.distributed coordinator for "
            "data_sharding=multi_controller (docs/Sharding.md): rank 0 "
            "hosts it, every rank dials it during bring-up. Empty = "
            "read the LGBM_TPU_COORDINATOR env var (launchers usually "
            "set the env triple instead of editing per-host configs). "
            "All three of coordinator_address/num_hosts/host_rank must "
            "resolve or bring-up fails fast", section="network"),
    _p("num_hosts", int, 0, (), check=">= 0",
       desc="process count of the multi_controller pod slice (one "
            "process per host). 0 = read LGBM_TPU_NUM_HOSTS. Bring-up "
            "verifies jax.process_count() matches and fails fast on "
            "mismatch", section="network"),
    _p("host_rank", int, -1, (), check=">= -1",
       desc="this process's rank in [0, num_hosts) for "
            "multi_controller; rank 0 hosts the coordinator, runs "
            "streaming round 1 (count + reservoir + find-bin), "
            "broadcasts the BinMapper reference, and owns the pod "
            "checkpoint manifest. -1 = read LGBM_TPU_HOST_RANK",
       section="network"),

    # -- device -----------------------------------------------------------
    _p("gpu_platform_id", int, -1, (), desc="compat; ignored", section="device"),
    _p("gpu_device_id", int, -1, (), desc="compat; ignored", section="device"),
    _p("gpu_use_dp", bool, False, (),
       desc="use float64 histogram accumulation on device (maps the reference's "
            "gpu_use_dp); default float32", section="device"),
    _p("tpu_double_precision", bool, False, (),
       desc="alias-level switch for float64 accumulation on TPU", section="device"),
    _p("tpu_rows_per_block", int, 0, (),
       desc="rows per Pallas histogram grid block; 0 = auto", section="device"),
    _p("hist_kernel", str, "auto", (),
       check="auto/pallas/einsum/interpret",
       desc="wave-histogram implementation for the device grower: "
            "einsum = XLA one-hot matmul (default; fastest measured for "
            "bf16), pallas = VMEM-resident Pallas TPU kernel "
            "(ops/hist_pallas.py; serves full-width waves whose stat "
            "columns fit one 128-lane tile, bf16 or int8 — the int8 "
            "variant accumulates int8->int32 on the MXU and is "
            "byte-identical to the int8 einsum), interpret = Pallas "
            "interpreter mode (CPU testing/CI parity), auto = einsum. "
            "Routing per dispatch is recorded as grow.hist.* counters",
       section="device"),
    _p("grad_quant_bits", int, 0, ("gradient_quant_bits", "quant_bits"),
       check=">= 0",
       desc="int8-quantized gradient histograms for the device grower: "
            "0 (default) = full-precision bf16 hi/lo wave histograms; 8 = "
            "stochastically round grad/hess to int8 against a per-tree "
            "global scale so the wave contraction runs on the MXU's native "
            "int8->int32 path. Below ~16.9M rows (ops/grow."
            "INT32_SCAN_ROWS) the histograms stay INTEGER end-to-end "
            "through the find-best prefix-sum scan — counts, default-bin "
            "reconstruction and histogram subtraction are exact — and are "
            "dequantized only at gain/leaf-value math; larger datasets "
            "dequantize once in f32 before the scan. Leaf values are "
            "refit from full-precision gradients after growth either way "
            "(Shi et al., Quantized Training of GBDT, NeurIPS 2022). "
            "Ignored with gpu_use_dp. Only 0 and 8 are accepted",
       section="device"),
    _p("wave_plan", str, "auto", (),
       check="auto/fixed/profiled",
       desc="wave-stage plan for the device grower (ops/stage_plan.py): "
            "fixed = the byte-stable doubling plan; profiled = time every "
            "candidate stage width on the real binned matrix at init, fit "
            "the fixed-vs-per-column wave cost model and install the "
            "cheapest plan; auto = adopt a plan already cached for this "
            "(shape, config) signature (in process or persisted beside "
            "the compile cache), else profile ON FIRST USE at production "
            "scale (>= 2^19 training rows AND a persistent compile cache "
            "active, so the verdict persists — probe timings are noisy, "
            "and an unpersistable plan would let same-config processes "
            "grow different trees) and install the derived plan only "
            "when it beats the byte-stable ladder by the 2% bar. "
            "Profiled plans persist to <compile_cache_dir>/stage_plans "
            "so retrain windows AND fresh processes measure once "
            "(zero re-profiles; docs/ColdStart.md)",
       section="device"),
    _p("find_best_fusion", str, "auto", (),
       check="auto/fused/two_pass",
       desc="find-best placement inside the device grower's wave "
            "(ops/grow.py): fused = the wave's histogram contraction "
            "feeds the per-feature gain scan in ONE traced program per "
            "wave — the fresh and subtracted sibling histogram stacks "
            "are scanned in place and only the packed winner records "
            "plus the parent-minus-sibling residuals survive the wave, "
            "never a concatenated (2*wave, slots, stats) tensor "
            "round-tripping through HBM; two_pass = the legacy layout "
            "(histograms materialize, then a second scan pass reduces "
            "them); auto = fused, unless wave_plan=profiled measured "
            "two-pass faster for this (shape, config) and persisted "
            "that verdict beside the stage plan. Both paths are "
            "byte-identical in every guaranteed regime (f32, int8 "
            "einsum, int8 Pallas, striped columns, sharded "
            "single-controller); the mode joins programs_signature so "
            "switching retraces instead of reusing a stale program. "
            "Per-wave dispatch equivalents are recorded as "
            "grow.fused_find.* counters and the "
            "grow.wave_dispatch_factor gauge", section="device"),
    _p("grower_cache", bool, True, (),
       desc="share the device grower's jitted programs process-wide, "
            "keyed on (shape signature, config hash): a warm retrain "
            "window re-dispatches into already-traced programs (zero new "
            "traces; obs counters grow.cache_hits/grow.cache_misses). "
            "Disable only to debug trace-level issues", section="device"),
    _p("device_growth", str, "auto", ("tpu_device_growth",),
       check="auto/on/off",
       desc="fully on-device wave-synchronized tree growth (one dispatch "
            "per boosting iteration, no per-split host sync). auto = on "
            "for TPU backends when the config is eligible (serial learner, "
            "no monotone constraints/forced splits/renew-tree objectives); "
            "off = always use the host-driven learner",
       section="device"),
    _p("device_predict", str, "auto", ("tpu_device_predict",),
       check="auto/force/off",
       desc="routing for batch prediction (GBDT.predict_raw): auto = "
            "the packed-forest device kernel (serve/packed.py: whole "
            "ensemble flattened into padded device arrays, one jitted "
            "dispatch per batch, works for file-loaded models) when the "
            "batch has at least device_predict_min_rows rows, host tree "
            "walk below; force = always the device kernel; off = always "
            "the host walk. Row-wise pred_early_stop always takes the "
            "host path. Leaf routing is bit-identical between the two; "
            "accumulated values differ ~1e-6 relative (float32 device "
            "accumulation, docs/Serving.md)", section="device"),
    _p("device_predict_min_rows", int, 65536, (),
       check=">= 0",
       desc="batch size at which device_predict=auto switches from the "
            "host tree walk to the packed-forest device kernel: below "
            "it the host walk wins on latency (no transfer, no "
            "dispatch), above it the single fused device dispatch wins "
            "on throughput. Tune per deployment; the PredictionServer "
            "(lightgbm_tpu.serve) always uses the device kernel",
       section="device"),
    _p("serve_replicas", int, 1, (), check=">= 0",
       desc="device replicas for multi-tenant fleet serving "
            "(lightgbm_tpu.serve.FleetServer / LGBM_FleetCreate): the "
            "packed fleet arrays are copied onto this many local "
            "devices and request micro-batch queues round-robin across "
            "them, each replica degrading to the host tree walk "
            "independently through its own circuit breaker "
            "(docs/Serving.md). 0 = one replica per local device; 1 "
            "(default) = single-device serving", section="device"),
    _p("fleet_value_dtype", str, "f32", (),
       check="f32/bf16",
       desc="leaf-value storage dtype of the packed model fleet "
            "(lightgbm_tpu.serve.FleetServer): f32 (default) serves "
            "byte-identical to each tenant's solo PackedEnsemble; bf16 "
            "halves the leaf-table bytes for inference throughput — "
            "leaf ROUTING stays exact (the hi/lo threshold compare is "
            "untouched), only the accumulated VALUES quantize to ~3 "
            "decimal digits, mirroring the training-side int8 contract "
            "(routing exact, values quantize; docs/Serving.md)",
       section="device"),
    _p("train_row_bucketing", bool, True, ("row_bucketing",),
       desc="pad the training row count to a pow2 bucket (ops/histogram."
            "bucket_size, min 1024 — the same ladder the bagging buffer "
            "and the serving path already use) before the device "
            "grower's program-cache signature, so ONE compiled program "
            "family covers a whole traffic range of retrain-window sizes "
            "instead of one program per exact row count (the real row "
            "count travels as a traced scalar; padded rows carry zero "
            "gradient/hessian/count, exactly like the chunk pad). Trees "
            "are byte-identical to the unbucketed path. Auto-disabled "
            "with grad_quant_bits=8 (the stochastic rounding stream is "
            "keyed on the padded shape), for objectives whose fused "
            "device gradient is not row-local (lambdarank), and when "
            "the pow2 bucket would cross the striped-count bound "
            "(datasets over 2^24 rows fall back to exact rows, logged). "
            "See docs/ColdStart.md", section="device"),
    _p("data_sharding", str, "off", (),
       check="off/single_controller/multi_controller",
       desc="data-parallel training for the device grower "
            "(docs/Sharding.md): single_controller row-shards the "
            "binned matrix and every per-row buffer across a local "
            "device mesh with shard_map from ONE process, runs the "
            "fused K-trees-per-dispatch scan on all chips, and "
            "psum-reduces the wave histograms over the mesh axis as "
            "the growth loop's sole cross-device sync — find-best runs "
            "replicated on the global histograms, so every device "
            "grows the identical tree. Under grad_quant_bits=8's int32 "
            "scan, models are BYTE-identical to the single-device "
            "fused path; f32 histograms are bit-reproducible "
            "run-to-run. Falls back (logged) to unsharded training "
            "with fewer than 2 devices. multi_controller extends the "
            "same program to a pod slice: N processes (one per host) "
            "initialize jax.distributed against coordinator_address/"
            "num_hosts/host_rank, build ONE global mesh, and run the "
            "identical fused scan — program signatures are "
            "mesh-invariant, so a pod run is byte-identical to "
            "single_controller under the int32 quant scan; bring-up "
            "failures RAISE (a host silently falling back would wedge "
            "the slice on the psum). off (default) = unsharded; the "
            "multiprocess tree_learner=data/feature/voting mesh remains "
            "the socket-level fallback", section="device"),
    _p("shard_devices", int, 0, (), check=">= 0",
       desc="device count for data_sharding=single_controller: the "
            "first N local devices form the one-axis mesh; 0 (default) "
            "= all local devices", section="device"),
    _p("compile_cache_dir", str, "", ("xla_cache_dir",),
       desc="directory for JAX's persistent XLA compilation cache "
            "(lightgbm_tpu.compile_cache): compiled executables are "
            "written to an on-disk LRU store so a FRESH process training "
            "the same (bucketed shape, config) pays zero XLA recompiles "
            "— the cross-process completion of the in-process "
            "grower_cache. Empty = use the LGBM_TPU_COMPILE_CACHE env "
            "var if set, else no persistent cache. Precompile a "
            "deployment's declared shapes with the warmup entry points "
            "(task=warmup / LGBM_WarmupTrain). See docs/ColdStart.md",
       section="device"),
    _p("compile_cache_min_entry_bytes", int, 0, (),
       check=">= 0",
       desc="skip persisting compiled executables smaller than this "
            "many bytes (0 = persist everything, the default: the "
            "warm-cold-start contract and the CI zero-miss smoke need "
            "even sub-second glue ops cached). Raise it when a "
            "deployment wants a lean cache dir at the cost of a few "
            "small recompiles", section="device"),
    _p("compile_cache_strict_keys", bool, False, (),
       desc="sharing-safety knob for a compile cache dir mounted across "
            "heterogeneous hosts: include compiler/runtime build "
            "metadata in the cache key, so an executable compiled by a "
            "different jaxlib/XLA build is never reused (a guaranteed "
            "miss instead of trusting serialized-executable "
            "compatibility). Leave off for identical builds — strict "
            "keys make every software update a full cold start",
       section="device"),
    _p("warmup_rows", list, [], (),
       desc="task=warmup (CLI) / lightgbm_tpu.warmup: comma-separated "
            "training row counts to precompile grower programs for "
            "(each is padded to its pow2 bucket under "
            "train_row_bucketing, so one entry covers the whole "
            "bucket's window-size range)", section="device"),
    _p("warmup_features", int, 0, (),
       check=">= 0",
       desc="task=warmup: feature count of the declared training/"
            "serving shape (ignored when a data= file is given — the "
            "file's binned shape is used instead)", section="device"),
    _p("warmup_serve_rows", list, [], (),
       desc="task=warmup: serving batch-row buckets to precompile the "
            "packed-forest traversal for; unset = skip the serving "
            "warmup; a 0 entry = the PredictionServer warmup defaults "
            "(128/1024/8192 plus the device_predict_min_rows bucket)",
       section="device"),
    _p("fused_chunk", int, 20, (),
       check=">= 0",
       desc="boosting iterations fused into ONE device dispatch by the "
            "multi-iteration training path (GBDT.train_chunked): gradients, "
            "bagging/feature_fraction draws and tree growth run inside a "
            "single lax.scan. Drivers (engine.train, the CLI, the C API's "
            "UpdateChunked) cap each dispatch at the next callback/eval/"
            "snapshot boundary so observable cadence is unchanged; <= 1 "
            "disables fusing", section="device"),
    _p("dispatch_retries", int, 2, (), check=">= 0",
       desc="bounded retries (with short backoff) around a device "
            "dispatch that raises a TRANSIENT runtime error (the JAX "
            "runtime error type, OSError/TimeoutError, and injected "
            "faults) before the failure propagates — a preempted or "
            "briefly wedged accelerator gets dispatch_retries more "
            "chances; deterministic programs re-dispatch identically "
            "so a retry never changes results. 0 disables",
       section="device"),
    _p("deterministic", bool, True, (),
       desc="bit-deterministic device reductions where possible", section="device"),
)


PARAM_BY_NAME = {p.name: p for p in PARAM_SCHEMA}

# alias -> canonical name (includes the canonical names themselves)
PARAM_ALIASES = {}
for _param in PARAM_SCHEMA:
    PARAM_ALIASES[_param.name] = _param.name
    for _a in _param.aliases:
        # first writer wins, like the reference alias table
        PARAM_ALIASES.setdefault(_a, _param.name)

# objective aliases resolved at value level (Config.set handles these)
OBJECTIVE_ALIASES = {
    "regression": "regression",
    "regression_l2": "regression",
    "l2": "regression",
    "mean_squared_error": "regression",
    "mse": "regression",
    "l2_root": "regression",
    "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1",
    "l1": "regression_l1",
    "mean_absolute_error": "regression_l1",
    "mae": "regression_l1",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "quantile": "quantile",
    "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass",
    "softmax": "multiclass",
    "multiclassova": "multiclassova",
    "multiclass_ova": "multiclassova",
    "ova": "multiclassova",
    "ovr": "multiclassova",
    "cross_entropy": "cross_entropy",
    "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "none": "none",
    "null": "none",
    "custom": "none",
    "na": "none",
}

METRIC_ALIASES = {
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression_l2": "l2",
    "regression": "l2",
    "l2_root": "rmse", "root_mean_squared_error": "rmse", "rmse": "rmse",
    "quantile": "quantile",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "gamma": "gamma",
    "gamma_deviance": "gamma_deviance", "gamma-deviance": "gamma_deviance",
    "tweedie": "tweedie",
    "ndcg": "ndcg", "lambdarank": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "auc": "auc",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kldiv", "kldiv": "kldiv",
    "": "", "none": "none", "null": "none", "na": "none", "custom": "none",
}

BOOSTING_ALIASES = {
    "gbdt": "gbdt", "gbrt": "gbdt",
    "dart": "dart",
    "goss": "goss",
    "rf": "rf", "random_forest": "rf",
}

TREE_LEARNER_ALIASES = {
    "serial": "serial",
    "feature": "feature", "feature_parallel": "feature",
    "data": "data", "data_parallel": "data",
    "voting": "voting", "voting_parallel": "voting",
}
