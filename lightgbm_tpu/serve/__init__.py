"""Packed-ensemble device inference + hot-swap prediction serving.

``packed``: flatten a tree slice into one set of padded device arrays
and route any batch through the whole ensemble in a single jitted
dispatch (no binning, no ``train_set`` — file-loaded models serve the
same as freshly trained ones).  ``engine``: a thread-safe
:class:`~.engine.PredictionServer` with shape-bucketed batch padding,
optional micro-batching, warmup precompiles and atomic model
``swap()`` for the retrain-every-window loop.  See docs/Serving.md.
"""

from .engine import PredictionServer  # noqa: F401
from .fleet import (FleetServer, PackedFleet, TenantHandle,  # noqa: F401
                    fleet_predict_leaves, fleet_predict_scores,
                    pack_fleet)
from .packed import (PackedEnsemble, pack_ensemble, pack_gbdt,  # noqa: F401
                     predict_leaves, predict_scores, row_bucket)

__all__ = ["PredictionServer", "PackedEnsemble", "pack_ensemble",
           "pack_gbdt", "predict_leaves", "predict_scores", "row_bucket",
           "FleetServer", "PackedFleet", "TenantHandle", "pack_fleet",
           "fleet_predict_scores", "fleet_predict_leaves"]
