"""FIL-style packed-forest inference: the whole ensemble as flat arrays.

The training-side device paths (``ops/traverse.py``) walk ONE tree per
dispatch over the BINNED matrix and need the live ``train_set`` for the
bin mappers — fine for validation-score updates, useless for serving:
the LRB cache-admission loop (PAPER.md) predicts on every arriving
request against a model that may have been loaded from a file.  This
module packs an arbitrary tree slice into padded device arrays keyed on
RAW feature values, so one jitted ``lax.scan`` over the padded depth
routes every (row, tree) pair in a single dispatch — the standard
packed-forest layout of GPU inference engines (RAPIDS FIL, Treelite).

Raw-threshold precision: thresholds are float64 on host but TPUs run
x64-disabled, so each threshold is stored as a **hi/lo float32 pair**
(``hi = f32(t)``, ``lo = f32(t - hi)``) and query values are split the
same way on host.  The lexicographic compare ``(vhi, vlo) <= (thi,
tlo)`` reproduces the float64 ``v <= t`` decision to ~2^-49 relative
precision — leaf routing is bit-identical to the host walk unless a
query value sits within ~1e-14 relative distance of a threshold
(``tests/test_serve.py`` pins routing parity).  Remaining caveats, by
construction: |threshold| below the f32-subnormal floor (~1e-44) or
above f32-overflow (~3e38) lose exactness, and leaf-value ACCUMULATION
is float32 on device vs float64 on host (values agree to ~1e-6
relative; routing is unaffected).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..data.binning import K_ZERO_THRESHOLD
from ..tree.tree import (K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK, Tree,
                         _structural_depth)
from ..utils.log import LightGBMError


def _pow2_at_least(n: int, lo: int = 1) -> int:
    p = max(int(lo), 1)
    while p < n:
        p <<= 1
    return p


def row_bucket(n: int, lo: int = 128) -> int:
    """Pow2 row bucket a batch pads to: bounds the number of distinct
    jit signatures (hence compiles) to log2(max batch) per ensemble
    shape."""
    return _pow2_at_least(n, lo)


def _depth_pad(d: int) -> int:
    """Depth pads to a pow2 (min 8) so the per-window depth jitter of
    leaf-wise growth (the same config routinely lands anywhere in a
    range of a few levels) does not re-trace the scan; only crossing a
    pow2 boundary changes the pad."""
    return _pow2_at_least(int(d), 8) if d > 0 else 0


def split_hi_lo(arr64: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Split float64 into (hi, lo) float32 on host.  Non-finite hi
    (NaN from NaN input, +-inf from f32 overflow) takes lo = 0 — the
    hi part alone decides those comparisons."""
    with np.errstate(invalid="ignore", over="ignore"):
        # |t| >= ~3.4e38 overflows to +-inf by design: the hi part alone
        # decides those comparisons (serialized thresholds cap at 1e300,
        # the reference's AvoidInf clamp)
        hi = np.asarray(arr64, np.float64).astype(np.float32)
        lo = np.where(np.isfinite(hi), np.asarray(arr64, np.float64)
                      - hi.astype(np.float64), 0.0).astype(np.float32)
    return hi, lo


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class PackedEnsemble:
    """An ensemble slice as padded device arrays (a jax pytree).

    Array layout — T = padded tree count (= padded iterations x
    num_model, iteration-major like ``GBDT.models``), N = padded
    internal-node count, L = N + 1 leaves, W = padded categorical
    bitset words:

    ================  ===========  =========================================
    field             shape/dtype  contents
    ================  ===========  =========================================
    split_feature     (T,N) i32    raw feature index per node
    threshold_hi/lo   (T,N) f32    float64 threshold as a hi/lo f32 pair
    decision_type     (T,N) i32    bit0 cat, bit1 default_left, bits2-3
                                   missing type (reference encoding)
    left/right_child  (T,N) i32    child node; negative = ~leaf
    cat_start/len     (T,N) i32    slice of ``cat_words`` per cat node
    cat_words         (W,)  u32    all trees' raw-category bitsets, packed
    leaf_value        (T,L) f32    shrinkage-applied leaf outputs
    is_stump          (T,)  bool   single-leaf trees (and tree padding)
    ================  ===========  =========================================

    The static aux (``num_model``, ``max_depth``, ``num_trees``,
    ``num_features``) rides in the pytree treedef, so two packs with
    equal pads AND equal aux hit the same jit cache entry — that is the
    hot-swap zero-retrace contract.
    """

    split_feature: jnp.ndarray
    threshold_hi: jnp.ndarray
    threshold_lo: jnp.ndarray
    decision_type: jnp.ndarray
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    cat_start: jnp.ndarray
    cat_len: jnp.ndarray
    cat_words: jnp.ndarray
    leaf_value: jnp.ndarray
    is_stump: jnp.ndarray
    num_model: int = 1
    max_depth: int = 0
    num_trees: int = 0          # real (unpadded) tree count
    num_features: int = 1       # columns a query matrix must provide

    _ARRAY_FIELDS = ("split_feature", "threshold_hi", "threshold_lo",
                     "decision_type", "left_child", "right_child",
                     "cat_start", "cat_len", "cat_words", "leaf_value",
                     "is_stump")

    def tree_flatten(self):
        children = tuple(getattr(self, f) for f in self._ARRAY_FIELDS)
        aux = (self.num_model, self.max_depth, self.num_trees,
               self.num_features)
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    @property
    def num_iterations(self) -> int:
        return self.num_trees // max(self.num_model, 1)

    def shape_signature(self) -> tuple:
        """Hashable pad signature: equal signatures guarantee a model
        swap re-dispatches into already-compiled programs."""
        return (self.split_feature.shape, self.leaf_value.shape,
                self.cat_words.shape, self.num_model, self.max_depth,
                self.num_features)


def tree_slice(models: List[Tree], num_model: int,
               start_iteration: int = 0,
               num_iteration: int = -1) -> List[Tree]:
    """The SERVED tree slice ``models[start*K : end*K]`` (K =
    ``num_model``) with the clamping every consumer must agree on —
    shared by :func:`pack_ensemble` and the PredictionServer's
    host-fallback trees, so the degrade path can never answer from a
    different slice than the device kernel."""
    k = max(int(num_model), 1)
    total_iter = len(models) // k
    start = max(0, min(int(start_iteration), total_iter))
    end = total_iter if num_iteration <= 0 \
        else min(start + int(num_iteration), total_iter)
    return models[start * k:end * k]


def pack_ensemble(models: List[Tree], num_model: int,
                  start_iteration: int = 0, num_iteration: int = -1,
                  num_features: Optional[int] = None) -> PackedEnsemble:
    """Flatten ``models[start*K : end*K]`` (K = ``num_model``) into a
    :class:`PackedEnsemble`.  Works from the host ``Tree`` objects
    alone — no dataset, no bin mappers — so file-loaded Boosters pack
    the same as freshly trained ones."""
    k = max(int(num_model), 1)
    trees = tree_slice(models, num_model, start_iteration, num_iteration)
    n_iter = len(trees) // k

    i_pad = _pow2_at_least(max(n_iter, 1))
    t_pad = i_pad * k
    max_nodes = max([t.num_leaves - 1 for t in trees] or [0])
    n_pad = _pow2_at_least(max(max_nodes, 1))
    l_pad = n_pad + 1
    depth = max([_structural_depth(t) for t in trees] or [0])
    d_pad = _depth_pad(depth)

    sf = np.zeros((t_pad, n_pad), np.int32)
    thi = np.zeros((t_pad, n_pad), np.float32)
    tlo = np.zeros((t_pad, n_pad), np.float32)
    dt = np.zeros((t_pad, n_pad), np.int32)
    lc = np.full((t_pad, n_pad), -1, np.int32)
    rc = np.full((t_pad, n_pad), -1, np.int32)
    cstart = np.zeros((t_pad, n_pad), np.int32)
    clen = np.zeros((t_pad, n_pad), np.int32)
    lv = np.zeros((t_pad, l_pad), np.float32)
    stump = np.ones(t_pad, bool)
    words: List[int] = []
    max_split_f = -1

    for ti, tree in enumerate(trees):
        n = tree.num_leaves - 1
        if n <= 0:
            # real stump: only leaf 0's value (bias) contributes
            lv[ti, 0] = np.float32(tree.leaf_value[0])
            continue
        stump[ti] = False
        sf[ti, :n] = tree.split_feature[:n]
        if n > 0:
            max_split_f = max(max_split_f,
                              int(tree.split_feature[:n].max()))
        h, lo = split_hi_lo(tree.threshold[:n])
        thi[ti, :n] = h
        tlo[ti, :n] = lo
        dt[ti, :n] = tree.decision_type[:n].astype(np.int32)
        lc[ti, :n] = tree.left_child[:n]
        rc[ti, :n] = tree.right_child[:n]
        lv[ti, :tree.num_leaves] = \
            tree.leaf_value[:tree.num_leaves].astype(np.float32)
        if tree.num_cat > 0:
            for node in range(n):
                if not (int(tree.decision_type[node])
                        & K_CATEGORICAL_MASK):
                    continue
                cat_idx = int(tree.threshold[node])
                wlo = tree.cat_boundaries[cat_idx]
                whi = tree.cat_boundaries[cat_idx + 1]
                cstart[ti, node] = len(words)
                clen[ti, node] = whi - wlo
                words.extend(int(w) for w in tree.cat_threshold[wlo:whi])

    w_pad = _pow2_at_least(max(len(words), 1))
    cat_words = np.zeros(w_pad, np.uint32)
    if words:
        cat_words[:len(words)] = np.asarray(words, np.uint32)

    nf = int(num_features) if num_features else max(max_split_f + 1, 1)
    if nf <= max_split_f:
        raise LightGBMError(
            f"num_features={nf} is smaller than the ensemble's highest "
            f"split feature index {max_split_f}")
    as_j = jnp.asarray
    return PackedEnsemble(
        as_j(sf), as_j(thi), as_j(tlo), as_j(dt), as_j(lc), as_j(rc),
        as_j(cstart), as_j(clen), as_j(cat_words), as_j(lv),
        as_j(stump), num_model=k, max_depth=d_pad,
        num_trees=len(trees), num_features=nf)


def pack_gbdt(gbdt, start_iteration: int = 0,
              num_iteration: int = -1) -> PackedEnsemble:
    """Pack a :class:`~lightgbm_tpu.boosting.gbdt.GBDT` (trained OR
    loaded from file: only ``models``/``num_model``/``max_feature_idx``
    are read)."""
    gbdt._flush_pending()
    return pack_ensemble(gbdt.models, gbdt.num_model,
                         start_iteration=start_iteration,
                         num_iteration=num_iteration,
                         num_features=gbdt.max_feature_idx + 1)


# ---------------------------------------------------------------------------
# jitted traversal: one dispatch for the whole (rows x trees) lattice
# ---------------------------------------------------------------------------

_K_ZERO = np.float32(K_ZERO_THRESHOLD)
# |value| clamp before the int32 categorical cast (2e9 < 2^31; any real
# category index that large is out of every bitset's range anyway)
_CAT_CLIP = np.float32(2.0e9)


def route_left(dt, thi, tlo, cat_len, fetch_word, vhi, vlo):
    """goes-left from per-(row, tree) GATHERED node tables — the one
    implementation of the reference decision semantics (missing modes,
    zero threshold, hi/lo lexicographic compare, categorical bitsets),
    shared by the solo kernel below and the fleet kernel
    (``serve/fleet.py``) so the two can never route differently.
    ``fetch_word(widx)`` gathers the categorical bitset word at an
    already-clipped in-range word index."""
    is_cat = (dt & K_CATEGORICAL_MASK) != 0
    default_left = (dt & K_DEFAULT_LEFT_MASK) != 0
    missing = (dt >> 2) & 3
    nan_v = jnp.isnan(vhi)
    zhi = jnp.where(nan_v & (missing != 2), jnp.float32(0), vhi)
    zlo = jnp.where(nan_v, jnp.float32(0), vlo)
    is_miss = ((missing == 1) & (jnp.abs(zhi) <= _K_ZERO)) \
        | ((missing == 2) & nan_v)
    le = (zhi < thi) | ((zhi == thi) & (zlo <= tlo))
    left_num = jnp.where(is_miss, default_left, le)

    # categorical: iv = trunc-toward-zero int of the raw value (exact
    # via the hi/lo pair: when hi is integral the lo sign says whether
    # the true value sits just below/above it), -1 for NaN with NaN
    # missing-handling, 0 for NaN otherwise
    zc = jnp.clip(zhi, -_CAT_CLIP, _CAT_CLIP)
    iv0 = zc.astype(jnp.int32)
    integral = zc == iv0.astype(jnp.float32)
    iv = iv0 \
        - (integral & (zc > 0) & (zlo < 0)).astype(jnp.int32) \
        + (integral & (zc < 0) & (zlo > 0)).astype(jnp.int32)
    iv = jnp.where(nan_v, jnp.where(missing == 2, -1, 0), iv)
    widx = iv >> 5
    in_range = (iv >= 0) & (widx < cat_len)
    word = fetch_word(jnp.where(in_range, widx, 0))
    bit = ((word >> (iv & 31).astype(jnp.uint32)) & 1) == 1
    left_cat = in_range & bit
    return jnp.where(is_cat, left_cat, left_num)


def _decide(pe: PackedEnsemble, cur, vhi, vlo):
    """goes-left per (row, tree) — mirrors ``Tree._decision_matrix``
    (missing modes, zero threshold, categorical bitsets) over the
    packed layout.  ``cur`` is the (R, T) node index, ``vhi``/``vlo``
    the gathered hi/lo query values."""
    t_ix = jnp.arange(cur.shape[1], dtype=jnp.int32)[None, :]
    return route_left(
        pe.decision_type[t_ix, cur],
        pe.threshold_hi[t_ix, cur], pe.threshold_lo[t_ix, cur],
        pe.cat_len[t_ix, cur],
        lambda widx: pe.cat_words[pe.cat_start[t_ix, cur] + widx],
        vhi, vlo)


def _traverse(pe: PackedEnsemble, xhi, xlo):
    """(R, T) leaf index per (row, tree) via ``lax.scan`` over the
    padded depth; rows and trees advance in lockstep, finished pairs
    (negative node = ~leaf) stay put."""
    r, t = xhi.shape[0], pe.split_feature.shape[0]
    t_ix = jnp.arange(t, dtype=jnp.int32)[None, :]
    r_ix = jnp.arange(r, dtype=jnp.int32)[:, None]
    node0 = jnp.broadcast_to(
        jnp.where(pe.is_stump[None, :], -1, 0), (r, t)).astype(jnp.int32)

    def body(node, _):
        act = node >= 0
        cur = jnp.maximum(node, 0)
        sf = pe.split_feature[t_ix, cur]
        left = _decide(pe, cur, xhi[r_ix, sf], xlo[r_ix, sf])
        nxt = jnp.where(left, pe.left_child[t_ix, cur],
                        pe.right_child[t_ix, cur])
        return jnp.where(act, nxt, node), None

    node, _ = jax.lax.scan(body, node0, None, length=pe.max_depth)
    return ~node


@jax.jit
def _apply_scores(pe: PackedEnsemble, xhi, xlo):
    """(K, R) float32 raw scores: traverse + leaf-value gather + per-
    class sum, one fused program."""
    r, t = xhi.shape[0], pe.split_feature.shape[0]
    leaves = _traverse(pe, xhi, xlo)
    vals = pe.leaf_value[jnp.arange(t, dtype=jnp.int32)[None, :], leaves]
    per_class = vals.reshape(r, t // pe.num_model, pe.num_model)
    return per_class.sum(axis=1).T


@jax.jit
def _apply_leaves(pe: PackedEnsemble, xhi, xlo):
    """(R, T) int32 leaf index per (row, tree) — padding trees
    included; callers slice to ``pe.num_trees``."""
    return _traverse(pe, xhi, xlo)


_apply_scores = obs.track_jit("serve.scores", _apply_scores)
_apply_leaves = obs.track_jit("serve.leaves", _apply_leaves)


def _prepare_rows(pe: PackedEnsemble, data: np.ndarray, pad_rows: int):
    """Validate + hi/lo-split + row-pad a raw query matrix on host."""
    data = np.asarray(data, np.float64)
    if data.ndim != 2:
        raise LightGBMError("query data must be 2-dimensional")
    if data.shape[1] < pe.num_features:
        raise LightGBMError(
            f"query data has {data.shape[1]} features but the packed "
            f"ensemble needs {pe.num_features}")
    if data.shape[1] > pe.num_features:
        # trailing unused columns would otherwise change the jit
        # signature (and pay hi/lo split + transfer for dead data)
        data = data[:, :pe.num_features]
    data = np.ascontiguousarray(data)
    xhi, xlo = split_hi_lo(data)
    n = data.shape[0]
    if pad_rows > n:
        pad = ((0, pad_rows - n), (0, 0))
        xhi = np.pad(xhi, pad)
        xlo = np.pad(xlo, pad)
    return jnp.asarray(xhi), jnp.asarray(xlo), n


def predict_scores(pe: PackedEnsemble, data: np.ndarray,
                   bucket_rows: bool = True,
                   min_bucket: int = 128) -> np.ndarray:
    """Raw scores (num_model, rows) float64 for a raw query matrix —
    ONE device dispatch regardless of tree count or batch size.  Rows
    pad to a pow2 bucket (>= ``min_bucket``) by default so varying
    batch sizes reuse a bounded set of compiled programs."""
    n = int(np.asarray(data).shape[0])
    if n == 0 or pe.num_trees == 0:
        return np.zeros((pe.num_model, n), np.float64)
    pad = row_bucket(n, min_bucket) if bucket_rows else n
    xhi, xlo, n = _prepare_rows(pe, data, pad)
    obs.inc("serve.device_batches")
    out = _apply_scores(pe, xhi, xlo)
    return np.asarray(out, np.float64)[:, :n]


def predict_leaves(pe: PackedEnsemble, data: np.ndarray,
                   bucket_rows: bool = True,
                   min_bucket: int = 128) -> np.ndarray:
    """Leaf index (rows, num_trees) int32 — the packed analog of
    stacking ``Tree.predict_leaf`` per tree."""
    n = int(np.asarray(data).shape[0])
    if n == 0 or pe.num_trees == 0:
        return np.zeros((n, pe.num_trees), np.int32)
    pad = row_bucket(n, min_bucket) if bucket_rows else n
    xhi, xlo, n = _prepare_rows(pe, data, pad)
    obs.inc("serve.device_batches")
    out = _apply_leaves(pe, xhi, xlo)
    return np.asarray(out, np.int32)[:n, :pe.num_trees]
